"""mx.rnn symbolic cell API (reference: python/mxnet/rnn/rnn_cell.py).

The v1.x pre-Gluon recurrent API: cells compose SYMBOLS (weight
variables are auto-shared via RNNParams), `unroll` builds the
time-unrolled graph that BucketingModule compiles per bucket, and
FusedRNNCell wraps the fused RNN op (cuDNN role → ops/rnn.py lax.scan).

Deviation (documented): `begin_state()` needs `batch_size` when called
standalone — the reference's shape-0 placeholder trick rides nnvm's
partial shape inference, which the jax.eval_shape-based inference here
does not model.  `unroll(begin_state=None)` needs no batch size: the
initial state is composed from the input symbol itself.
"""
from __future__ import annotations

from typing import List, Optional

from ..base import MXNetError
from .. import symbol as sym

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams:
    """Container sharing weight Symbols between steps (reference:
    rnn_cell.py class RNNParams)."""

    def __init__(self, prefix: str = ""):
        self._prefix = prefix
        self._params = {}

    def get(self, name: str, **kwargs) -> "sym.Symbol":
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell (reference: class BaseRNNCell)."""

    def __init__(self, prefix: str = "", params: Optional[RNNParams] = None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self) -> RNNParams:
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def __call__(self, inputs, states):
        raise NotImplementedError

    def begin_state(self, func=None, batch_size: int = 0, **kwargs):
        """Initial-state symbols.  With batch_size > 0 these are concrete
        zeros; without it, unroll() composes the zeros from the inputs."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if batch_size <= 0:
                raise MXNetError(
                    "begin_state: pass batch_size=N (the reference's "
                    "shape-0 placeholder needs nnvm partial inference; "
                    "unroll(begin_state=None) avoids the need entirely)")
            shape = (batch_size,) + tuple(info["shape"][1:])
            if func is None:
                states.append(sym._zeros(
                    shape=shape,
                    name="%sbegin_state_%d" % (self._prefix,
                                               self._init_counter)))
            else:
                states.append(func(
                    name="%sbegin_state_%d" % (self._prefix,
                                               self._init_counter),
                    shape=shape, **kwargs))
        return states

    def _zeros_from(self, x_step, n_units, name):
        """(N, n_units) zeros composed from an input symbol (batch size
        stays symbolic — no placeholder shapes needed)."""
        col = sym.slice_axis(x_step, axis=-1, begin=0, end=1)
        z = sym._zeros(shape=(1, n_units), name=name + "_zconst")
        return sym.broadcast_add(sym._mul_scalar(col, scalar=0.0), z)

    def _default_states(self, x_step):
        states = []
        for i, info in enumerate(self.state_info):
            states.append(self._zeros_from(
                x_step, info["shape"][-1],
                "%sbegin_state_%d" % (self._prefix, i)))
        return states

    def unpack_weights(self, args):
        """Fused blob → per-gate matrices; base cells store unfused
        already (reference contract: dict passthrough)."""
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._default_states(inputs[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """(reference: rnn_cell.py _normalize_sequence) list ⇄ merged tensor."""
    axis = layout.find("T")
    if isinstance(inputs, sym.Symbol):
        if merge is False:
            sliced = sym.split(inputs, num_outputs=length, axis=axis,
                               squeeze_axis=True)
            inputs = list(sliced) if length > 1 else [sliced]
    else:
        inputs = list(inputs)
        if merge is True:
            inputs = [sym.expand_dims(x, axis=axis) for x in inputs]
            inputs = sym.concat(*inputs, dim=axis)
    return inputs, axis


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (reference: class RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW,
                                 bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name="%sh2h" % name)
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gate order i f g o like the fused op (reference:
    class LSTMCell; gate order matches ops/rnn.py _cell_step)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias
        self._iB = self.params.get(
            "i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW,
                                 bias=self._iB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%sh2h" % name)
        gates = i2h + h2h
        slices = list(sym.SliceChannel(gates, num_outputs=4, axis=1,
                                       name="%sslice" % name))
        in_gate = sym.Activation(slices[0], act_type="sigmoid")
        forget_gate = sym.Activation(slices[1], act_type="sigmoid")
        in_transform = sym.Activation(slices[2], act_type="tanh")
        out_gate = sym.Activation(slices[3], act_type="sigmoid")
        next_c = sym.broadcast_add(
            sym.broadcast_mul(forget_gate, states[1]),
            sym.broadcast_mul(in_gate, in_transform))
        next_h = sym.broadcast_mul(
            out_gate, sym.Activation(next_c, act_type="tanh"))
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, gate order r z n (reference: class GRUCell; cuDNN
    formulation matching ops/rnn.py)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = sym.FullyConnected(data=inputs, weight=self._iW,
                                 bias=self._iB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=prev_h, weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%sh2h" % name)
        i2h_r, i2h_z, i2h_n = list(sym.SliceChannel(
            i2h, num_outputs=3, axis=1, name="%si2h_slice" % name))
        h2h_r, h2h_z, h2h_n = list(sym.SliceChannel(
            h2h, num_outputs=3, axis=1, name="%sh2h_slice" % name))
        reset = sym.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = sym.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = sym.Activation(
            i2h_n + sym.broadcast_mul(reset, h2h_n), act_type="tanh")
        ones = sym._rminus_scalar(update, scalar=1.0)
        next_h = sym.broadcast_add(
            sym.broadcast_mul(ones, next_h_tmp),
            sym.broadcast_mul(update, prev_h))
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer cell over the RNN op (reference: class
    FusedRNNCell — the cuDNN path; here ops/rnn.py's lax.scan kernel)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        # forget_bias applies when the blob is initialized with
        # mx.init.FusedRNN (the reference contract); a default here would
        # shadow the user's global initializer
        self._forget_bias = forget_bias
        self._param = self.params.get("parameters")

    @property
    def state_info(self):
        dirs = 2 if self._bidirectional else 1
        info = [{"shape": (self._num_layers * dirs, 0, self._num_hidden),
                 "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append(dict(info[0]))
        return info

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    def _state_like(self, x_tnc, name):
        """(L*dirs, N, H) zeros composed from (T, N, C) inputs."""
        dirs = 2 if self._bidirectional else 1
        step = sym.slice_axis(x_tnc, axis=0, begin=0, end=1)     # (1,N,C)
        col = sym.slice_axis(step, axis=-1, begin=0, end=1)      # (1,N,1)
        z = sym._zeros(shape=(self._num_layers * dirs, 1,
                             self._num_hidden), name=name + "_zconst")
        return sym.broadcast_add(sym._mul_scalar(col, scalar=0.0), z)

    # -- fused blob <-> per-gate matrices (reference: FusedRNNCell
    # unpack_weights/pack_weights over the cuDNN parameter layout) ---------
    def _blob_geometry(self, total):
        """(G, H, dirs, input_size) from the flat blob length."""
        G = len(self._gate_names)
        H = self._num_hidden
        dirs = 2 if self._bidirectional else 1
        L = self._num_layers
        bias_total = L * dirs * 2 * G * H
        w_rest = sum(dirs * (G * H * (H * dirs) + G * H * H)
                     for _ in range(L - 1))
        w0_h2h = dirs * G * H * H
        rem = total - bias_total - w_rest - w0_h2h
        assert rem % (dirs * G * H) == 0, \
            "fused blob length %d inconsistent with cell geometry" % total
        return G, H, dirs, rem // (dirs * G * H)

    def _param_names_ordered(self, G, dirs):
        """(weight names, bias names) in the cuDNN layout order the flat
        blob packs them (layer-major; i2h before h2h; gates split)."""
        wnames, bnames = [], []
        for layer in range(self._num_layers):
            for d in range(dirs):
                p = "%s%s%d_" % (self._prefix, "lr"[d], layer)
                for kind in ("i2h", "h2h"):
                    wnames.append([("%s%s%s_weight" % (p, kind, g))
                                   for g in self._gate_names])
        for layer in range(self._num_layers):
            for d in range(dirs):
                p = "%s%s%d_" % (self._prefix, "lr"[d], layer)
                for kind in ("i2h", "h2h"):
                    bnames.append([("%s%s%s_bias" % (p, kind, g))
                                   for g in self._gate_names])
        return wnames, bnames

    def unpack_weights(self, args):
        """Fused blob -> per-gate i2h/h2h matrices (reference naming:
        ``{prefix}{l|r}{layer}_{i2h|h2h}{gate}_weight/bias``)."""
        args = dict(args)
        pname = self._prefix + "parameters"
        if pname not in args:
            return args
        from .. import ndarray as nd
        blob = args.pop(pname)
        flat = blob.asnumpy().ravel()
        G, H, dirs, I = self._blob_geometry(flat.size)
        wnames, bnames = self._param_names_ordered(G, dirs)
        ofs = 0
        wi = 0
        for layer in range(self._num_layers):
            isz = I if layer == 0 else H * dirs
            for _d in range(dirs):
                for kind_sz in (isz, H):
                    mat = flat[ofs:ofs + G * H * kind_sz].reshape(
                        G * H, kind_sz)
                    ofs += G * H * kind_sz
                    for g, name in enumerate(wnames[wi]):
                        args[name] = nd.array(mat[g * H:(g + 1) * H])
                    wi += 1
        for names in bnames:
            vec = flat[ofs:ofs + G * H]
            ofs += G * H
            for g, name in enumerate(names):
                args[name] = nd.array(vec[g * H:(g + 1) * H])
        return args

    def pack_weights(self, args):
        """Per-gate matrices -> fused blob (inverse of unpack_weights)."""
        args = dict(args)
        G = len(self._gate_names)
        dirs = 2 if self._bidirectional else 1
        wnames, bnames = self._param_names_ordered(G, dirs)
        if not all(n in args for group in wnames + bnames for n in group):
            return args          # nothing (or only partial) to pack
        import numpy as _np
        from .. import ndarray as nd
        parts = []
        for group in wnames:
            parts.append(_np.concatenate(
                [args.pop(n).asnumpy() for n in group], axis=0).ravel())
        for group in bnames:
            parts.append(_np.concatenate(
                [args.pop(n).asnumpy() for n in group], axis=0).ravel())
        args[self._prefix + "parameters"] = nd.array(
            _np.concatenate(parts))
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        # fused op wants (T, N, C)
        if isinstance(inputs, sym.Symbol):
            x = inputs if layout == "TNC" else \
                sym.swapaxes(inputs, dim1=0, dim2=1)
        else:
            xs = [sym.expand_dims(i, axis=0) for i in inputs]
            x = sym.concat(*xs, dim=0)
        if begin_state is None:
            states = [self._state_like(x, "%sbegin_state_%d"
                                       % (self._prefix, i))
                      for i in range(len(self.state_info))]
        else:
            states = list(begin_state)
        kwargs = {}
        if self._mode == "lstm":
            kwargs["state_cell"] = states[1]
        rnn = sym.RNN(data=x, parameters=self._param, state=states[0],
                      state_size=self._num_hidden,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=self._get_next_state,
                      name="%srnn" % self._prefix, **kwargs)
        heads = list(rnn)
        outputs = heads[0]
        if layout == "NTC":
            outputs = sym.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            sliced = sym.split(outputs, num_outputs=length,
                               axis=layout.find("T"), squeeze_axis=True)
            outputs = list(sliced) if length > 1 else [sliced]
        if self._get_next_state:
            next_states = heads[1:3] if self._mode == "lstm" \
                else heads[1:2]
        else:
            next_states = []
        return outputs, next_states

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped; use unroll, or unfuse()")

    def unfuse(self) -> "SequentialRNNCell":
        """Stacked unfused cells matching this cell's geometry (weights
        are NOT shared — reference unfuse() + unpack_weights covers
        conversion; here conversion goes through the .params blob)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i))))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stacked cells (reference: class SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, **kwargs):
        return sum((c.begin_state(**kwargs) for c in self._cells), [])

    def _default_states(self, x_step):
        return sum((c._default_states(x_step) for c in self._cells), [])

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Layer-major: each cell unrolls the WHOLE sequence (reference
        SequentialRNNCell.unroll) — required for Bidirectional children,
        and it keeps per-layer graphs compact."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, None)
        num_cells = len(self._cells)
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = None if begin_state is None \
                else begin_state[p:p + n]
            p += n
            inputs, st = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(st)
        return inputs, next_states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward over the sequence (reference: class
    BidirectionalCell); only unroll is defined."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params)
        self._l = l_cell
        self._r = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l.state_info + self._r.state_info

    def _default_states(self, x_step):
        return (self._l._default_states(x_step)
                + self._r._default_states(x_step))

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._default_states(inputs[0])
        nl = len(self._l.state_info)
        l_out, l_states = self._l.unroll(
            length, inputs, begin_state[:nl], layout=layout,
            merge_outputs=False)
        r_out, r_states = self._r.unroll(
            length, list(reversed(inputs)), begin_state[nl:],
            layout=layout, merge_outputs=False)
        outs = []
        for i in range(length):
            outs.append(sym.concat(l_out[i], r_out[length - 1 - i],
                                   dim=1,
                                   name="%st%d" % (self._output_prefix,
                                                   i)))
        outs, _ = _normalize_sequence(length, outs, layout, merge_outputs)
        return outs, l_states + r_states


class DropoutCell(BaseRNNCell):
    """Dropout on outputs between stacked cells (reference: class
    DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def _default_states(self, x_step):
        return []

    def __call__(self, inputs, states):
        self._counter += 1
        if self._dropout > 0:
            inputs = sym.Dropout(data=inputs, p=self._dropout,
                                 name="%st%d" % (self._prefix,
                                                 self._counter))
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base of cells that wrap another cell (reference: class
    ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def _default_states(self, x_step):
        return self.base_cell._default_states(x_step)

    def begin_state(self, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout on states (reference: class ZoneoutCell; the stochastic
    path rides the Dropout op's mask)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev = None

    def reset(self):
        super().reset()
        self._prev = None

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)

        def mix(p, new, old):
            if p <= 0 or old is None:
                return new
            # Dropout(ones) is 0 or 1/(1-p): rescale to an exact {0,1}
            # mask so kept units get NEW (not the reference-diverging
            # 2*new-old extrapolation)
            mask = sym._mul_scalar(
                sym.Dropout(data=sym._mul_scalar(new, scalar=0.0) + 1.0,
                            p=p), scalar=1.0 - p)
            keep = sym.broadcast_mul(mask, new - old)
            return old + keep
        next_states = [mix(self._zs, n, o)
                       for n, o in zip(next_states, states)]
        # first timestep: the reference zones the output against a zeros
        # prev_output (mask * new), not an unmasked pass-through
        prev = self._prev
        if prev is None and self._zo > 0:
            prev = sym._mul_scalar(out, scalar=0.0)
        out = mix(self._zo, out, prev)
        self._prev = out
        return out, next_states


class ResidualCell(ModifierCell):
    """Output = base(x) + x (reference: class ResidualCell)."""

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        return sym.broadcast_add(out, inputs), next_states

