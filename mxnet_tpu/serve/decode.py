"""Autoregressive decode serving: device-resident KV cache + continuous
batching (ISSUE 15 tentpole).

Until this module every served request was one fixed-shape forward; the
sequence-generation traffic that dominates real serving (one prompt in,
many tokens out) would have held its whole micro-batch hostage for the
longest generation.  This is the decode engine that opens it, built on
the same disciplines the rest of ``serve/`` runs on:

* **Split prefill / decode, both AOT-bucketed.**  Prefill (process the
  whole prompt, fill the KV pages, emit the first token) compiles one
  program per PROMPT-LENGTH bucket (``MX_SERVE_DECODE_PROMPT_BUCKETS``);
  decode (one token for every active sequence) compiles one program per
  ACTIVE-SLOT-COUNT bucket (powers of two up to
  ``MX_SERVE_DECODE_SLOTS``).  Both register through
  ``programs.register_program`` so the compile cache, census and
  zero-retrace accounting carry over unchanged — after
  :meth:`DecodeServable.warm` serve time is pure cached-executable
  dispatch.

* **Device-resident KV pool, donated every step.**  K/V pages for every
  slot live in two fixed arrays ``(layers, slots+1, max_len, heads,
  head_dim)`` (+1 = the scratch slot padded decode lanes park on),
  owner-tagged ``kv_cache`` in ``programs.buffer_census()`` and donated
  through every prefill/decode dispatch — the pool is allocated once
  and HBM stays flat across any number of generations.  Retiring a
  sequence "evicts" its pages by bookkeeping alone: the slot's length
  resets on reuse and stale entries beyond it are masked, never read.

* **Continuous batching.**  The decode pump packs ALL active sequences
  into the smallest covering slot bucket each step (ONE device dispatch
  regardless of the active count), and at step boundaries retires
  finished sequences and admits queued prefills into the freed slots —
  a long generation never blocks a short one.  Sampled tokens stay
  device-resident between steps (the program writes the next input
  token into a donated pool-shaped array), so the pump never syncs the
  host; a separate harvester thread reads each step's emitted tokens
  asynchronously, stamps per-token latency and flags EOS/limit
  completions for the next boundary.  ``mode="request"`` is the
  request-level strawman (admit a batch, run it to completion) the
  bench lane compares against.

Slot state machine (one slot)::

    FREE --admit/prefill--> ACTIVE --harvest flags done--> FINISHED
      ^                                                       |
      +------------- retire at step boundary (kv_evict) ------+

Concurrency/lint contract: ``DecodeBatcher._tick`` / ``_admit`` /
``_retire`` / ``_step`` / ``_dispatch_prefill`` and the
``DecodeServable`` dispatch path are mxlint hot-path roots — no host
sync may land between state dequeue and device dispatch (the
tests/test_mxlint.py reinjection test proves a blocking host read there
trips the rule).  The device→host token read lives ONLY in the
harvester thread (``_harvest_once``).  Result/stream wait budgets ride
``mxnet_tpu.fault.Deadline`` (virtual-time aware, like the
micro-batcher's coalescing window); the pump's idle wait is a plain
short condition poll.

Telemetry: ``prefill`` / ``decode_step`` / ``kv_evict`` phases land in
``step_phase_seconds``; ``serve.decode.token_seconds`` histograms
per-token latency (first token = submit→harvest incl. queue + prefill,
then inter-token gaps); counters ``serve.decode.requests`` / ``tokens``
/ ``steps`` / ``prefills`` / ``sequences`` / ``rejected`` and the
``serve.decode.occupancy`` active-slots histogram drive the bench lane
and the fleet plane.

**The paged engine (ISSUE 18).**  :class:`PagedDecodeServable` /
:class:`PagedDecodeBatcher` rebuild the KV store as a shared PAGE HEAP
``(L, kv_pages, kv_page_len, H, Dh)`` (owner ``kv_pages`` in the
census, donated every dispatch) addressed through per-session block
tables, so admission is bounded by FREE PAGES, not slots — a mix of
2-token and 10k-token sessions packs tightly into the same bytes the
flat pool spends on worst-case extents.  Full read-only prompt pages
are hash-shared across sessions (rolling content hash chained at page
boundaries, refcounted adoption, copy-on-write at divergence:
``mxnet_tpu/serve/paging.py``), and prompts prefill as page-aligned
CHUNK trains that interleave with decode steps inside the pump's
1-dispatch-per-tick cadence.  Greedy decode stays token-identical to
the flat engine and :func:`reference_generate` — sharing and chunking
change WHEN work happens, never what it computes.  Select with
``MX_SERVE_KV_PAGES`` > 0 (``python -m mxnet_tpu.serve --decode``);
knobs: ``MX_SERVE_KV_PAGE_LEN``, ``MX_SERVE_PREFIX_SHARE``,
``MX_SERVE_PREFILL_CHUNK``.
"""
from __future__ import annotations

import functools
import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import telemetry as _telemetry
from ..ops.attention import (attention_core, cached_attention,
                             paged_attention, paged_attention_multi)
from .batcher import Overloaded, result_timeout as _result_timeout
from .paging import PageAllocator, page_hashes

__all__ = ["DecodeConfig", "DecodeServable", "DecodeBatcher",
           "PagedDecodeServable", "PagedDecodeBatcher",
           "DraftDecodeServable", "SpeculativeDecodeBatcher",
           "demo_lm_params", "demo_spec_pair", "reference_generate"]

# extra pool positions past prompt+generation capacity: the pump may
# run a few steps ahead of the harvester (bounded by the harvest queue)
# before a finished sequence is retired, and those overrun writes must
# still land inside the slot's pages
_OVERRUN_MARGIN = 8


class DecodeConfig:
    """Decode-engine geometry: model dims + pool/bucket layout.

    Slot buckets are the powers of two up to ``slots`` (plus ``slots``
    itself) — every active-set size packs into the smallest covering
    bucket, so the decode program table is closed over 1..slots.
    ``max_len`` is the per-slot page capacity: top prompt bucket +
    ``max_tokens`` + the pipeline overrun margin, rounded up to whole
    ``page``-sized pages.
    """

    def __init__(self, vocab: int = 48, dim: int = 32, heads: int = 4,
                 layers: int = 2, slots: Optional[int] = None,
                 max_tokens: Optional[int] = None,
                 page: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, seed: int = 7,
                 kv_pages: Optional[int] = None,
                 kv_page_len: Optional[int] = None,
                 prefix_share: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 spec_k: Optional[int] = None):
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.heads = int(heads)
        if self.dim % self.heads:
            raise MXNetError("decode: dim %d must divide by heads %d"
                             % (self.dim, self.heads))
        self.head_dim = self.dim // self.heads
        self.layers = int(layers)
        self.slots = int(slots if slots is not None else
                         get_env("MX_SERVE_DECODE_SLOTS", 8, int))
        if self.slots < 1:
            raise MXNetError("decode: need >= 1 slot")
        self.max_tokens = int(max_tokens if max_tokens is not None else
                              get_env("MX_SERVE_DECODE_MAX_TOKENS", 32,
                                      int))
        self.page = int(page if page is not None else
                        get_env("MX_SERVE_DECODE_PAGE", 16, int))
        if prompt_buckets is None:
            raw = get_env("MX_SERVE_DECODE_PROMPT_BUCKETS") or "4,8,16"
            prompt_buckets = [int(p) for p in str(raw).split(",")
                              if p.strip()]
        self.prompt_buckets: Tuple[int, ...] = \
            tuple(sorted({int(b) for b in prompt_buckets}))
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise MXNetError("decode: prompt buckets must be positive, "
                             "got %r" % (prompt_buckets,))
        sizes = set()
        b = 1
        while b < self.slots:
            sizes.add(b)
            b *= 2
        sizes.add(self.slots)
        self.slot_buckets: Tuple[int, ...] = tuple(sorted(sizes))
        self.eos_id = None if eos_id is None else int(eos_id)
        need = self.prompt_buckets[-1] + self.max_tokens + _OVERRUN_MARGIN
        self.pages = -(-need // self.page)
        self.max_len = self.pages * self.page
        self.seed = int(seed)
        # -- paged pool geometry (ISSUE 18) ---------------------------------
        # the paged engine swaps per-slot flat extents for one shared
        # page heap; a session holds only the pages its actual
        # prompt+generation extent needs, so admission is bounded by
        # free pages, not slots
        self.kv_page_len = int(
            kv_page_len if kv_page_len is not None else
            get_env("MX_SERVE_KV_PAGE_LEN", 0, int) or self.page)
        if self.kv_page_len < 1:
            raise MXNetError("decode: MX_SERVE_KV_PAGE_LEN must be "
                             ">= 1, got %d" % self.kv_page_len)
        self.pages_per_slot = -(-need // self.kv_page_len)
        self.slot_extent = self.pages_per_slot * self.kv_page_len
        n_pages = int(kv_pages if kv_pages is not None else
                      get_env("MX_SERVE_KV_PAGES", 0, int))
        if n_pages <= 0:
            # auto: the same HBM the flat pool's (slots+1) extents take
            n_pages = (self.slots + 1) * self.pages_per_slot
        # floor: the scratch page plus one worst-case session
        self.kv_pages = max(n_pages, self.pages_per_slot + 1)
        share = (prefix_share if prefix_share is not None else
                 get_env("MX_SERVE_PREFIX_SHARE", 1, int))
        self.prefix_share = bool(int(share))
        chunk = int(prefill_chunk if prefill_chunk is not None else
                    get_env("MX_SERVE_PREFILL_CHUNK", 0, int))
        if chunk <= 0:
            chunk = self.kv_page_len
        # chunks are page-aligned by construction: round up
        self.prefill_chunk = \
            -(-chunk // self.kv_page_len) * self.kv_page_len
        # -- speculative window width (ISSUE 20) ----------------------------
        # the verify program writes positions len..len+k into the slot's
        # pages before acceptance truncates back, so k may never exceed
        # the overrun margin the pool geometry already reserves
        k = int(spec_k if spec_k is not None else
                get_env("MX_SERVE_SPEC_K", 4, int))
        self.spec_k = max(1, min(k, _OVERRUN_MARGIN))

    def prompt_bucket_for(self, n: int) -> Optional[int]:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return None

    def slot_bucket_for(self, n: int) -> int:
        for b in self.slot_buckets:
            if b >= n:
                return b
        return self.slot_buckets[-1]

    def __repr__(self):
        return ("DecodeConfig(vocab=%d, dim=%d, heads=%d, layers=%d, "
                "slots=%d, max_tokens=%d, page=%d, max_len=%d)"
                % (self.vocab, self.dim, self.heads, self.layers,
                   self.slots, self.max_tokens, self.page, self.max_len))


def demo_lm_params(config: Optional[DecodeConfig] = None
                   ) -> Dict[str, jnp.ndarray]:
    """Seeded deterministic demo LM parameters (the decode analogue of
    ``serve.demo.demo_block``): both sides of a chaos run build these
    independently, so generated-token *correctness* is assertable
    across processes.  The unembedding is scaled up so greedy-argmax
    margins are decisive — bucket packing must not flip a token on a
    float whisker."""
    cfg = config or DecodeConfig()
    rs = _np.random.RandomState(cfg.seed)
    d = cfg.dim

    def mat(rows, cols, scale):
        return jnp.asarray(rs.randn(rows, cols).astype(_np.float32)
                           * scale)

    params: Dict[str, jnp.ndarray] = {
        "emb": mat(cfg.vocab, d, 1.0),
        "unemb": mat(d, cfg.vocab, 4.0 / (d ** 0.5)),
    }
    for l in range(cfg.layers):
        for name in ("wq", "wk", "wv", "wo"):
            params["l%d.%s" % (l, name)] = mat(d, d, 1.0 / (d ** 0.5))
        params["l%d.w1" % l] = mat(d, 2 * d, 1.0 / (d ** 0.5))
        params["l%d.w2" % l] = mat(2 * d, d, 1.0 / ((2 * d) ** 0.5))
    return params


def demo_spec_pair(config: DecodeConfig, draft_layers: int = 1,
                   residual_eps: float = 1e-4):
    """A draft-friendly (target, draft) parameter pair for speculative
    decoding (ISSUE 20).

    The target is ``config.layers`` deep, but every layer past
    ``draft_layers`` has its residual write-back matrices (``wo`` /
    ``w2``) scaled by ``residual_eps`` — those layers still run at full
    cost, yet perturb the residual stream by ~eps, so the target's
    greedy argmax (decisive margins: the demo unembedding is scaled x4)
    almost always equals what the first ``draft_layers`` layers alone
    predict.  The draft is exactly that shallow prefix, sharing the
    embedding/unembedding tables, so acceptance runs near 100% while
    the draft costs ``draft_layers / layers`` of a target step — the
    regime speculative decoding pays off in.

    Returns ``(target_params, draft_config, draft_params)``; the draft
    config shares every pool/bucket dimension with ``config`` (slot ids
    and lengths line up 1:1) but is only ``draft_layers`` deep.
    """
    cfg = config
    draft_layers = max(1, min(int(draft_layers), cfg.layers))
    target = demo_lm_params(cfg)
    for l in range(draft_layers, cfg.layers):
        target["l%d.wo" % l] = target["l%d.wo" % l] * residual_eps
        target["l%d.w2" % l] = target["l%d.w2" % l] * residual_eps
    draft_cfg = DecodeConfig(
        vocab=cfg.vocab, dim=cfg.dim, heads=cfg.heads,
        layers=draft_layers, slots=cfg.slots,
        max_tokens=cfg.max_tokens, page=cfg.page,
        prompt_buckets=cfg.prompt_buckets, eos_id=cfg.eos_id,
        seed=cfg.seed, kv_pages=cfg.kv_pages,
        kv_page_len=cfg.kv_page_len, prefix_share=cfg.prefix_share,
        prefill_chunk=cfg.prefill_chunk, spec_k=cfg.spec_k)
    draft = {"emb": target["emb"], "unemb": target["unemb"]}
    for l in range(draft_layers):
        for name in ("wq", "wk", "wv", "wo", "w1", "w2"):
            key = "l%d.%s" % (l, name)
            draft[key] = target[key]
    return target, draft_cfg, draft


# ---------------------------------------------------------------------------
# traced program bodies (pure; jit-purity applies via register_program)
# ---------------------------------------------------------------------------


def _block_mlp(params, l, x):
    h = jnp.maximum(x @ params["l%d.w1" % l], 0.0)
    return x + h @ params["l%d.w2" % l]


def _decode_body(cfg: DecodeConfig, params, k_pool, v_pool, tokens,
                 lengths, slot_ids):
    """One decode step over the packed active set.

    ``k_pool``/``v_pool``: (L, S+1, P, H, Dh) donated; ``tokens`` /
    ``lengths``: (S+1,) int32 donated (tokens = each slot's NEXT input
    token, device-resident so the pump never reads the host between
    steps); ``slot_ids``: (b,) int32, padded lanes carry the scratch
    index S.  Returns the four state arrays (aliased in place via
    donation) plus the (b,) sampled tokens for the harvester.
    """
    tok = tokens[slot_ids]                              # (b,)
    lens = lengths[slot_ids]                            # (b,)
    x = params["emb"][tok]                              # (b, D)
    b = x.shape[0]
    pos = lens                     # this token's KV write position
    for l in range(cfg.layers):
        k_new = (x @ params["l%d.wk" % l]).reshape(
            b, cfg.heads, cfg.head_dim)
        v_new = (x @ params["l%d.wv" % l]).reshape(
            b, cfg.heads, cfg.head_dim)
        k_pool = k_pool.at[l, slot_ids, pos].set(k_new)
        v_pool = v_pool.at[l, slot_ids, pos].set(v_new)
        q = (x @ params["l%d.wq" % l]).reshape(b, cfg.heads,
                                               cfg.head_dim)
        att = cached_attention(q, k_pool[l, slot_ids],
                               v_pool[l, slot_ids], lens + 1)
        x = x + att.reshape(b, cfg.dim) @ params["l%d.wo" % l]
        x = _block_mlp(params, l, x)
    logits = x @ params["unemb"]                        # (b, V)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = tokens.at[slot_ids].set(nxt)
    lengths = lengths.at[slot_ids].set(lens + 1)
    # park the scratch slot: padded lanes read/write it every step, so
    # its bookkeeping must reset or its fake length would creep past
    # the pool extent
    tokens = tokens.at[cfg.slots].set(0)
    lengths = lengths.at[cfg.slots].set(0)
    return k_pool, v_pool, tokens, lengths, nxt


def _prefill_body(cfg: DecodeConfig, params, k_pool, v_pool, tokens,
                  lengths, slot_id, prompt, n):
    """Process one padded prompt into slot ``slot_id``: causal attention
    over the prompt (keys masked to the true length ``n``), KV pages
    written for every position, first generated token sampled from the
    last REAL position.  Rows past ``n`` compute garbage that is never
    attended (decode masks by length) and is overwritten as the
    generation advances."""
    Lp = prompt.shape[0]
    x = params["emb"][prompt]                           # (Lp, D)
    valid = jnp.arange(Lp) < n
    for l in range(cfg.layers):
        k = (x @ params["l%d.wk" % l]).reshape(Lp, cfg.heads,
                                               cfg.head_dim)
        v = (x @ params["l%d.wv" % l]).reshape(Lp, cfg.heads,
                                               cfg.head_dim)
        k_pool = lax.dynamic_update_slice(
            k_pool, k[None, None], (l, slot_id, 0, 0, 0))
        v_pool = lax.dynamic_update_slice(
            v_pool, v[None, None], (l, slot_id, 0, 0, 0))
        q = (x @ params["l%d.wq" % l]).reshape(Lp, cfg.heads,
                                               cfg.head_dim)
        q4 = q.transpose(1, 0, 2)[None]                 # (1, H, Lp, Dh)
        k4 = k.transpose(1, 0, 2)[None]
        v4 = v.transpose(1, 0, 2)[None]
        att = attention_core(q4, k4, v4, causal=True,
                             mask=valid[None, None, None, :])
        x = x + att[0].transpose(1, 0, 2).reshape(Lp, cfg.dim) \
            @ params["l%d.wo" % l]
        x = _block_mlp(params, l, x)
    x_last = jnp.take(x, jnp.maximum(n - 1, 0), axis=0)
    logits = x_last @ params["unemb"]
    t0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = tokens.at[slot_id].set(t0)
    lengths = lengths.at[slot_id].set(n)
    return k_pool, v_pool, tokens, lengths, t0


def _paged_decode_body(cfg: DecodeConfig, params, k_heap, v_heap,
                       tokens, lengths, slot_ids, block_tbls):
    """One decode step over the packed active set, PAGED pool (ISSUE
    18).

    ``k_heap``/``v_heap``: (L, kv_pages, kv_page_len, H, Dh) donated —
    the ONE shared heap; ``block_tbls``: (b, pages_per_slot) int32
    physical page ids per lane (padded lanes carry all-zero rows: page
    0 is the reserved scratch page).  The new token's KV entry scatters
    to ``block_tbls[lane][pos // page_len]`` at offset ``pos %
    page_len``; attention gathers each lane's pages back into its
    logical extent via :func:`paged_attention`.  Decode never writes a
    SHARED page: generation positions live past the prompt, in pages
    the session allocated privately.
    """
    pl = cfg.kv_page_len
    tok = tokens[slot_ids]                              # (b,)
    lens = lengths[slot_ids]                            # (b,)
    x = params["emb"][tok]                              # (b, D)
    b = x.shape[0]
    pos = lens                     # this token's logical write position
    page_idx = jnp.clip(pos // pl, 0, cfg.pages_per_slot - 1)
    phys = jnp.take_along_axis(block_tbls, page_idx[:, None],
                               axis=1)[:, 0]            # (b,)
    off = pos % pl
    for l in range(cfg.layers):
        k_new = (x @ params["l%d.wk" % l]).reshape(
            b, cfg.heads, cfg.head_dim)
        v_new = (x @ params["l%d.wv" % l]).reshape(
            b, cfg.heads, cfg.head_dim)
        k_heap = k_heap.at[l, phys, off].set(k_new)
        v_heap = v_heap.at[l, phys, off].set(v_new)
        q = (x @ params["l%d.wq" % l]).reshape(b, cfg.heads,
                                               cfg.head_dim)
        att = paged_attention(q, k_heap[l], v_heap[l], block_tbls,
                              lens + 1)
        x = x + att.reshape(b, cfg.dim) @ params["l%d.wo" % l]
        x = _block_mlp(params, l, x)
    logits = x @ params["unemb"]                        # (b, V)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = tokens.at[slot_ids].set(nxt)
    lengths = lengths.at[slot_ids].set(lens + 1)
    # park the scratch slot (same discipline as the flat body)
    tokens = tokens.at[cfg.slots].set(0)
    lengths = lengths.at[cfg.slots].set(0)
    return k_heap, v_heap, tokens, lengths, nxt


def _prefill_chunk_body(cfg: DecodeConfig, params, k_heap, v_heap,
                        tokens, lengths, slot_id, block_tbl, chunk,
                        start, nvalid, emit, cow_src, cow_dst):
    """One page-aligned prefill CHUNK into the paged heap (ISSUE 18).

    ``chunk``: (prefill_chunk,) token ids for absolute positions
    ``start .. start+Lc-1`` (rows past ``nvalid`` are padding — their
    KV writes land in the session's own reserved pages or the scratch
    page and are masked/overwritten, never attended); ``block_tbl``:
    (pages_per_slot,) this session's physical pages.  Row ``r``
    attends causally over absolute keys ``0 .. start+r``, gathered
    through the block table — earlier chunks' (or a DONOR's shared)
    pages included, so chunking is bit-compatible with one monolithic
    prefill.

    Copy-on-write folds in here: the program FIRST copies page
    ``cow_src`` -> ``cow_dst`` (both scalars; ``src == dst == 0`` is
    the self-copy no-op for chunks with no divergence), so a full
    prompt-coverage prefix hit needs only this ONE replay-chunk
    dispatch to fork the donor's last page and emit the first token —
    one trace signature regardless, keeping the chunk program table
    closed.

    ``emit`` > 0 (the final chunk) samples the first generated token
    from row ``nvalid - 1`` and arms the slot's next-input token;
    earlier chunks leave it untouched.  ``lengths[slot]`` advances to
    ``start + nvalid`` either way.
    """
    pl = cfg.kv_page_len
    Lc = chunk.shape[0]
    k_heap = k_heap.at[:, cow_dst].set(k_heap[:, cow_src])
    v_heap = v_heap.at[:, cow_dst].set(v_heap[:, cow_src])
    x = params["emb"][chunk]                            # (Lc, D)
    p = start + jnp.arange(Lc)                          # absolute pos
    page_idx = jnp.clip(p // pl, 0, cfg.pages_per_slot - 1)
    phys = block_tbl[page_idx]                          # (Lc,)
    off = p % pl
    ext = cfg.pages_per_slot * pl
    # causal-prefix mask: row r sees absolute keys 0..start+r (>= 1
    # live key per row, so the finite -1e30 masking stays NaN-free)
    mask = jnp.arange(ext)[None, :] <= p[:, None]       # (Lc, ext)
    for l in range(cfg.layers):
        k = (x @ params["l%d.wk" % l]).reshape(Lc, cfg.heads,
                                               cfg.head_dim)
        v = (x @ params["l%d.wv" % l]).reshape(Lc, cfg.heads,
                                               cfg.head_dim)
        k_heap = k_heap.at[l, phys, off].set(k)
        v_heap = v_heap.at[l, phys, off].set(v)
        q = (x @ params["l%d.wq" % l]).reshape(Lc, cfg.heads,
                                               cfg.head_dim)
        k_all = k_heap[l, block_tbl].reshape(ext, cfg.heads,
                                             cfg.head_dim)
        v_all = v_heap[l, block_tbl].reshape(ext, cfg.heads,
                                             cfg.head_dim)
        q4 = q.transpose(1, 0, 2)[None]                 # (1, H, Lc, Dh)
        k4 = k_all.transpose(1, 0, 2)[None]
        v4 = v_all.transpose(1, 0, 2)[None]
        att = attention_core(q4, k4, v4, mask=mask[None, None])
        x = x + att[0].transpose(1, 0, 2).reshape(Lc, cfg.dim) \
            @ params["l%d.wo" % l]
        x = _block_mlp(params, l, x)
    x_last = jnp.take(x, jnp.maximum(nvalid - 1, 0), axis=0)
    logits = x_last @ params["unemb"]
    t0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = tokens.at[slot_id].set(
        jnp.where(emit > 0, t0, tokens[slot_id]))
    lengths = lengths.at[slot_id].set(start + nvalid)
    return k_heap, v_heap, tokens, lengths, t0


def _draft_step_body(cfg: DecodeConfig, params, k_pool, v_pool, tokens,
                     lengths, props, slot_ids, col):
    """One DRAFT autoregressive step (ISSUE 20): the flat decode body
    on the draft's own tiny KV pool, with the sampled token ALSO
    written into column ``col`` of the device-resident proposals
    buffer ``props`` (slots+1, spec_k) — the verify dispatch reads the
    whole window from there, so the k draft steps + verify chain never
    syncs the host.  ``col`` is a traced scalar: one program per slot
    bucket covers every window position."""
    k_pool, v_pool, tokens, lengths, nxt = _decode_body(
        cfg, params, k_pool, v_pool, tokens, lengths, slot_ids)
    props = props.at[slot_ids, col].set(nxt)
    # park the scratch row (padded lanes write it every step)
    props = props.at[cfg.slots].set(0)
    return k_pool, v_pool, tokens, lengths, props


def _draft_prefill_body(cfg: DecodeConfig, params, k_pool, v_pool,
                        tokens, lengths, tgt_tokens, slot_id, prompt,
                        n):
    """Prefill the DRAFT's KV pool for one admitted session (ISSUE
    20): the flat prefill body, except the slot's next-input token is
    adopted from the TARGET's token array (read-only input) rather
    than the draft's own first-token prediction — the window invariant
    is that draft and target agree on (next token, length) at every
    window boundary, and the first committed token is the target's.
    Passing ``tgt_tokens`` as a program input also makes XLA order
    this dispatch after the target's emitting prefill chunk."""
    k_pool, v_pool, tokens, lengths, _t0 = _prefill_body(
        cfg, params, k_pool, v_pool, tokens, lengths, slot_id, prompt,
        n)
    tokens = tokens.at[slot_id].set(tgt_tokens[slot_id])
    return k_pool, v_pool, tokens, lengths


def _verify_body(cfg: DecodeConfig, params, k_heap, v_heap, t_tok,
                 t_len, d_tok, d_len, props, slot_ids, block_tbls):
    """Verify one speculative window in ONE dispatch (ISSUE 20).

    Window invariant on entry (per active lane, slot ``s``, length
    ``L``, next token ``t``): the draft ran k steps from (t, L), so
    ``props[s]`` holds its proposals d_1..d_k and the draft KV covers
    positions L..L+k-1 (inputs t, d_1..d_{k-1}).  This program runs
    the TARGET over the k+1 inputs ``[t, d_1..d_k]`` at positions
    ``L..L+k`` through the paged heap — the chunked-prefill scatter/
    gather pattern with a per-position causal mask — and argmaxes
    every position: ``a_j`` is the target's greedy token after input
    j.  Acceptance is the standard longest-prefix rule, CAPPED at
    k-1 so the committed state never depends on position L+k (whose
    input d_k may be wrong):

        m  = max prefix with d_j == a_{j-1}        (0..k)
        m' = min(m, k-1)
        emit a_0..a_{m'}  (1..k tokens; all provably equal what
                           non-speculative greedy decode emits)
        next token = a_{m'},  new length = L + m' + 1

    On full acceptance (m == k) this emits k tokens and the next
    token a_{k-1} == d_k is exactly the draft's current state — both
    models stay in lockstep with no host round-trip; on a rejection
    the program itself rewrites the DRAFT's (token, length) arrays
    (donated in) to the corrected values, so the draft's stale KV
    past the new length is masked garbage, overwritten by its next
    window's steps.  Target KV entries past L+m' are likewise stale
    and land inside the slot's pages (k <= _OVERRUN_MARGIN).

    Returns (k_heap, v_heap, t_tok, t_len, d_tok, d_len, emitted,
    n_em): ``emitted`` (b, k) holds a_0..a_{k-1}, of which the first
    ``n_em[lane]`` are real — the harvester appends exactly those.
    """
    pl = cfg.kv_page_len
    K = props.shape[1]
    E = K + 1
    lens = t_len[slot_ids]                              # (b,) = L
    cur = t_tok[slot_ids]                               # (b,)
    d = props[slot_ids]                                 # (b, K)
    inp = jnp.concatenate([cur[:, None], d], axis=1)    # (b, E)
    x = params["emb"][inp]                              # (b, E, D)
    b = x.shape[0]
    pos = lens[:, None] + jnp.arange(E)[None, :]        # (b, E)
    page_idx = jnp.clip(pos // pl, 0, cfg.pages_per_slot - 1)
    phys = jnp.take_along_axis(block_tbls, page_idx, axis=1)  # (b, E)
    off = pos % pl
    for l in range(cfg.layers):
        k_new = (x @ params["l%d.wk" % l]).reshape(
            b, E, cfg.heads, cfg.head_dim)
        v_new = (x @ params["l%d.wv" % l]).reshape(
            b, E, cfg.heads, cfg.head_dim)
        k_heap = k_heap.at[l, phys, off].set(k_new)
        v_heap = v_heap.at[l, phys, off].set(v_new)
        q = (x @ params["l%d.wq" % l]).reshape(b, E, cfg.heads,
                                               cfg.head_dim)
        att = paged_attention_multi(q, k_heap[l], v_heap[l],
                                    block_tbls, pos)
        x = x + att.reshape(b, E, cfg.dim) @ params["l%d.wo" % l]
        x = _block_mlp(params, l, x)
    logits = x @ params["unemb"]                        # (b, E, V)
    a = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # (b, E)
    # accept d_{i+1} while it equals a_i, longest prefix, capped k-1
    match = (d == a[:, :K]).astype(jnp.int32)           # (b, K)
    m = jnp.sum(jnp.cumprod(match, axis=1), axis=1)     # (b,) 0..K
    m_cap = jnp.minimum(m, K - 1)
    n_em = (m_cap + 1).astype(jnp.int32)                # (b,) 1..K
    emitted = a[:, :K]                                  # (b, K)
    new_tok = jnp.take_along_axis(a, m_cap[:, None], axis=1)[:, 0]
    new_len = lens + n_em
    t_tok = t_tok.at[slot_ids].set(new_tok)
    t_len = t_len.at[slot_ids].set(new_len)
    d_tok = d_tok.at[slot_ids].set(new_tok)
    d_len = d_len.at[slot_ids].set(new_len)
    # park the scratch slot on BOTH state pairs (padded lanes)
    t_tok = t_tok.at[cfg.slots].set(0)
    t_len = t_len.at[cfg.slots].set(0)
    d_tok = d_tok.at[cfg.slots].set(0)
    d_len = d_len.at[cfg.slots].set(0)
    return (k_heap, v_heap, t_tok, t_len, d_tok, d_len, emitted,
            n_em)


# geometry-keyed jit cache for the reference oracle: a load driver
# replays MANY reference decodes against one model — per-token eager
# dispatch would dominate its wall time.  Plain jax.jit, deliberately
# NOT register_program: the oracle is a verification tool, not a
# serving path, and must not pollute the serve census.
_reference_jits: Dict[Tuple, Tuple] = {}
_reference_jits_lock = threading.Lock()


def _reference_step_fns(cfg: DecodeConfig):
    key = (cfg.vocab, cfg.dim, cfg.heads, cfg.layers, cfg.slots,
           cfg.max_len)
    with _reference_jits_lock:
        fns = _reference_jits.get(key)
        if fns is None:
            fns = (jax.jit(functools.partial(_prefill_body, cfg)),
                   jax.jit(functools.partial(_decode_body, cfg)))
            _reference_jits[key] = fns
        return fns


def reference_generate(prompt: Sequence[int], max_new: int,
                       params: Optional[Dict] = None,
                       config: Optional[DecodeConfig] = None,
                       eos_id: Optional[int] = None) -> List[int]:
    """Local greedy-decode oracle: drives the SAME prefill/decode
    bodies through a private single-slot state (no pool sharing), so a
    load driver can recompute what a correct replica must answer — the
    decode analogue of ``demo.demo_expected``."""
    cfg = config or DecodeConfig()
    params = params if params is not None else demo_lm_params(cfg)
    lp = cfg.prompt_bucket_for(len(prompt))
    if lp is None:
        raise MXNetError("reference_generate: prompt of %d tokens "
                         "exceeds the top prompt bucket %d"
                         % (len(prompt), cfg.prompt_buckets[-1]))
    prefill_fn, decode_fn = _reference_step_fns(cfg)
    shape = (cfg.layers, cfg.slots + 1, cfg.max_len, cfg.heads,
             cfg.head_dim)
    k = jnp.zeros(shape, jnp.float32)
    v = jnp.zeros(shape, jnp.float32)
    tok = jnp.zeros((cfg.slots + 1,), jnp.int32)
    ln = jnp.zeros((cfg.slots + 1,), jnp.int32)
    padded = _np.zeros(lp, _np.int32)
    padded[:len(prompt)] = list(prompt)
    k, v, tok, ln, t0 = prefill_fn(params, k, v, tok, ln,
                                   _np.int32(0), jnp.asarray(padded),
                                   _np.int32(len(prompt)))
    out = [int(t0)]
    ids = jnp.zeros((1,), jnp.int32)
    while len(out) < max_new:
        if eos_id is not None and out[-1] == eos_id:
            break
        k, v, tok, ln, nxt = decode_fn(params, k, v, tok, ln, ids)
        out.append(int(nxt[0]))
    return out[:max_new]


class _CensusHandle:
    """Weakref-able holder so one servable can own two census buckets
    (its KV pool under ``kv_cache``, its parameters under ``serve``)."""

    __slots__ = ("fn", "__weakref__")

    def __init__(self, fn):
        self.fn = fn


def _counter(name, doc):
    return _telemetry.registry.counter(name, doc=doc)


class DecodeServable:
    """One immutable decode-model version: params + device-resident KV
    pool + the two bucketed AOT program tables (prefill by prompt
    bucket, decode by slot bucket).

    The KV state (pool pages, per-slot next-token and length arrays) is
    DONATED through every dispatch: ``_state`` always holds the only
    live copy, rebound from the program outputs, so pool bytes in
    ``buffer_census()['kv_cache']`` are constant for the servable's
    lifetime.  Only the pump thread may dispatch (single-writer state).
    """

    #: engine discriminator on the health surface; the paged subclass
    #: overrides both (its heap is censused under ``kv_pages``)
    engine = "flat"
    census_owner = "kv_cache"

    def _alloc_state(self) -> Dict[str, jnp.ndarray]:
        cfg = self.config
        shape = (cfg.layers, cfg.slots + 1, cfg.max_len, cfg.heads,
                 cfg.head_dim)
        return {
            "k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32),
            "tok": jnp.zeros((cfg.slots + 1,), jnp.int32),
            "len": jnp.zeros((cfg.slots + 1,), jnp.int32),
        }

    def __init__(self, params: Optional[Dict] = None,
                 config: Optional[DecodeConfig] = None,
                 name: str = "demo-lm", version: int = 1):
        self.config = config or DecodeConfig()
        self.params = params if params is not None \
            else demo_lm_params(self.config)
        self.name = str(name)
        self.version = int(version)
        self._state: Dict[str, jnp.ndarray] = self._alloc_state()
        from .. import programs as _programs
        self._kv_handle = _CensusHandle(
            lambda: list(self._state.values()))
        self._params_handle = _CensusHandle(
            lambda: list(self.params.values()))
        _programs.track_buffers(self.census_owner, self._kv_handle,
                                lambda h: h.fn())
        _programs.track_buffers("serve", self._params_handle,
                                lambda h: h.fn())
        self._lock = threading.Lock()
        self._step_programs: Dict[int, object] = {}
        self._prefill_programs: Dict[int, object] = {}
        self._verify_programs: Dict[int, object] = {}
        self.retraces = 0            # program builds (warm pays them)
        self.hits = 0                # dispatches answered by the table
        self.warmed = False
        self._c_retrace = _counter(
            "serve.retraces", "serve-side program builds (should be 0 "
            "after warmup; warm() pays them at deploy)")
        self._c_hits = _counter(
            "serve.bucket_hits", "dispatches answered by a pre-built "
            "bucket program")

    # -- HBM census (ISSUE 20 bin-packing) ----------------------------------
    def program_prefix(self) -> str:
        """Program-registry name prefix this servable's programs live
        under (the budget packer reads their memory_analysis here)."""
        return "serve.decode."

    def live_bytes(self) -> int:
        """Resident bytes: params + the whole KV state (pool or page
        heap) — exactly the arrays the buffer census owner-tags."""
        n = sum(int(getattr(a, "nbytes", 0))
                for a in self.params.values())
        n += sum(int(getattr(a, "nbytes", 0))
                 for a in self._state.values())
        return n

    def footprint_bytes(self) -> int:
        """Measured HBM footprint for the ModelHost budget packer:
        live bytes plus the peak transient bytes of any registered
        decode program (populated by :meth:`warm`)."""
        from .. import programs as _programs
        mem = _programs.program_memory_bytes(self.program_prefix())
        return self.live_bytes() + int(mem["temp_bytes_peak"])

    # -- program tables -----------------------------------------------------
    def step_program(self, bucket: int):
        """The decode program for one slot bucket (builds on miss,
        counted as a retrace — warm() pre-builds every bucket)."""
        bucket = int(bucket)
        with self._lock:
            prog = self._step_programs.get(bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_decode(params, k_pool, v_pool, tokens, lengths,
                       slot_ids):
            return _decode_body(cfg, params, k_pool, v_pool, tokens,
                                lengths, slot_ids)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.step.s%d" % bucket, run_decode,
                donate_argnums=(1, 2, 3, 4))
        with self._lock:
            prog = self._step_programs.setdefault(bucket, prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    def prefill_program(self, prompt_bucket: int):
        prompt_bucket = int(prompt_bucket)
        with self._lock:
            prog = self._prefill_programs.get(prompt_bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_prefill(params, k_pool, v_pool, tokens, lengths,
                        slot_id, prompt, n):
            return _prefill_body(cfg, params, k_pool, v_pool, tokens,
                                 lengths, slot_id, prompt, n)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.prefill.p%d" % prompt_bucket, run_prefill,
                donate_argnums=(1, 2, 3, 4))
        with self._lock:
            prog = self._prefill_programs.setdefault(prompt_bucket,
                                                     prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    # -- dispatch (pump thread only; mxlint hot-path roots) -----------------
    def dispatch_step(self, slot_ids: _np.ndarray):
        """ONE device program over the packed active set; rebinds the
        donated state and returns the (b,) emitted-token device array
        (async — the harvester syncs it)."""
        from ..engine import engine as _engine
        prog = self.step_program(len(slot_ids))
        st = self._state
        k, v, tok, ln, out = prog(self.params, st["k"], st["v"],
                                  st["tok"], st["len"], slot_ids)
        self._state = {"k": k, "v": v, "tok": tok, "len": ln}
        _engine.count_dispatch(1)
        return out

    def dispatch_prefill(self, slot: int, prompt: _np.ndarray, n: int):
        """ONE device program filling ``slot``'s KV pages from a padded
        prompt; returns the first generated token as a () device
        array."""
        from ..engine import engine as _engine
        prog = self.prefill_program(prompt.shape[0])
        st = self._state
        k, v, tok, ln, t0 = prog(self.params, st["k"], st["v"],
                                 st["tok"], st["len"],
                                 _np.int32(slot), prompt, _np.int32(n))
        self._state = {"k": k, "v": v, "tok": tok, "len": ln}
        _engine.count_dispatch(1)
        return t0

    def warm(self) -> "DecodeServable":
        """Pre-build + pre-run EVERY prefill and decode bucket (against
        the scratch slot), then reset the generation bookkeeping —
        after this, serve time never pays a trace."""
        cfg = self.config
        for lp in cfg.prompt_buckets:
            self.dispatch_prefill(cfg.slots,
                                  _np.zeros(lp, _np.int32), lp)
        for b in cfg.slot_buckets:
            self.dispatch_step(_np.full(b, cfg.slots, _np.int32))
        jax.block_until_ready(self._state["k"])
        # scratch-slot bookkeeping back to empty; the pool's warmed
        # garbage is masked by zero lengths and overwritten on reuse
        self._state["tok"] = jnp.zeros_like(self._state["tok"])
        self._state["len"] = jnp.zeros_like(self._state["len"])
        self.warmed = True
        return self

    def kv_state_bytes(self) -> int:
        """Current KV-state footprint (pool pages + token/length
        arrays) — the number that must stay FLAT across generations."""
        return sum(int(a.nbytes) for a in self._state.values())

    def kv_slot_bytes(self) -> int:
        """One slot's share of the KV pool (the scratch lane counts as
        a slot here — the pool is ``slots + 1`` lanes wide), i.e. the
        bytes a free slot represents as ADMISSION headroom."""
        return self.kv_state_bytes() // (self.config.slots + 1)


class PagedDecodeServable(DecodeServable):
    """The PAGED decode servable (ISSUE 18): same model, but the KV
    store is one shared page heap ``(L, kv_pages, kv_page_len, H,
    Dh)`` — owner-tagged ``kv_pages`` in the census, donated through
    every dispatch — addressed per session through host-side block
    tables.  Two program tables replace the flat pair:

    * ``serve.decode.paged.step.s{b}`` per slot bucket — the decode
      step with per-lane block tables (scatter the new KV entry to its
      physical page, gather the lane's pages for attention);
    * ``serve.decode.paged.prefill.c{Lc}`` — ONE chunk program (the
      chunk length is the compile unit, not the prompt bucket): any
      admitted prompt prefills as a train of page-aligned chunks, and
      the CoW page fork rides the same signature, so the trace set is
      closed with a single prefill program regardless of prompt
      length.

    The monolithic flat prefill has no paged analogue —
    :meth:`dispatch_prefill` raises; the pump schedules
    :meth:`dispatch_chunk` trains instead.
    """

    engine = "paged"
    census_owner = "kv_pages"

    def _alloc_state(self) -> Dict[str, jnp.ndarray]:
        cfg = self.config
        heap = (cfg.layers, cfg.kv_pages, cfg.kv_page_len, cfg.heads,
                cfg.head_dim)
        return {
            "k": jnp.zeros(heap, jnp.float32),
            "v": jnp.zeros(heap, jnp.float32),
            "tok": jnp.zeros((cfg.slots + 1,), jnp.int32),
            "len": jnp.zeros((cfg.slots + 1,), jnp.int32),
        }

    # -- program tables -----------------------------------------------------
    def step_program(self, bucket: int):
        bucket = int(bucket)
        with self._lock:
            prog = self._step_programs.get(bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_decode(params, k_heap, v_heap, tokens, lengths,
                       slot_ids, block_tbls):
            return _paged_decode_body(cfg, params, k_heap, v_heap,
                                      tokens, lengths, slot_ids,
                                      block_tbls)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.paged.step.s%d" % bucket, run_decode,
                donate_argnums=(1, 2, 3, 4))
        with self._lock:
            prog = self._step_programs.setdefault(bucket, prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    def chunk_program(self):
        """THE prefill program: one signature (chunk length
        ``prefill_chunk``) covers every admitted prompt as a chunk
        train."""
        lc = self.config.prefill_chunk
        with self._lock:
            prog = self._prefill_programs.get(lc)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_chunk(params, k_heap, v_heap, tokens, lengths, slot_id,
                      block_tbl, chunk, start, nvalid, emit, cow_src,
                      cow_dst):
            return _prefill_chunk_body(cfg, params, k_heap, v_heap,
                                       tokens, lengths, slot_id,
                                       block_tbl, chunk, start, nvalid,
                                       emit, cow_src, cow_dst)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.paged.prefill.c%d" % lc, run_chunk,
                donate_argnums=(1, 2, 3, 4))
        with self._lock:
            prog = self._prefill_programs.setdefault(lc, prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    def prefill_program(self, prompt_bucket: int):
        raise MXNetError("paged decode servable has no monolithic "
                         "prefill program; prompts prefill as chunk "
                         "trains (chunk_program)")

    # -- dispatch (pump thread only; mxlint hot-path roots) -----------------
    def dispatch_step(self, slot_ids: _np.ndarray,
                      block_tbls: _np.ndarray):
        """ONE device program over the packed active set + its block
        tables; rebinds the donated heap state."""
        from ..engine import engine as _engine
        prog = self.step_program(len(slot_ids))
        st = self._state
        k, v, tok, ln, out = prog(self.params, st["k"], st["v"],
                                  st["tok"], st["len"], slot_ids,
                                  block_tbls)
        self._state = {"k": k, "v": v, "tok": tok, "len": ln}
        _engine.count_dispatch(1)
        return out

    def dispatch_prefill(self, slot: int, prompt: _np.ndarray, n: int):
        raise MXNetError("paged decode servable has no monolithic "
                         "prefill dispatch; use dispatch_chunk")

    def dispatch_chunk(self, slot: int, block_tbl: _np.ndarray,
                       chunk: _np.ndarray, start: int, nvalid: int,
                       emit: bool, cow_src: int = 0, cow_dst: int = 0):
        """ONE device program writing one page-aligned prefill chunk
        (plus the optional CoW page fork) through ``slot``'s block
        table; returns the chunk's sampled token as a () device array
        (meaningful only when ``emit``)."""
        from ..engine import engine as _engine
        prog = self.chunk_program()
        st = self._state
        k, v, tok, ln, t0 = prog(
            self.params, st["k"], st["v"], st["tok"], st["len"],
            _np.int32(slot), block_tbl, chunk, _np.int32(start),
            _np.int32(nvalid), _np.int32(1 if emit else 0),
            _np.int32(cow_src), _np.int32(cow_dst))
        self._state = {"k": k, "v": v, "tok": tok, "len": ln}
        _engine.count_dispatch(1)
        return t0

    def verify_program(self, bucket: int):
        """The speculative VERIFY program for one slot bucket (ISSUE
        20): all k+1 window positions of every lane in one dispatch —
        multi-position paged attention, per-position argmax, the
        accept-longest-prefix rule and the draft-state correction all
        traced into a single program."""
        bucket = int(bucket)
        with self._lock:
            prog = self._verify_programs.get(bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_verify(params, k_heap, v_heap, t_tok, t_len, d_tok,
                       d_len, props, slot_ids, block_tbls):
            return _verify_body(cfg, params, k_heap, v_heap, t_tok,
                                t_len, d_tok, d_len, props, slot_ids,
                                block_tbls)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.verify.k%d.s%d" % (cfg.spec_k, bucket),
                run_verify, donate_argnums=(1, 2, 3, 4, 5, 6))
        with self._lock:
            prog = self._verify_programs.setdefault(bucket, prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    def dispatch_verify(self, draft: "DraftDecodeServable",
                        slot_ids: _np.ndarray,
                        block_tbls: _np.ndarray):
        """ONE verify dispatch over the packed window set: donates the
        target heap state AND the draft's token/length arrays (both
        rebound), reads the draft's device-resident proposals buffer —
        no host sync anywhere; the (emitted, n_em) pair goes to the
        harvester."""
        from ..engine import engine as _engine
        prog = self.verify_program(len(slot_ids))
        st = self._state
        dst = draft._state
        k, v, tt, tl, dt, dl, emitted, n_em = prog(
            self.params, st["k"], st["v"], st["tok"], st["len"],
            dst["tok"], dst["len"], dst["props"], slot_ids,
            block_tbls)
        self._state = {"k": k, "v": v, "tok": tt, "len": tl}
        draft._state = {"k": dst["k"], "v": dst["v"], "tok": dt,
                        "len": dl, "props": dst["props"]}
        _engine.count_dispatch(1)
        return emitted, n_em

    def warm(self) -> "PagedDecodeServable":
        """Pre-build + pre-run the chunk program and every decode slot
        bucket against the scratch page/slot, then reset the
        bookkeeping — zero serve-time retraces, as the flat engine."""
        cfg = self.config
        tbl = _np.zeros(cfg.pages_per_slot, _np.int32)
        self.dispatch_chunk(cfg.slots, tbl,
                            _np.zeros(cfg.prefill_chunk, _np.int32),
                            0, cfg.prefill_chunk, False)
        for b in cfg.slot_buckets:
            self.dispatch_step(
                _np.full(b, cfg.slots, _np.int32),
                _np.zeros((b, cfg.pages_per_slot), _np.int32))
        jax.block_until_ready(self._state["k"])
        self._state["tok"] = jnp.zeros_like(self._state["tok"])
        self._state["len"] = jnp.zeros_like(self._state["len"])
        self.warmed = True
        return self

    def page_bytes(self) -> int:
        """One physical page's K+V bytes across all layers — the unit
        the allocator's headroom gauges convert to bytes with."""
        cfg = self.config
        return (2 * cfg.layers * cfg.kv_page_len * cfg.heads *
                cfg.head_dim * 4)

    def kv_slot_bytes(self) -> int:
        """A worst-case session's heap share (its full block-table
        extent) — what one admission can cost at most."""
        return self.page_bytes() * self.config.pages_per_slot


class DraftDecodeServable(DecodeServable):
    """The DRAFT servable for speculative decoding (ISSUE 20): a small
    flat-pool decode model whose steps write their sampled tokens into
    a device-resident PROPOSALS buffer ``(slots+1, spec_k)`` instead
    of feeding the harvester — the target's verify program reads the
    whole window from it, so draft + verify form a pure device-side
    chain.  Geometry (slots, buckets, pool length) must match the
    target's so slot ids and lengths line up 1:1; only depth/width
    differ.  Co-hosted under the ModelHost HBM budget like any other
    servable (its pool is censused ``kv_cache``, its params
    ``serve``)."""

    engine = "draft"

    def program_prefix(self) -> str:
        return "serve.decode.draft."

    def _alloc_state(self) -> Dict[str, jnp.ndarray]:
        st = super()._alloc_state()
        cfg = self.config
        st["props"] = jnp.zeros((cfg.slots + 1, cfg.spec_k),
                                jnp.int32)
        return st

    # -- program tables -----------------------------------------------------
    def step_program(self, bucket: int):
        bucket = int(bucket)
        with self._lock:
            prog = self._step_programs.get(bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_draft(params, k_pool, v_pool, tokens, lengths, props,
                      slot_ids, col):
            return _draft_step_body(cfg, params, k_pool, v_pool,
                                    tokens, lengths, props, slot_ids,
                                    col)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.draft.s%d" % bucket, run_draft,
                donate_argnums=(1, 2, 3, 4, 5))
        with self._lock:
            prog = self._step_programs.setdefault(bucket, prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    def prefill_program(self, prompt_bucket: int):
        prompt_bucket = int(prompt_bucket)
        with self._lock:
            prog = self._prefill_programs.get(prompt_bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_prefill(params, k_pool, v_pool, tokens, lengths,
                        tgt_tokens, slot_id, prompt, n):
            return _draft_prefill_body(cfg, params, k_pool, v_pool,
                                       tokens, lengths, tgt_tokens,
                                       slot_id, prompt, n)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.draft.prefill.p%d" % prompt_bucket,
                run_prefill, donate_argnums=(1, 2, 3, 4))
        with self._lock:
            prog = self._prefill_programs.setdefault(prompt_bucket,
                                                     prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    # -- dispatch (pump thread only; mxlint hot-path roots) -----------------
    def dispatch_step(self, slot_ids: _np.ndarray, col: int):
        """ONE draft step over the packed window set, writing window
        column ``col`` of the proposals buffer."""
        from ..engine import engine as _engine
        prog = self.step_program(len(slot_ids))
        st = self._state
        k, v, tok, ln, props = prog(self.params, st["k"], st["v"],
                                    st["tok"], st["len"], st["props"],
                                    slot_ids, _np.int32(col))
        self._state = {"k": k, "v": v, "tok": tok, "len": ln,
                       "props": props}
        _engine.count_dispatch(1)
        return props

    def dispatch_prefill(self, slot: int, prompt: _np.ndarray, n: int,
                         tgt_tokens=None):
        """ONE draft-prefill dispatch; ``tgt_tokens`` is the TARGET's
        token array (read-only), whose ``slot`` entry arms the draft's
        next-input token."""
        from ..engine import engine as _engine
        prog = self.prefill_program(prompt.shape[0])
        st = self._state
        if tgt_tokens is None:
            tgt_tokens = jnp.zeros_like(st["tok"])
        k, v, tok, ln = prog(self.params, st["k"], st["v"], st["tok"],
                             st["len"], tgt_tokens, _np.int32(slot),
                             prompt, _np.int32(n))
        self._state = {"k": k, "v": v, "tok": tok, "len": ln,
                       "props": st["props"]}
        _engine.count_dispatch(1)
        return None

    def warm(self) -> "DraftDecodeServable":
        """Pre-build + pre-run every draft prefill and step bucket
        against the scratch slot, then reset the bookkeeping."""
        cfg = self.config
        zeros_tok = jnp.zeros((cfg.slots + 1,), jnp.int32)
        for lp in cfg.prompt_buckets:
            self.dispatch_prefill(cfg.slots,
                                  _np.zeros(lp, _np.int32), lp,
                                  tgt_tokens=zeros_tok)
        for b in cfg.slot_buckets:
            self.dispatch_step(_np.full(b, cfg.slots, _np.int32), 0)
        jax.block_until_ready(self._state["k"])
        self._state["tok"] = jnp.zeros_like(self._state["tok"])
        self._state["len"] = jnp.zeros_like(self._state["len"])
        self._state["props"] = jnp.zeros_like(self._state["props"])
        self.warmed = True
        return self


class _PendingGen:
    """One admitted generation request: prompt in, tokens accumulating
    out.  The pump owns its slot; the HARVESTER appends tokens, stamps
    per-token latency and flags completion; handler threads block in
    :meth:`result` / stream via :meth:`wait_new`."""

    __slots__ = ("prompt", "max_new", "eos_id", "trace_ctx", "submit_t",
                 "slot", "token_times", "_cv", "_tokens", "_done",
                 "_err", "_last_t")

    def __init__(self, prompt: List[int], max_new: int,
                 eos_id: Optional[int],
                 trace_ctx: Optional[Tuple[str, str]] = None):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.trace_ctx = trace_ctx
        self.submit_t = time.perf_counter()
        self.slot: Optional[int] = None
        self.token_times: List[float] = []   # per-token latency (s)
        self._cv = threading.Condition()
        self._tokens: List[int] = []
        self._done = False
        self._err: Optional[BaseException] = None
        self._last_t: Optional[float] = None

    # -- harvester side -----------------------------------------------------
    def _append(self, tok: int, now: float) -> Tuple[bool, bool]:
        """Record one harvested token; returns (appended, finished).
        Tokens arriving after completion (pipeline overrun) are
        dropped."""
        with self._cv:
            if self._done:
                return False, True
            base = self._last_t if self._last_t is not None \
                else self.submit_t
            self.token_times.append(now - base)
            self._last_t = now
            self._tokens.append(int(tok))
            finished = len(self._tokens) >= self.max_new or (
                self.eos_id is not None and int(tok) == self.eos_id)
            if finished:
                self._done = True
            self._cv.notify_all()
            return True, finished

    def _fail(self, err: BaseException) -> None:
        with self._cv:
            if not self._done:
                self._err = err
                self._done = True
            self._cv.notify_all()

    # -- consumer side ------------------------------------------------------
    def done(self) -> bool:
        with self._cv:
            return self._done

    def tokens_so_far(self) -> List[int]:
        with self._cv:
            return list(self._tokens)

    def wait_new(self, have: int, timeout: float
                 ) -> Tuple[List[int], bool]:
        """Block until more than ``have`` tokens exist (or the
        generation completes / the wait times out); returns (the tokens
        past ``have``, done)."""
        deadline = _fault.Deadline(timeout)
        with self._cv:
            while len(self._tokens) <= have and not self._done:
                remaining = deadline.remaining()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=min(0.05, remaining))
            return list(self._tokens[have:]), self._done

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block (bounded) for the whole generation; raises on engine
        failure or timeout."""
        timeout = _result_timeout(timeout)
        deadline = _fault.Deadline(timeout)
        with self._cv:
            while not self._done:
                remaining = deadline.remaining()
                if remaining <= 0:
                    raise MXNetError(
                        "serve: generation timed out after %.3gs "
                        "(%d/%d tokens)" % (timeout, len(self._tokens),
                                            self.max_new))
                self._cv.wait(timeout=min(0.1, remaining))
            if self._err is not None:
                raise self._err
            return list(self._tokens)


class DecodeBatcher:
    """The continuous-batching decode engine: admission queue + slot
    allocator + decode pump (pure dispatch) + token harvester (the only
    device→host reader)."""

    def __init__(self, servable: DecodeServable,
                 queue_cap: Optional[int] = None,
                 mode: str = "continuous", on_tick=None,
                 autostart: bool = True):
        if mode not in ("continuous", "request"):
            raise MXNetError("DecodeBatcher mode must be 'continuous' "
                             "or 'request', got %r" % (mode,))
        self._sv = servable
        if not servable.warmed:
            servable.warm()
        self._cap = int(queue_cap if queue_cap is not None else
                        get_env("MX_SERVE_QUEUE_CAP", 256, int))
        self._mode = mode
        self._on_tick = on_tick
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._slot_lk = threading.Lock()
        self._slots: List[Optional[_PendingGen]] = \
            [None] * servable.config.slots
        # bounded pump->harvester handoff: one step boundary emits at
        # most `slots` prefill items + 1 step item, so this bound can
        # never wedge a synchronous (autostart=False) driver, while in
        # threaded mode it caps how far the pump runs ahead of the
        # host-side token reads
        self._harvest_q: _queue.Queue = _queue.Queue(
            maxsize=servable.config.slots + 4)
        self._stop = threading.Event()
        reg = _telemetry.registry
        self._c_requests = reg.counter(
            "serve.decode.requests", doc="admitted generation requests")
        self._c_rejected = reg.counter(
            "serve.decode.rejected", doc="generation requests shed at "
            "admission (queue cap) or refused (prompt too long)")
        self._c_tokens = reg.counter(
            "serve.decode.tokens", doc="generated tokens harvested")
        self._c_steps = reg.counter(
            "serve.decode.steps", doc="decode-step device dispatches "
            "(exactly 1 per step regardless of the active count)")
        self._c_prefills = reg.counter(
            "serve.decode.prefills", doc="prefill device dispatches "
            "(one per admitted sequence)")
        self._c_seqs = reg.counter(
            "serve.decode.sequences", doc="generations retired complete")
        # per-model labeled twins (ISSUE 20): the unlabeled aggregates
        # stay (bench/dispatch_count read them); the labeled series is
        # what fleet.py rolls up per co-hosted model
        _lbl = {"model": servable.name}
        self._c_requests_m = reg.counter(
            "serve.decode.requests", doc="admitted generation requests",
            labels=_lbl)
        self._c_tokens_m = reg.counter(
            "serve.decode.tokens", doc="generated tokens harvested",
            labels=_lbl)
        self._c_seqs_m = reg.counter(
            "serve.decode.sequences", doc="generations retired complete",
            labels=_lbl)
        self._g_queue = reg.gauge(
            "serve.decode.queue", doc="generation requests queued")
        self._g_active = reg.gauge(
            "serve.decode.active_slots", doc="sequences in decode slots")
        # first-class capacity signals (ISSUE 17): the router and
        # autoscaler read these per-replica off the merged FLEET
        # snapshot — no more deriving load from occupancy histograms
        self._g_occupancy = reg.gauge(
            "serve.decode.slot_occupancy",
            doc="fraction of decode slots holding an active sequence "
                "(0..1; router load signal)")
        self._g_headroom = reg.gauge(
            "serve.decode.kv_headroom_bytes",
            doc="KV-pool bytes behind currently-FREE decode slots "
                "(admission headroom; router/autoscaler signal)")
        self._h_occ = reg.histogram(
            "serve.decode.occupancy", doc="active sequences per decode "
            "step", buckets=(1, 2, 4, 8, 16, 32, 64))
        self._h_token = reg.histogram(
            "serve.decode.token_seconds", doc="per-token latency: first "
            "token = submit->harvest (queue + prefill included), then "
            "inter-token gaps",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
        self._set_capacity_gauges(0)
        self._pump = threading.Thread(
            target=self._loop, daemon=True, name="mx-serve-decode-pump")
        self._harvester = threading.Thread(
            target=self._harvest_loop, daemon=True,
            name="mx-serve-decode-harvest")
        if autostart:
            self._pump.start()
            self._harvester.start()

    @property
    def servable(self) -> DecodeServable:
        return self._sv

    @property
    def version(self) -> int:
        return self._sv.version

    @property
    def mode(self) -> str:
        return self._mode

    # -- admission ----------------------------------------------------------
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q)

    def active_count(self) -> int:
        with self._slot_lk:
            return sum(1 for g in self._slots if g is not None)

    def page_stats(self) -> Optional[Dict]:
        """Paged-engine capacity detail for the health surface; the
        flat engine has none."""
        return None

    def _set_capacity_gauges(self, active: int) -> None:
        """Publish the per-replica capacity signals for ``active``
        occupied slots (called wherever occupancy changes)."""
        slots = self._sv.config.slots
        self._g_occupancy.set(active / float(slots) if slots else 0.0)
        self._g_headroom.set(
            max(0, slots - active) * self._sv.kv_slot_bytes())

    def submit(self, prompt: Sequence[int],
               max_new: Optional[int] = None,
               eos_id: Optional[int] = None,
               trace_ctx: Optional[Tuple[str, str]] = None
               ) -> _PendingGen:
        """Admit one generation request.  ``eos_id`` overrides the
        config's stop token for this request (stop tokens are
        per-request in real serving).  Raises :class:`Overloaded` when
        the bounded queue is full, MXNetError when the request can
        never be served (empty/over-bucket prompt, bad token ids)."""
        cfg = self._sv.config
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            self._c_rejected.inc()
            raise MXNetError("serve: GENERATE prompt must be a sequence "
                             "of token ids")
        if not prompt:
            self._c_rejected.inc()
            raise MXNetError("serve: GENERATE needs >= 1 prompt token")
        if any(t < 0 or t >= cfg.vocab for t in prompt):
            self._c_rejected.inc()
            raise MXNetError("serve: prompt token out of vocab range "
                             "[0, %d)" % cfg.vocab)
        if cfg.prompt_bucket_for(len(prompt)) is None:
            self._c_rejected.inc()
            raise MXNetError(
                "serve: prompt of %d tokens exceeds the top prompt "
                "bucket %d (MX_SERVE_DECODE_PROMPT_BUCKETS)"
                % (len(prompt), cfg.prompt_buckets[-1]))
        limit = cfg.max_tokens if max_new is None \
            else max(1, min(int(max_new), cfg.max_tokens))
        stop = cfg.eos_id if eos_id is None else int(eos_id)
        gen = _PendingGen(prompt, limit, stop, trace_ctx=trace_ctx)
        with self._cv:
            if len(self._q) >= self._cap:
                self._c_rejected.inc()
                raise Overloaded(
                    "serve: decode admission queue full (%d/%d; "
                    "MX_SERVE_QUEUE_CAP) - retry later or add replicas"
                    % (len(self._q), self._cap))
            self._q.append(gen)
            self._g_queue.set(len(self._q))
            self._cv.notify_all()
        self._c_requests.inc()
        self._c_requests_m.inc()
        return gen

    # -- the decode pump (mxlint hot-path roots) ----------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            idle = self._tick()
            if self._on_tick is not None:
                self._on_tick()
            if idle:
                with self._cv:
                    if not self._q:
                        self._cv.wait(timeout=0.01)
        # stop: refuse whatever is still queued so no handler thread is
        # left waiting on a generation nobody will advance
        with self._cv:
            leftover = list(self._q)
            self._q.clear()
            self._g_queue.set(0)
        with self._slot_lk:
            leftover += [g for g in self._slots if g is not None]
            self._slots = [None] * len(self._slots)
        for g in leftover:
            g._fail(MXNetError("serve: decode engine stopped"))

    def _tick(self) -> bool:
        """One step boundary: retire finished sequences, admit queued
        prefills into the freed slots, then ONE decode dispatch over
        the packed active set.  Returns True when there was nothing to
        do (idle)."""
        self._retire()
        self._admit()
        active = self._active()
        if not active:
            return True
        try:
            self._step(active)
        except BaseException as e:            # XLA failure: fail the set
            for _slot, g in active:
                g._fail(e)
        return False

    # -- locked slot/queue helpers ------------------------------------------
    # the ONLY direct touches of ``_slots`` / ``_q`` outside __init__ /
    # _loop / submit: the paged subclass schedules through these, so
    # the lock discipline lives (and is lint-attributed) in one class
    def _finished_slots(self) -> List[Tuple[int, _PendingGen]]:
        with self._slot_lk:
            return [(i, g) for i, g in enumerate(self._slots)
                    if g is not None and g.done()]

    def _free_slot_ids(self) -> List[int]:
        with self._slot_lk:
            return [i for i, g in enumerate(self._slots) if g is None]

    def _clear_slots(self, ids: Sequence[int]) -> None:
        with self._slot_lk:
            for i in ids:
                self._slots[i] = None

    def _bind_slot(self, slot: int, gen: _PendingGen) -> None:
        with self._slot_lk:
            self._slots[slot] = gen

    def _peek_queued(self) -> Optional[_PendingGen]:
        """Head of the admission queue without taking it (the pump is
        the only consumer, so a later pop returns the same request)."""
        with self._cv:
            return self._q[0] if self._q else None

    def _pop_queued(self) -> Optional[_PendingGen]:
        with self._cv:
            if not self._q:
                return None
            gen = self._q.popleft()
            self._g_queue.set(len(self._q))
            return gen

    def _retire(self) -> None:
        """Step boundary, phase ``kv_evict``: free the slots of
        completed sequences.  Eviction is bookkeeping — the pool pages
        stay allocated (flat HBM); the next prefill into the slot
        resets its length and overwrites from position 0, and stale
        entries beyond the new length are masked, never read."""
        done = self._finished_slots()
        if not done:
            return
        with _telemetry.phase("kv_evict"):
            self._clear_slots([i for i, _g in done])
        self._c_seqs.inc(len(done))
        self._c_seqs_m.inc(len(done))
        active = self.active_count()
        self._g_active.set(active)
        self._set_capacity_gauges(active)

    def _admit(self) -> None:
        """The slot allocator: fill free slots from the queue at the
        step boundary, one prefill dispatch each.  Request-level mode
        (the bench strawman) admits only when the whole previous batch
        has retired — exactly the behavior continuous batching
        exists to beat."""
        free = self._free_slot_ids()
        occupied = self._sv.config.slots - len(free)
        if self._mode == "request" and occupied:
            return
        while free:
            gen = self._pop_queued()
            if gen is None:
                break
            slot = free.pop(0)
            gen.slot = slot
            self._bind_slot(slot, gen)
            try:
                self._dispatch_prefill(gen, slot)
            except BaseException as e:
                self._clear_slots([slot])
                gen._fail(e)

    def _active(self) -> List[Tuple[int, _PendingGen]]:
        with self._slot_lk:
            return [(i, g) for i, g in enumerate(self._slots)
                    if g is not None and not g.done()]

    def _dispatch_prefill(self, gen: _PendingGen, slot: int) -> None:
        cfg = self._sv.config
        lp = cfg.prompt_bucket_for(len(gen.prompt))
        padded = _np.zeros(lp, _np.int32)
        padded[:len(gen.prompt)] = gen.prompt
        with _telemetry.phase("prefill") as span:
            if gen.trace_ctx is not None:
                span.event("request", req_trace=gen.trace_ctx[0],
                           req_span=gen.trace_ctx[1], slot=slot)
            t0 = self._sv.dispatch_prefill(slot, padded,
                                           len(gen.prompt))
        self._c_prefills.inc()
        active = self.active_count()
        self._g_active.set(active)
        self._set_capacity_gauges(active)
        self._hq_put(([gen], t0))

    def _step(self, active: List[Tuple[int, _PendingGen]]) -> None:
        """ONE decode dispatch: pack the active slots into the smallest
        covering bucket (padded lanes park on the scratch slot) — no
        host sync anywhere on this path; the emitted-token array goes
        to the harvester."""
        cfg = self._sv.config
        bucket = cfg.slot_bucket_for(len(active))
        ids = _np.full(bucket, cfg.slots, _np.int32)
        ids[:len(active)] = [slot for slot, _g in active]
        with _telemetry.phase("decode_step") as span:
            for _slot, g in active:
                if g.trace_ctx is not None:
                    span.event("request", req_trace=g.trace_ctx[0],
                               req_span=g.trace_ctx[1])
            out = self._sv.dispatch_step(ids)
        self._c_steps.inc()
        self._h_occ.observe(len(active))
        self._hq_put(([g for _slot, g in active], out))

    def _hq_put(self, item) -> None:
        """Bounded handoff to the harvester: the pump may run at most
        the queue depth ahead of the host-side token reads (that bound
        is what sizes the pool's overrun margin)."""
        while not self._stop.is_set():
            try:
                self._harvest_q.put(item, timeout=0.05)
                return
            except _queue.Full:
                continue

    # -- the harvester (the ONLY device->host reader) -----------------------
    def _harvest_loop(self) -> None:
        while not (self._stop.is_set() and self._harvest_q.empty()):
            self._harvest_once(block=True)

    def _harvest_once(self, block: bool = False) -> bool:
        """Read one dispatch's emitted tokens (the device sync lives
        HERE, overlapping the pump's next dispatch), append them to
        their generations, stamp per-token latency, flag EOS/limit
        completions for the next boundary's retire."""
        try:
            if block:
                gens, out = self._harvest_q.get(timeout=0.05)
            else:
                gens, out = self._harvest_q.get_nowait()
        except _queue.Empty:
            return False
        now = time.perf_counter()
        appended = 0
        if isinstance(out, tuple):
            # speculative verify result: (emitted (b, k), n_em (b,)) —
            # lane ``i`` contributed its first n_em[i] tokens this
            # window (ISSUE 20).  _append drops post-done tokens, so a
            # mid-window EOS/limit truncates here automatically.
            emitted, n_em = out
            em = _np.asarray(emitted)
            ne = _np.asarray(n_em).reshape(-1)
            for lane, g in enumerate(gens):
                for t in em[lane, :int(ne[lane])]:
                    did, finished = g._append(int(t), now)
                    if did:
                        appended += 1
                        self._h_token.observe(g.token_times[-1])
                    if finished:
                        break
        else:
            toks = _np.asarray(out).reshape(-1)
            for g, t in zip(gens, toks[:len(gens)]):
                did, _finished = g._append(int(t), now)
                if did:
                    appended += 1
                    self._h_token.observe(g.token_times[-1])
        if appended:
            self._c_tokens.inc(appended)
            self._c_tokens_m.inc(appended)
        return True

    # -- synchronous driving (tests, the dispatch-count budget) -------------
    def step_sync(self) -> bool:
        """One boundary + dispatch + synchronous harvest — the
        deterministic test face (requires ``autostart=False``: no
        pipeline lag, token counts exact).  Returns False once idle
        with an empty queue."""
        idle = self._tick()
        while self._harvest_once(block=False):
            pass
        with self._cv:
            empty = not self._q
        return not (idle and empty)

    def drain_sync(self, max_ticks: int = 10000) -> None:
        """step_sync until idle (tests)."""
        for _ in range(max_ticks):
            if not self.step_sync():
                return
        raise MXNetError("decode: drain_sync did not converge in %d "
                         "ticks" % max_ticks)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "DecodeBatcher":
        if not self._pump.is_alive():
            self._pump.start()
        if not self._harvester.is_alive():
            self._harvester.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._pump.is_alive():
            self._pump.join(timeout=timeout)
        if self._harvester.is_alive():
            self._harvester.join(timeout=timeout)


class _PagedSeq:
    """Host bookkeeping for one admitted PAGED session: its block
    table, the page references it holds, the remaining prefill-chunk
    train, and the full-page hashes to publish once the train has
    dispatched.  Pump-thread-only."""

    __slots__ = ("gen", "table", "held", "chunks", "publish", "t0")

    def __init__(self, gen, table, held, chunks, publish):
        self.gen = gen
        self.table = table          # np.int32 (pages_per_slot,)
        self.held = held            # page ids to release at retire
        self.chunks = chunks        # deque of pending chunk dispatches
        self.publish = publish      # [(chain_hash, page)] after train
        self.t0 = None              # emit chunk's first token (spec
        #                             engine: harvested only after the
        #                             draft-prefill sentinel)


class PagedDecodeBatcher(DecodeBatcher):
    """The paged continuous-batching engine (ISSUE 18): the flat
    pump's loop with three changes —

    * **Admission is bounded by pages, not slots.**  ``_admit`` plans
      each head-of-queue request against the
      :class:`~mxnet_tpu.serve.paging.PageAllocator`: hash-share full
      prompt pages from earlier sessions, allocate private pages for
      the rest of the worst-case extent, and queue the prefill-chunk
      train.  No pages -> the request WAITS (head-of-line; no
      half-allocation); free slots beyond page capacity are just
      cheap int32 rows, so configs can run slots >> the flat pool's
      count at the same heap bytes.

    * **Chunked prefill interleaves with decode.**  Each tick
      dispatches exactly ONE program: a pending prefill chunk and the
      decode step over the DECODING active set alternate
      (``_chunk_turn``), so a 10k-token admission never stalls
      in-flight generations for more than one chunk-step, and the
      1-dispatch-per-tick budget ``tools/dispatch_count.py`` pins
      holds with chunks counted as steps.

    * **Prefix reuse is plumbed, not special-cased.**  A full-coverage
      hash hit admits with a single CoW replay chunk (fork the donor's
      last page, recompute its final position, emit the first token);
      a partial hit prefills only the suffix chunks.  Decode never
      writes shared pages (generation positions land in private
      pages), and publication happens strictly after the owning
      chunks' dispatches, so sharing is invisible to correctness —
      paged greedy decode is token-identical to the flat engine and
      the oracle.

    Continuous-only: the request-level strawman stays on the flat
    engine.
    """

    def __init__(self, servable: PagedDecodeServable,
                 queue_cap: Optional[int] = None,
                 mode: str = "continuous", on_tick=None,
                 autostart: bool = True):
        if not isinstance(servable, PagedDecodeServable):
            raise MXNetError("PagedDecodeBatcher needs a "
                             "PagedDecodeServable")
        if mode != "continuous":
            raise MXNetError("the paged engine is continuous-only; "
                             "mode=%r belongs to the flat engine's "
                             "bench strawman" % (mode,))
        # pre-super wiring: the base __init__ publishes capacity gauges
        # through our override, which needs the allocator + extra
        # instruments in place
        self._sv = servable
        self._alloc = PageAllocator(servable.config.kv_pages)
        self._seqs: Dict[int, _PagedSeq] = {}
        self._chunk_turn = False
        self._chunk_rr = -1      # last slot whose chunk was served
        reg = _telemetry.registry
        self._c_chunks = reg.counter(
            "serve.decode.prefill_chunks",
            doc="prefill-chunk device dispatches (a prompt admits as a "
                "train of page-aligned chunks interleaved with decode "
                "steps)")
        self._c_shared = reg.counter(
            "serve.decode.shared_page_hits",
            doc="prompt pages adopted from the prefix hash table "
                "instead of prefilled (each one is a skipped chunk's "
                "worth of work and a page of HBM not allocated)")
        self._c_cow = reg.counter(
            "serve.decode.cow_forks",
            doc="copy-on-write page forks (full prompt-coverage prefix "
                "hits replaying only their final position)")
        self._g_free_pages = reg.gauge(
            "serve.decode.kv_free_pages",
            doc="KV heap pages currently allocatable (free + evictable "
                "cached prefix pages); the paged admission headroom "
                "the fleet plane reports")
        self._g_shared_saved = reg.gauge(
            "serve.decode.kv_shared_saved_bytes",
            doc="KV heap bytes prefix sharing is saving right now "
                "(extra references on hashed pages x page bytes)")
        super().__init__(servable, queue_cap=queue_cap, mode=mode,
                         on_tick=on_tick, autostart=autostart)

    # -- capacity surface ---------------------------------------------------
    def _set_capacity_gauges(self, active: int) -> None:
        slots = self._sv.config.slots
        self._g_occupancy.set(active / float(slots) if slots else 0.0)
        pb = self._sv.page_bytes()
        free = self._alloc.free_pages()
        self._g_headroom.set(free * pb)
        self._g_free_pages.set(free)
        self._g_shared_saved.set(self._alloc.shared_extra_refs() * pb)

    def page_stats(self) -> Dict:
        cfg = self._sv.config
        pb = self._sv.page_bytes()
        st = self._alloc.stats()
        return {
            "engine": "paged",
            "kv_pages": cfg.kv_pages,
            "kv_page_len": cfg.kv_page_len,
            "prefill_chunk": cfg.prefill_chunk,
            "prefix_share": cfg.prefix_share,
            "kv_free_pages": st["free"],
            "kv_cached_pages": st["cached"],
            "shared_hits": st["shared_hits"],
            "shared_saved_bytes":
                self._alloc.shared_extra_refs() * pb,
        }

    # -- the paged pump (mxlint hot-path roots) -----------------------------
    def _tick(self) -> bool:
        """One boundary, ONE dispatch: retire, admit (bookkeeping
        only), then EITHER the next pending prefill chunk OR the
        decode step — alternating while both kinds of work exist."""
        self._retire()
        self._admit()
        chunk_slot = self._next_chunk_slot()
        active = self._active()
        if chunk_slot is not None and (self._chunk_turn or not active):
            self._chunk_turn = False
            self._dispatch_chunk_for(chunk_slot)
            return False
        self._chunk_turn = True
        if not active:
            return chunk_slot is None
        try:
            self._step(active)
        except BaseException as e:            # XLA failure: fail the set
            for _slot, g in active:
                g._fail(e)
        return False

    def _retire(self) -> None:
        """Step boundary, phase ``kv_evict``: release finished
        sessions' page references.  A released page whose content is
        published under a prefix hash parks in the allocator's LRU
        cache — still adoptable — instead of freeing; the heap itself
        never reallocates (flat HBM)."""
        done = self._finished_slots()
        if not done:
            return
        with _telemetry.phase("kv_evict"):
            self._clear_slots([i for i, _g in done])
            for i, _g in done:
                seq = self._seqs.pop(i, None)
                if seq is not None:
                    for p in seq.held:
                        self._alloc.release(p)
        self._c_seqs.inc(len(done))
        self._c_seqs_m.inc(len(done))
        active = self.active_count()
        self._g_active.set(active)
        self._set_capacity_gauges(active)

    def _admit(self) -> None:
        """Admission bounded by PAGES: plan the head-of-queue request
        (prefix lookup + private-page allocation + chunk train) and
        take a slot only when its worst-case extent fits.  Pure
        bookkeeping — the chunks dispatch on later ticks."""
        while True:
            free = self._free_slot_ids()
            if not free:
                return
            gen = self._peek_queued()
            if gen is None:
                return
            plan = self._plan(gen)
            if plan is None:
                return            # head-of-line waits for free pages
            self._pop_queued()    # == gen: the pump is the only consumer
            slot = free[0]
            gen.slot = slot
            table, held, chunks, publish = plan
            self._bind_slot(slot, gen)
            self._seqs[slot] = _PagedSeq(gen, table, held, chunks,
                                         publish)
            active = self.active_count()
            self._g_active.set(active)
            self._set_capacity_gauges(active)

    def _plan(self, gen: _PendingGen):
        """Map one request onto the heap: shared prefix pages adopted
        by hash, private pages allocated for the rest of the
        worst-case extent, prefill chunks laid out page-aligned.
        Returns (table, held, chunks, publish) or None when the pages
        don't fit (nothing is retained on failure)."""
        cfg = self._sv.config
        pl = cfg.kv_page_len
        prompt = gen.prompt
        n = len(prompt)
        need_pages = min(
            cfg.pages_per_slot,
            -(-(n + gen.max_new + _OVERRUN_MARGIN) // pl))
        hashes = page_hashes(prompt, pl) if cfg.prefix_share else []
        shared: List[int] = []
        for h in hashes:
            p = self._alloc.lookup(h)
            if p is None:
                break
            shared.append(p)
        cow_src = None
        if shared and len(shared) * pl == n:
            # full coverage: fork the donor's last page (CoW) and
            # replay only the final position to emit the first token
            cow_src = shared.pop()
        priv = self._alloc.alloc(need_pages - len(shared))
        if priv is None:
            for p in shared:
                self._alloc.release(p)
            if cow_src is not None:
                self._alloc.release(cow_src)
            return None
        if shared or cow_src is not None:
            self._c_shared.inc(len(shared) +
                               (1 if cow_src is not None else 0))
        table = _np.zeros(cfg.pages_per_slot, _np.int32)
        table[:len(shared)] = shared
        table[len(shared):need_pages] = priv
        held = shared + priv
        if cow_src is not None:
            held.append(cow_src)   # keep the donor page live until
            #                        retire: its fork copy must not
            #                        race a reuse of the page
        chunks: deque = deque()
        publish: List[Tuple[int, int]] = []
        Lc = cfg.prefill_chunk
        if cow_src is not None:
            self._c_cow.inc()
            buf = _np.zeros(Lc, _np.int32)
            buf[0] = prompt[n - 1]
            chunks.append((buf, n - 1, 1, True, int(cow_src),
                           int(priv[0])))
        else:
            start0 = len(shared) * pl
            for s in range(start0, n, Lc):
                e = min(n, s + Lc)
                buf = _np.zeros(Lc, _np.int32)
                buf[:e - s] = prompt[s:e]
                chunks.append((buf, s, e - s, e == n, 0, 0))
            if cfg.prefix_share:
                for i in range(len(shared), n // pl):
                    publish.append((hashes[i], int(table[i])))
        return table, held, chunks, publish

    def _active(self) -> List[Tuple[int, _PendingGen]]:
        """The DECODING active set: sessions whose prefill-chunk train
        has fully dispatched (prefilling sessions are not packed into
        decode steps)."""
        return [(i, g) for i, g in super()._active()
                if not (i in self._seqs and self._seqs[i].chunks)]

    def _next_chunk_slot(self) -> Optional[int]:
        # _seqs entries are popped exactly when their slot clears
        # (_retire / _drop_seq, both on the pump), so a live entry
        # implies a live slot.  ROUND-ROBIN over chunk-pending
        # sessions: a 10k-token train must not starve a later
        # admission's one-chunk prefill of its first token.
        pending = sorted(i for i in self._seqs if self._seqs[i].chunks)
        if not pending:
            return None
        for i in pending:
            if i > self._chunk_rr:
                return i
        return pending[0]

    def _dispatch_chunk_for(self, slot: int) -> None:
        """ONE prefill-chunk dispatch.  The train's last chunk emits
        the first token (handed to the harvester like the flat
        prefill's) and triggers hash publication — strictly after the
        pages' writes are in the dispatch stream."""
        seq = self._seqs[slot]
        gen = seq.gen
        self._chunk_rr = slot
        chunk, start, nvalid, emit, cow_src, cow_dst = \
            seq.chunks.popleft()
        try:
            with _telemetry.phase("prefill") as span:
                if gen.trace_ctx is not None:
                    span.event("request", req_trace=gen.trace_ctx[0],
                               req_span=gen.trace_ctx[1], slot=slot)
                t0 = self._sv.dispatch_chunk(slot, seq.table, chunk,
                                             start, nvalid, emit,
                                             cow_src, cow_dst)
        except BaseException as e:
            self._drop_seq(slot)
            gen._fail(e)
            return
        self._c_chunks.inc()
        if not seq.chunks:
            # train complete = the flat engine's "prefill" unit
            self._c_prefills.inc()
            for h, page in seq.publish:
                self._alloc.publish(h, page)
            seq.publish = []
            active = self.active_count()
            self._g_active.set(active)
            self._set_capacity_gauges(active)
            self._hq_put(([gen], t0))

    def _drop_seq(self, slot: int) -> None:
        self._clear_slots([slot])
        seq = self._seqs.pop(slot, None)
        if seq is not None:
            for p in seq.held:
                self._alloc.release(p)

    def _dispatch_prefill(self, gen: _PendingGen, slot: int) -> None:
        raise MXNetError("paged engine prefills via chunk trains, "
                         "never the monolithic prefill")

    def _step(self, active: List[Tuple[int, _PendingGen]]) -> None:
        """ONE decode dispatch over the packed DECODING set, each lane
        carrying its block-table row (padded lanes: all-zero rows ->
        the scratch page)."""
        cfg = self._sv.config
        bucket = cfg.slot_bucket_for(len(active))
        ids = _np.full(bucket, cfg.slots, _np.int32)
        ids[:len(active)] = [slot for slot, _g in active]
        tbls = _np.zeros((bucket, cfg.pages_per_slot), _np.int32)
        for lane, (slot, _g) in enumerate(active):
            tbls[lane] = self._seqs[slot].table
        with _telemetry.phase("decode_step") as span:
            for _slot, g in active:
                if g.trace_ctx is not None:
                    span.event("request", req_trace=g.trace_ctx[0],
                               req_span=g.trace_ctx[1])
            out = self._sv.dispatch_step(ids, tbls)
        self._c_steps.inc()
        self._h_occ.observe(len(active))
        self._hq_put(([g for _slot, g in active], out))


class SpeculativeDecodeBatcher(PagedDecodeBatcher):
    """The SPECULATIVE paged engine (ISSUE 20): the paged pump, but
    decode advances in WINDOWS of ``spec_k`` tokens —

    * **k draft ticks + 1 verify tick per window.**  The window's
      active set freezes at the first draft tick; each draft tick is
      one dispatch of the co-hosted draft servable writing its
      proposal into the device-resident proposals buffer; the verify
      tick is ONE target dispatch over all k+1 window positions of
      every lane (multi-position paged attention), which accepts the
      longest agreeing prefix, corrects the next token from the
      target's own argmax, and rewrites the draft's (token, length)
      state in-program — the whole window is a device-side chain with
      zero host syncs, and the 1-dispatch-per-tick budget holds.

    * **Output is bit-identical to plain paged greedy decode.**  Every
      emitted token is the target's own argmax under the committed
      prefix (the draft only chooses how many of them one dispatch
      yields), so correctness never depends on draft quality — a
      worthless draft just degrades throughput to ~1 token per 2
      dispatches, a draft-friendly model approaches k tokens per k+1
      (cheap) dispatches.

    * **Admission grows a draft-prefill sentinel.**  A session's
      prefill-chunk train ends with one extra dispatch that prefills
      the DRAFT's KV pool and adopts the target's emitted first token
      (read-only input -> XLA orders it after the emit chunk); the
      first token is harvested only once the sentinel has dispatched,
      so a session never enters a window with a cold draft.
    """

    def __init__(self, servable: PagedDecodeServable,
                 draft: DraftDecodeServable,
                 queue_cap: Optional[int] = None,
                 mode: str = "continuous", on_tick=None,
                 autostart: bool = True):
        if not isinstance(draft, DraftDecodeServable):
            raise MXNetError("SpeculativeDecodeBatcher needs a "
                             "DraftDecodeServable draft")
        tcfg = servable.config
        dcfg = draft.config
        if (tcfg.slots != dcfg.slots or tcfg.vocab != dcfg.vocab
                or tcfg.prompt_buckets != dcfg.prompt_buckets
                or tcfg.max_tokens != dcfg.max_tokens
                or tcfg.spec_k != dcfg.spec_k):
            raise MXNetError(
                "speculative decode: draft/target geometry mismatch "
                "(slots, vocab, prompt buckets, max_tokens and spec_k "
                "must agree; got target=%r draft=%r)" % (tcfg, dcfg))
        self._draft = draft
        self._win_active: Optional[List[Tuple[int, _PendingGen]]] = \
            None
        self._win_step = 0
        reg = _telemetry.registry
        self._c_draft_steps = reg.counter(
            "serve.decode.draft_steps",
            doc="draft-model decode dispatches (spec_k per speculative "
                "window)")
        self._c_draft_prefills = reg.counter(
            "serve.decode.draft_prefills",
            doc="draft KV prefill dispatches (the sentinel ending each "
                "admission's chunk train)")
        self._c_windows = reg.counter(
            "serve.decode.spec_windows",
            doc="speculative verify dispatches (each commits 1..spec_k "
                "tokens for every window lane)")
        # warm everything BEFORE the pump threads exist: target buckets
        # + chunk program (base warm), draft buckets + prefills, and
        # the verify bucket table (scratch lanes only — the programs
        # park the scratch slot themselves)
        if not servable.warmed:
            servable.warm()
        if not draft.warmed:
            draft.warm()
        for b in tcfg.slot_buckets:
            servable.dispatch_verify(
                draft, _np.full(b, tcfg.slots, _np.int32),
                _np.zeros((b, tcfg.pages_per_slot), _np.int32))
        jax.block_until_ready(servable._state["k"])
        super().__init__(servable, queue_cap=queue_cap, mode=mode,
                         on_tick=on_tick, autostart=autostart)

    @property
    def draft(self) -> DraftDecodeServable:
        return self._draft

    def page_stats(self) -> Dict:
        st = super().page_stats()
        st["engine"] = "speculative"
        st["spec_k"] = self._sv.config.spec_k
        st["draft_model"] = self._draft.name
        st["draft_layers"] = self._draft.config.layers
        return st

    # -- the speculative pump (mxlint hot-path roots) -----------------------
    def _tick(self) -> bool:
        """One boundary, ONE dispatch.  Mid-window ticks only advance
        the window (the active set is frozen; retire/admit/chunks wait
        for the boundary); boundary ticks run the paged engine's
        retire/admit/chunk alternation and open the next window."""
        if self._win_active is not None:
            self._window_tick()
            return False
        self._retire()
        self._admit()
        chunk_slot = self._next_chunk_slot()
        active = self._active()
        if chunk_slot is not None and (self._chunk_turn or not active):
            self._chunk_turn = False
            self._dispatch_chunk_for(chunk_slot)
            return False
        self._chunk_turn = True
        if not active:
            return chunk_slot is None
        self._win_active = active
        self._win_step = 0
        self._window_tick()
        return False

    def _window_tick(self) -> None:
        """One dispatch of the current window: draft step ``_win_step``
        while < spec_k, else the verify dispatch that closes the
        window and hands (emitted, n_em) to the harvester."""
        active = self._win_active
        cfg = self._sv.config
        bucket = cfg.slot_bucket_for(len(active))
        ids = _np.full(bucket, cfg.slots, _np.int32)
        ids[:len(active)] = [slot for slot, _g in active]
        try:
            if self._win_step < cfg.spec_k:
                with _telemetry.phase("draft_step"):
                    self._draft.dispatch_step(ids, self._win_step)
                self._c_draft_steps.inc()
                self._win_step += 1
                return
            tbls = _np.zeros((bucket, cfg.pages_per_slot), _np.int32)
            for lane, (slot, _g) in enumerate(active):
                tbls[lane] = self._seqs[slot].table
            with _telemetry.phase("decode_step") as span:
                for _slot, g in active:
                    if g.trace_ctx is not None:
                        span.event("request", req_trace=g.trace_ctx[0],
                                   req_span=g.trace_ctx[1])
                out = self._sv.dispatch_verify(self._draft, ids, tbls)
        except BaseException as e:            # XLA failure: fail the set
            self._win_active = None
            self._win_step = 0
            for _slot, g in active:
                g._fail(e)
            return
        self._c_steps.inc()
        self._c_windows.inc()
        self._h_occ.observe(len(active))
        self._win_active = None
        self._win_step = 0
        self._hq_put(([g for _slot, g in active], out))

    # -- admission: chunk train + draft-prefill sentinel --------------------
    def _plan(self, gen: _PendingGen):
        plan = super()._plan(gen)
        if plan is None:
            return None
        table, held, chunks, publish = plan
        # sentinel: chunk=None marks the draft prefill ending the train
        chunks.append((None, 0, len(gen.prompt), False, 0, 0))
        return table, held, chunks, publish

    def _dispatch_chunk_for(self, slot: int) -> None:
        """ONE train dispatch: a target prefill chunk, or the
        draft-prefill sentinel that completes the train.  The emit
        chunk's first token parks on the session (``seq.t0``) and is
        harvested only when the sentinel has dispatched — the window
        invariant needs the draft warm before the first decode."""
        seq = self._seqs[slot]
        gen = seq.gen
        self._chunk_rr = slot
        chunk, start, nvalid, emit, cow_src, cow_dst = \
            seq.chunks.popleft()
        try:
            with _telemetry.phase("prefill") as span:
                if gen.trace_ctx is not None:
                    span.event("request", req_trace=gen.trace_ctx[0],
                               req_span=gen.trace_ctx[1], slot=slot)
                if chunk is None:
                    lp = self._draft.config.prompt_bucket_for(
                        len(gen.prompt))
                    padded = _np.zeros(lp, _np.int32)
                    padded[:len(gen.prompt)] = gen.prompt
                    self._draft.dispatch_prefill(
                        slot, padded, len(gen.prompt),
                        tgt_tokens=self._sv._state["tok"])
                    self._c_draft_prefills.inc()
                else:
                    t0 = self._sv.dispatch_chunk(slot, seq.table,
                                                 chunk, start, nvalid,
                                                 emit, cow_src,
                                                 cow_dst)
                    self._c_chunks.inc()
                    if emit:
                        seq.t0 = t0
        except BaseException as e:
            self._drop_seq(slot)
            gen._fail(e)
            return
        if not seq.chunks:
            # train complete = the flat engine's "prefill" unit
            self._c_prefills.inc()
            for h, page in seq.publish:
                self._alloc.publish(h, page)
            seq.publish = []
            active = self.active_count()
            self._g_active.set(active)
            self._set_capacity_gauges(active)
            self._hq_put(([gen], seq.t0))


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the decode engine's declared proofs.
# ``serve.decode`` covers every slot-bucket decode program:
#   * donation — all four KV-state leaves (k/v pools, token and length
#     arrays) alias input->output in the lowered executable, the static
#     form of "HBM stays flat across decode steps";
#   * trace closure — every active-set size 1..slots resolves to a
#     compiled slot bucket (zero serve-time retraces as a theorem).
# ``serve.prefill`` does the same over the prompt-length bucket set,
# with over-bucket prompts provably rejected at admission (resolve ->
# None).  Builders run only inside the contracts verifier.
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.lru_cache(maxsize=1)
def _decode_contract_built():
    from ..programs import ContractCase, ContractClosure
    cfg = DecodeConfig()
    sv = DecodeServable(config=cfg)
    params_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for k, v in sv.params.items()}
    pool_abs = jax.ShapeDtypeStruct(
        (cfg.layers, cfg.slots + 1, cfg.max_len, cfg.heads,
         cfg.head_dim), jnp.float32)
    tok_abs = jax.ShapeDtypeStruct((cfg.slots + 1,), jnp.int32)
    scalar_abs = jax.ShapeDtypeStruct((), jnp.int32)

    def step_args(bucket):
        return (params_abs, pool_abs, pool_abs, tok_abs, tok_abs,
                jax.ShapeDtypeStruct((bucket,), jnp.int32))

    def prefill_args(lp):
        return (params_abs, pool_abs, pool_abs, tok_abs, tok_abs,
                scalar_abs, jax.ShapeDtypeStruct((lp,), jnp.int32),
                scalar_abs)

    step_cases = [ContractCase("serve.decode.step.s%d" % b,
                               step_args(b), label="s%d" % b,
                               target=sv.step_program(b))
                  for b in cfg.slot_buckets]
    prefill_cases = [ContractCase("serve.decode.prefill.p%d" % lp,
                                  prefill_args(lp), label="p%d" % lp,
                                  target=sv.prefill_program(lp))
                     for lp in cfg.prompt_buckets]

    def resolve_step(n):
        # every active-set size packs to its covering slot bucket
        return step_args(cfg.slot_bucket_for(int(n)))

    def resolve_prefill(n):
        # prompts pad to their bucket; over-bucket prompts are refused
        # at admission (never reach a jit)
        lp = cfg.prompt_bucket_for(int(n))
        return None if lp is None else prefill_args(lp)

    step_closure = ContractClosure(range(1, cfg.slots + 1),
                                   resolve_step)
    prefill_closure = ContractClosure(
        range(1, cfg.prompt_buckets[-1] + 3), resolve_prefill)
    return step_cases, step_closure, prefill_cases, prefill_closure


@_functools.lru_cache(maxsize=1)
def _paged_contract_built():
    """The paged engine's contract cases/closures (ISSUE 18):

    * ``serve.paged.decode`` — every slot-bucket step program with its
      block-table argument; heap donation proven; closed over active
      set sizes 1..slots.
    * ``serve.paged.prefill`` — THE chunk program: one signature
      (chunk length) serves every admitted prompt length as a chunk
      train, CoW folds into the same signature, so the closure maps
      ANY prompt length 1..top-bucket to the single compiled case —
      zero serve-time retraces as a theorem with a one-program prefill
      table.
    """
    from ..programs import ContractCase, ContractClosure
    cfg = DecodeConfig()
    sv = PagedDecodeServable(config=cfg)
    params_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for k, v in sv.params.items()}
    heap_abs = jax.ShapeDtypeStruct(
        (cfg.layers, cfg.kv_pages, cfg.kv_page_len, cfg.heads,
         cfg.head_dim), jnp.float32)
    tok_abs = jax.ShapeDtypeStruct((cfg.slots + 1,), jnp.int32)
    scalar_abs = jax.ShapeDtypeStruct((), jnp.int32)
    tbl_abs = jax.ShapeDtypeStruct((cfg.pages_per_slot,), jnp.int32)

    def step_args(bucket):
        return (params_abs, heap_abs, heap_abs, tok_abs, tok_abs,
                jax.ShapeDtypeStruct((bucket,), jnp.int32),
                jax.ShapeDtypeStruct((bucket, cfg.pages_per_slot),
                                     jnp.int32))

    chunk_args = (params_abs, heap_abs, heap_abs, tok_abs, tok_abs,
                  scalar_abs, tbl_abs,
                  jax.ShapeDtypeStruct((cfg.prefill_chunk,),
                                       jnp.int32),
                  scalar_abs, scalar_abs, scalar_abs, scalar_abs,
                  scalar_abs)

    step_cases = [ContractCase("serve.decode.paged.step.s%d" % b,
                               step_args(b), label="s%d" % b,
                               target=sv.step_program(b))
                  for b in cfg.slot_buckets]
    chunk_cases = [ContractCase(
        "serve.decode.paged.prefill.c%d" % cfg.prefill_chunk,
        chunk_args, label="c%d" % cfg.prefill_chunk,
        target=sv.chunk_program())]

    def resolve_step(n):
        return step_args(cfg.slot_bucket_for(int(n)))

    def resolve_chunk(n):
        # ANY admitted prompt length prefills as a train of the ONE
        # chunk signature; over-bucket prompts are refused at
        # admission (never reach a jit)
        if cfg.prompt_bucket_for(int(n)) is None:
            return None
        return chunk_args

    step_closure = ContractClosure(range(1, cfg.slots + 1),
                                   resolve_step)
    chunk_closure = ContractClosure(
        range(1, cfg.prompt_buckets[-1] + 3), resolve_chunk)
    return step_cases, step_closure, chunk_cases, chunk_closure


@_functools.lru_cache(maxsize=1)
def _spec_contract_built():
    """The speculative engine's contract cases/closures (ISSUE 20):

    * ``serve.spec.draft`` — the draft-step slot-bucket table: the
      draft's KV pool, token/length arrays AND the proposals buffer
      all donate in place; the window column is a traced scalar, so
      ONE program per bucket is closed over every k — the closure maps
      any (active-set size, window column) to its compiled case.
    * ``serve.spec.draft.prefill`` — the draft-prefill sentinel per
      prompt bucket (target token array read-only; draft state
      donated).
    * ``serve.spec.verify`` — the verify slot-bucket table: BOTH KV
      states' mutable leaves (target heap + token/length, draft
      token/length) donate in place, proposals read-only; closed over
      every active-set size 1..slots.
    """
    from ..programs import ContractCase, ContractClosure
    cfg = DecodeConfig()
    tparams, dcfg, dparams = demo_spec_pair(cfg)
    sv = PagedDecodeServable(params=tparams, config=cfg)
    draft = DraftDecodeServable(params=dparams, config=dcfg)
    tparams_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in sv.params.items()}
    dparams_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in draft.params.items()}
    heap_abs = jax.ShapeDtypeStruct(
        (cfg.layers, cfg.kv_pages, cfg.kv_page_len, cfg.heads,
         cfg.head_dim), jnp.float32)
    dpool_abs = jax.ShapeDtypeStruct(
        (dcfg.layers, dcfg.slots + 1, dcfg.max_len, dcfg.heads,
         dcfg.head_dim), jnp.float32)
    tok_abs = jax.ShapeDtypeStruct((cfg.slots + 1,), jnp.int32)
    props_abs = jax.ShapeDtypeStruct((cfg.slots + 1, cfg.spec_k),
                                     jnp.int32)
    scalar_abs = jax.ShapeDtypeStruct((), jnp.int32)

    def draft_args(bucket):
        return (dparams_abs, dpool_abs, dpool_abs, tok_abs, tok_abs,
                props_abs, jax.ShapeDtypeStruct((bucket,), jnp.int32),
                scalar_abs)

    def draft_prefill_args(lp):
        return (dparams_abs, dpool_abs, dpool_abs, tok_abs, tok_abs,
                tok_abs, scalar_abs,
                jax.ShapeDtypeStruct((lp,), jnp.int32), scalar_abs)

    def verify_args(bucket):
        return (tparams_abs, heap_abs, heap_abs, tok_abs, tok_abs,
                tok_abs, tok_abs, props_abs,
                jax.ShapeDtypeStruct((bucket,), jnp.int32),
                jax.ShapeDtypeStruct((bucket, cfg.pages_per_slot),
                                     jnp.int32))

    draft_cases = [ContractCase("serve.decode.draft.s%d" % b,
                                draft_args(b), label="s%d" % b,
                                target=draft.step_program(b))
                   for b in dcfg.slot_buckets]
    dp_cases = [ContractCase("serve.decode.draft.prefill.p%d" % lp,
                             draft_prefill_args(lp), label="p%d" % lp,
                             target=draft.prefill_program(lp))
                for lp in dcfg.prompt_buckets]
    verify_cases = [ContractCase(
        "serve.decode.verify.k%d.s%d" % (cfg.spec_k, b),
        verify_args(b), label="k%d.s%d" % (cfg.spec_k, b),
        target=sv.verify_program(b))
        for b in cfg.slot_buckets]

    def resolve_draft(point):
        # (active-set size, window column): any size packs to its
        # covering bucket; every column 0..spec_k-1 rides the SAME
        # program (the column is traced data, not a signature)
        n, col = point
        if col < 0 or col >= cfg.spec_k:
            return None
        return draft_args(cfg.slot_bucket_for(int(n)))

    def resolve_dp(n):
        lp = cfg.prompt_bucket_for(int(n))
        return None if lp is None else draft_prefill_args(lp)

    def resolve_verify(n):
        return verify_args(cfg.slot_bucket_for(int(n)))

    draft_points = [(n, col) for n in range(1, cfg.slots + 1)
                    for col in range(cfg.spec_k)]
    draft_closure = ContractClosure(draft_points, resolve_draft)
    dp_closure = ContractClosure(
        range(1, cfg.prompt_buckets[-1] + 3), resolve_dp)
    verify_closure = ContractClosure(range(1, cfg.slots + 1),
                                     resolve_verify)
    return (draft_cases, draft_closure, dp_cases, dp_closure,
            verify_cases, verify_closure)


def _declare_decode_contracts():
    from ..programs import declare_contract
    declare_contract(
        "serve.decode", lambda: _decode_contract_built()[0],
        donate_argnums=(1, 2, 3, 4),
        temp_budget_bytes=8 << 20,
        closure=lambda: _decode_contract_built()[1],
        description="decode-step slot-bucket table: KV pool pages + "
                    "per-slot token/length arrays donate in place "
                    "(flat HBM across steps); trace signatures closed "
                    "over every active-set size 1..slots")
    declare_contract(
        "serve.prefill", lambda: _decode_contract_built()[2],
        donate_argnums=(1, 2, 3, 4),
        temp_budget_bytes=8 << 20,
        closure=lambda: _decode_contract_built()[3],
        description="prefill prompt-bucket table: same donated KV "
                    "state; trace signatures closed over the "
                    "MX_SERVE_DECODE_PROMPT_BUCKETS admission set "
                    "(over-bucket prompts provably rejected)")
    declare_contract(
        "serve.paged.decode", lambda: _paged_contract_built()[0],
        donate_argnums=(1, 2, 3, 4),
        temp_budget_bytes=8 << 20,
        closure=lambda: _paged_contract_built()[1],
        description="paged decode-step slot-bucket table (ISSUE 18): "
                    "the shared KV page heap + token/length arrays "
                    "donate in place (flat HBM across steps, one heap "
                    "for every session); trace signatures closed over "
                    "every active-set size 1..slots with per-lane "
                    "block tables")
    declare_contract(
        "serve.paged.prefill", lambda: _paged_contract_built()[2],
        donate_argnums=(1, 2, 3, 4),
        temp_budget_bytes=8 << 20,
        closure=lambda: _paged_contract_built()[3],
        description="paged prefill-chunk program (ISSUE 18): ONE "
                    "signature — chunk length — serves every admitted "
                    "prompt as a page-aligned chunk train, with the "
                    "copy-on-write page fork folded into the same "
                    "signature; heap donation proven, closure maps "
                    "any prompt length to the single compiled case")
    declare_contract(
        "serve.spec.draft", lambda: _spec_contract_built()[0],
        donate_argnums=(1, 2, 3, 4, 5),
        temp_budget_bytes=8 << 20,
        closure=lambda: _spec_contract_built()[1],
        description="speculative DRAFT step table (ISSUE 20): the "
                    "draft's KV pool, token/length arrays and the "
                    "device-resident proposals buffer donate in "
                    "place; the window column is traced data, so the "
                    "table is closed over every (active-set size, "
                    "window column 0..spec_k-1) pair with one program "
                    "per slot bucket")
    declare_contract(
        "serve.spec.draft.prefill", lambda: _spec_contract_built()[2],
        donate_argnums=(1, 2, 3, 4),
        temp_budget_bytes=8 << 20,
        closure=lambda: _spec_contract_built()[3],
        description="speculative draft-prefill sentinel (ISSUE 20): "
                    "draft KV state donated, the TARGET's token array "
                    "read-only (the adopted first token also orders "
                    "the sentinel after the emit chunk); closed over "
                    "the prompt-bucket admission set")
    declare_contract(
        "serve.spec.verify", lambda: _spec_contract_built()[4],
        donate_argnums=(1, 2, 3, 4, 5, 6),
        temp_budget_bytes=8 << 20,
        closure=lambda: _spec_contract_built()[5],
        description="speculative VERIFY table (ISSUE 20): one "
                    "dispatch covers all spec_k+1 window positions — "
                    "target heap + token/length AND the draft's "
                    "token/length donate in place (both models' "
                    "states stay flat and in lockstep), proposals "
                    "read-only; closed over every active-set size "
                    "1..slots")


_declare_decode_contracts()
