"""Autoregressive decode serving: device-resident KV cache + continuous
batching (ISSUE 15 tentpole).

Until this module every served request was one fixed-shape forward; the
sequence-generation traffic that dominates real serving (one prompt in,
many tokens out) would have held its whole micro-batch hostage for the
longest generation.  This is the decode engine that opens it, built on
the same disciplines the rest of ``serve/`` runs on:

* **Split prefill / decode, both AOT-bucketed.**  Prefill (process the
  whole prompt, fill the KV pages, emit the first token) compiles one
  program per PROMPT-LENGTH bucket (``MX_SERVE_DECODE_PROMPT_BUCKETS``);
  decode (one token for every active sequence) compiles one program per
  ACTIVE-SLOT-COUNT bucket (powers of two up to
  ``MX_SERVE_DECODE_SLOTS``).  Both register through
  ``programs.register_program`` so the compile cache, census and
  zero-retrace accounting carry over unchanged — after
  :meth:`DecodeServable.warm` serve time is pure cached-executable
  dispatch.

* **Device-resident KV pool, donated every step.**  K/V pages for every
  slot live in two fixed arrays ``(layers, slots+1, max_len, heads,
  head_dim)`` (+1 = the scratch slot padded decode lanes park on),
  owner-tagged ``kv_cache`` in ``programs.buffer_census()`` and donated
  through every prefill/decode dispatch — the pool is allocated once
  and HBM stays flat across any number of generations.  Retiring a
  sequence "evicts" its pages by bookkeeping alone: the slot's length
  resets on reuse and stale entries beyond it are masked, never read.

* **Continuous batching.**  The decode pump packs ALL active sequences
  into the smallest covering slot bucket each step (ONE device dispatch
  regardless of the active count), and at step boundaries retires
  finished sequences and admits queued prefills into the freed slots —
  a long generation never blocks a short one.  Sampled tokens stay
  device-resident between steps (the program writes the next input
  token into a donated pool-shaped array), so the pump never syncs the
  host; a separate harvester thread reads each step's emitted tokens
  asynchronously, stamps per-token latency and flags EOS/limit
  completions for the next boundary.  ``mode="request"`` is the
  request-level strawman (admit a batch, run it to completion) the
  bench lane compares against.

Slot state machine (one slot)::

    FREE --admit/prefill--> ACTIVE --harvest flags done--> FINISHED
      ^                                                       |
      +------------- retire at step boundary (kv_evict) ------+

Concurrency/lint contract: ``DecodeBatcher._tick`` / ``_admit`` /
``_retire`` / ``_step`` / ``_dispatch_prefill`` and the
``DecodeServable`` dispatch path are mxlint hot-path roots — no host
sync may land between state dequeue and device dispatch (the
tests/test_mxlint.py reinjection test proves a blocking host read there
trips the rule).  The device→host token read lives ONLY in the
harvester thread (``_harvest_once``).  Result/stream wait budgets ride
``mxnet_tpu.fault.Deadline`` (virtual-time aware, like the
micro-batcher's coalescing window); the pump's idle wait is a plain
short condition poll.

Telemetry: ``prefill`` / ``decode_step`` / ``kv_evict`` phases land in
``step_phase_seconds``; ``serve.decode.token_seconds`` histograms
per-token latency (first token = submit→harvest incl. queue + prefill,
then inter-token gaps); counters ``serve.decode.requests`` / ``tokens``
/ ``steps`` / ``prefills`` / ``sequences`` / ``rejected`` and the
``serve.decode.occupancy`` active-slots histogram drive the bench lane
and the fleet plane.
"""
from __future__ import annotations

import functools
import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import telemetry as _telemetry
from ..ops.attention import attention_core, cached_attention
from .batcher import Overloaded, result_timeout as _result_timeout

__all__ = ["DecodeConfig", "DecodeServable", "DecodeBatcher",
           "demo_lm_params", "reference_generate"]

# extra pool positions past prompt+generation capacity: the pump may
# run a few steps ahead of the harvester (bounded by the harvest queue)
# before a finished sequence is retired, and those overrun writes must
# still land inside the slot's pages
_OVERRUN_MARGIN = 8


class DecodeConfig:
    """Decode-engine geometry: model dims + pool/bucket layout.

    Slot buckets are the powers of two up to ``slots`` (plus ``slots``
    itself) — every active-set size packs into the smallest covering
    bucket, so the decode program table is closed over 1..slots.
    ``max_len`` is the per-slot page capacity: top prompt bucket +
    ``max_tokens`` + the pipeline overrun margin, rounded up to whole
    ``page``-sized pages.
    """

    def __init__(self, vocab: int = 48, dim: int = 32, heads: int = 4,
                 layers: int = 2, slots: Optional[int] = None,
                 max_tokens: Optional[int] = None,
                 page: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, seed: int = 7):
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.heads = int(heads)
        if self.dim % self.heads:
            raise MXNetError("decode: dim %d must divide by heads %d"
                             % (self.dim, self.heads))
        self.head_dim = self.dim // self.heads
        self.layers = int(layers)
        self.slots = int(slots if slots is not None else
                         get_env("MX_SERVE_DECODE_SLOTS", 8, int))
        if self.slots < 1:
            raise MXNetError("decode: need >= 1 slot")
        self.max_tokens = int(max_tokens if max_tokens is not None else
                              get_env("MX_SERVE_DECODE_MAX_TOKENS", 32,
                                      int))
        self.page = int(page if page is not None else
                        get_env("MX_SERVE_DECODE_PAGE", 16, int))
        if prompt_buckets is None:
            raw = get_env("MX_SERVE_DECODE_PROMPT_BUCKETS") or "4,8,16"
            prompt_buckets = [int(p) for p in str(raw).split(",")
                              if p.strip()]
        self.prompt_buckets: Tuple[int, ...] = \
            tuple(sorted({int(b) for b in prompt_buckets}))
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise MXNetError("decode: prompt buckets must be positive, "
                             "got %r" % (prompt_buckets,))
        sizes = set()
        b = 1
        while b < self.slots:
            sizes.add(b)
            b *= 2
        sizes.add(self.slots)
        self.slot_buckets: Tuple[int, ...] = tuple(sorted(sizes))
        self.eos_id = None if eos_id is None else int(eos_id)
        need = self.prompt_buckets[-1] + self.max_tokens + _OVERRUN_MARGIN
        self.pages = -(-need // self.page)
        self.max_len = self.pages * self.page
        self.seed = int(seed)

    def prompt_bucket_for(self, n: int) -> Optional[int]:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return None

    def slot_bucket_for(self, n: int) -> int:
        for b in self.slot_buckets:
            if b >= n:
                return b
        return self.slot_buckets[-1]

    def __repr__(self):
        return ("DecodeConfig(vocab=%d, dim=%d, heads=%d, layers=%d, "
                "slots=%d, max_tokens=%d, page=%d, max_len=%d)"
                % (self.vocab, self.dim, self.heads, self.layers,
                   self.slots, self.max_tokens, self.page, self.max_len))


def demo_lm_params(config: Optional[DecodeConfig] = None
                   ) -> Dict[str, jnp.ndarray]:
    """Seeded deterministic demo LM parameters (the decode analogue of
    ``serve.demo.demo_block``): both sides of a chaos run build these
    independently, so generated-token *correctness* is assertable
    across processes.  The unembedding is scaled up so greedy-argmax
    margins are decisive — bucket packing must not flip a token on a
    float whisker."""
    cfg = config or DecodeConfig()
    rs = _np.random.RandomState(cfg.seed)
    d = cfg.dim

    def mat(rows, cols, scale):
        return jnp.asarray(rs.randn(rows, cols).astype(_np.float32)
                           * scale)

    params: Dict[str, jnp.ndarray] = {
        "emb": mat(cfg.vocab, d, 1.0),
        "unemb": mat(d, cfg.vocab, 4.0 / (d ** 0.5)),
    }
    for l in range(cfg.layers):
        for name in ("wq", "wk", "wv", "wo"):
            params["l%d.%s" % (l, name)] = mat(d, d, 1.0 / (d ** 0.5))
        params["l%d.w1" % l] = mat(d, 2 * d, 1.0 / (d ** 0.5))
        params["l%d.w2" % l] = mat(2 * d, d, 1.0 / ((2 * d) ** 0.5))
    return params


# ---------------------------------------------------------------------------
# traced program bodies (pure; jit-purity applies via register_program)
# ---------------------------------------------------------------------------


def _block_mlp(params, l, x):
    h = jnp.maximum(x @ params["l%d.w1" % l], 0.0)
    return x + h @ params["l%d.w2" % l]


def _decode_body(cfg: DecodeConfig, params, k_pool, v_pool, tokens,
                 lengths, slot_ids):
    """One decode step over the packed active set.

    ``k_pool``/``v_pool``: (L, S+1, P, H, Dh) donated; ``tokens`` /
    ``lengths``: (S+1,) int32 donated (tokens = each slot's NEXT input
    token, device-resident so the pump never reads the host between
    steps); ``slot_ids``: (b,) int32, padded lanes carry the scratch
    index S.  Returns the four state arrays (aliased in place via
    donation) plus the (b,) sampled tokens for the harvester.
    """
    tok = tokens[slot_ids]                              # (b,)
    lens = lengths[slot_ids]                            # (b,)
    x = params["emb"][tok]                              # (b, D)
    b = x.shape[0]
    pos = lens                     # this token's KV write position
    for l in range(cfg.layers):
        k_new = (x @ params["l%d.wk" % l]).reshape(
            b, cfg.heads, cfg.head_dim)
        v_new = (x @ params["l%d.wv" % l]).reshape(
            b, cfg.heads, cfg.head_dim)
        k_pool = k_pool.at[l, slot_ids, pos].set(k_new)
        v_pool = v_pool.at[l, slot_ids, pos].set(v_new)
        q = (x @ params["l%d.wq" % l]).reshape(b, cfg.heads,
                                               cfg.head_dim)
        att = cached_attention(q, k_pool[l, slot_ids],
                               v_pool[l, slot_ids], lens + 1)
        x = x + att.reshape(b, cfg.dim) @ params["l%d.wo" % l]
        x = _block_mlp(params, l, x)
    logits = x @ params["unemb"]                        # (b, V)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = tokens.at[slot_ids].set(nxt)
    lengths = lengths.at[slot_ids].set(lens + 1)
    # park the scratch slot: padded lanes read/write it every step, so
    # its bookkeeping must reset or its fake length would creep past
    # the pool extent
    tokens = tokens.at[cfg.slots].set(0)
    lengths = lengths.at[cfg.slots].set(0)
    return k_pool, v_pool, tokens, lengths, nxt


def _prefill_body(cfg: DecodeConfig, params, k_pool, v_pool, tokens,
                  lengths, slot_id, prompt, n):
    """Process one padded prompt into slot ``slot_id``: causal attention
    over the prompt (keys masked to the true length ``n``), KV pages
    written for every position, first generated token sampled from the
    last REAL position.  Rows past ``n`` compute garbage that is never
    attended (decode masks by length) and is overwritten as the
    generation advances."""
    Lp = prompt.shape[0]
    x = params["emb"][prompt]                           # (Lp, D)
    valid = jnp.arange(Lp) < n
    for l in range(cfg.layers):
        k = (x @ params["l%d.wk" % l]).reshape(Lp, cfg.heads,
                                               cfg.head_dim)
        v = (x @ params["l%d.wv" % l]).reshape(Lp, cfg.heads,
                                               cfg.head_dim)
        k_pool = lax.dynamic_update_slice(
            k_pool, k[None, None], (l, slot_id, 0, 0, 0))
        v_pool = lax.dynamic_update_slice(
            v_pool, v[None, None], (l, slot_id, 0, 0, 0))
        q = (x @ params["l%d.wq" % l]).reshape(Lp, cfg.heads,
                                               cfg.head_dim)
        q4 = q.transpose(1, 0, 2)[None]                 # (1, H, Lp, Dh)
        k4 = k.transpose(1, 0, 2)[None]
        v4 = v.transpose(1, 0, 2)[None]
        att = attention_core(q4, k4, v4, causal=True,
                             mask=valid[None, None, None, :])
        x = x + att[0].transpose(1, 0, 2).reshape(Lp, cfg.dim) \
            @ params["l%d.wo" % l]
        x = _block_mlp(params, l, x)
    x_last = jnp.take(x, jnp.maximum(n - 1, 0), axis=0)
    logits = x_last @ params["unemb"]
    t0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = tokens.at[slot_id].set(t0)
    lengths = lengths.at[slot_id].set(n)
    return k_pool, v_pool, tokens, lengths, t0


# geometry-keyed jit cache for the reference oracle: a load driver
# replays MANY reference decodes against one model — per-token eager
# dispatch would dominate its wall time.  Plain jax.jit, deliberately
# NOT register_program: the oracle is a verification tool, not a
# serving path, and must not pollute the serve census.
_reference_jits: Dict[Tuple, Tuple] = {}
_reference_jits_lock = threading.Lock()


def _reference_step_fns(cfg: DecodeConfig):
    key = (cfg.vocab, cfg.dim, cfg.heads, cfg.layers, cfg.slots,
           cfg.max_len)
    with _reference_jits_lock:
        fns = _reference_jits.get(key)
        if fns is None:
            fns = (jax.jit(functools.partial(_prefill_body, cfg)),
                   jax.jit(functools.partial(_decode_body, cfg)))
            _reference_jits[key] = fns
        return fns


def reference_generate(prompt: Sequence[int], max_new: int,
                       params: Optional[Dict] = None,
                       config: Optional[DecodeConfig] = None,
                       eos_id: Optional[int] = None) -> List[int]:
    """Local greedy-decode oracle: drives the SAME prefill/decode
    bodies through a private single-slot state (no pool sharing), so a
    load driver can recompute what a correct replica must answer — the
    decode analogue of ``demo.demo_expected``."""
    cfg = config or DecodeConfig()
    params = params if params is not None else demo_lm_params(cfg)
    lp = cfg.prompt_bucket_for(len(prompt))
    if lp is None:
        raise MXNetError("reference_generate: prompt of %d tokens "
                         "exceeds the top prompt bucket %d"
                         % (len(prompt), cfg.prompt_buckets[-1]))
    prefill_fn, decode_fn = _reference_step_fns(cfg)
    shape = (cfg.layers, cfg.slots + 1, cfg.max_len, cfg.heads,
             cfg.head_dim)
    k = jnp.zeros(shape, jnp.float32)
    v = jnp.zeros(shape, jnp.float32)
    tok = jnp.zeros((cfg.slots + 1,), jnp.int32)
    ln = jnp.zeros((cfg.slots + 1,), jnp.int32)
    padded = _np.zeros(lp, _np.int32)
    padded[:len(prompt)] = list(prompt)
    k, v, tok, ln, t0 = prefill_fn(params, k, v, tok, ln,
                                   _np.int32(0), jnp.asarray(padded),
                                   _np.int32(len(prompt)))
    out = [int(t0)]
    ids = jnp.zeros((1,), jnp.int32)
    while len(out) < max_new:
        if eos_id is not None and out[-1] == eos_id:
            break
        k, v, tok, ln, nxt = decode_fn(params, k, v, tok, ln, ids)
        out.append(int(nxt[0]))
    return out[:max_new]


class _CensusHandle:
    """Weakref-able holder so one servable can own two census buckets
    (its KV pool under ``kv_cache``, its parameters under ``serve``)."""

    __slots__ = ("fn", "__weakref__")

    def __init__(self, fn):
        self.fn = fn


def _counter(name, doc):
    return _telemetry.registry.counter(name, doc=doc)


class DecodeServable:
    """One immutable decode-model version: params + device-resident KV
    pool + the two bucketed AOT program tables (prefill by prompt
    bucket, decode by slot bucket).

    The KV state (pool pages, per-slot next-token and length arrays) is
    DONATED through every dispatch: ``_state`` always holds the only
    live copy, rebound from the program outputs, so pool bytes in
    ``buffer_census()['kv_cache']`` are constant for the servable's
    lifetime.  Only the pump thread may dispatch (single-writer state).
    """

    def __init__(self, params: Optional[Dict] = None,
                 config: Optional[DecodeConfig] = None,
                 name: str = "demo-lm", version: int = 1):
        self.config = config or DecodeConfig()
        self.params = params if params is not None \
            else demo_lm_params(self.config)
        self.name = str(name)
        self.version = int(version)
        cfg = self.config
        shape = (cfg.layers, cfg.slots + 1, cfg.max_len, cfg.heads,
                 cfg.head_dim)
        self._state: Dict[str, jnp.ndarray] = {
            "k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32),
            "tok": jnp.zeros((cfg.slots + 1,), jnp.int32),
            "len": jnp.zeros((cfg.slots + 1,), jnp.int32),
        }
        from .. import programs as _programs
        self._kv_handle = _CensusHandle(
            lambda: list(self._state.values()))
        self._params_handle = _CensusHandle(
            lambda: list(self.params.values()))
        _programs.track_buffers("kv_cache", self._kv_handle,
                                lambda h: h.fn())
        _programs.track_buffers("serve", self._params_handle,
                                lambda h: h.fn())
        self._lock = threading.Lock()
        self._step_programs: Dict[int, object] = {}
        self._prefill_programs: Dict[int, object] = {}
        self.retraces = 0            # program builds (warm pays them)
        self.hits = 0                # dispatches answered by the table
        self.warmed = False
        self._c_retrace = _counter(
            "serve.retraces", "serve-side program builds (should be 0 "
            "after warmup; warm() pays them at deploy)")
        self._c_hits = _counter(
            "serve.bucket_hits", "dispatches answered by a pre-built "
            "bucket program")

    # -- program tables -----------------------------------------------------
    def step_program(self, bucket: int):
        """The decode program for one slot bucket (builds on miss,
        counted as a retrace — warm() pre-builds every bucket)."""
        bucket = int(bucket)
        with self._lock:
            prog = self._step_programs.get(bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_decode(params, k_pool, v_pool, tokens, lengths,
                       slot_ids):
            return _decode_body(cfg, params, k_pool, v_pool, tokens,
                                lengths, slot_ids)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.step.s%d" % bucket, run_decode,
                donate_argnums=(1, 2, 3, 4))
        with self._lock:
            prog = self._step_programs.setdefault(bucket, prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    def prefill_program(self, prompt_bucket: int):
        prompt_bucket = int(prompt_bucket)
        with self._lock:
            prog = self._prefill_programs.get(prompt_bucket)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        cfg = self.config

        def run_prefill(params, k_pool, v_pool, tokens, lengths,
                        slot_id, prompt, n):
            return _prefill_body(cfg, params, k_pool, v_pool, tokens,
                                 lengths, slot_id, prompt, n)

        from .. import programs as _programs
        with _telemetry.phase("retrace"):
            prog = _programs.register_program(
                "serve.decode.prefill.p%d" % prompt_bucket, run_prefill,
                donate_argnums=(1, 2, 3, 4))
        with self._lock:
            prog = self._prefill_programs.setdefault(prompt_bucket,
                                                     prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    # -- dispatch (pump thread only; mxlint hot-path roots) -----------------
    def dispatch_step(self, slot_ids: _np.ndarray):
        """ONE device program over the packed active set; rebinds the
        donated state and returns the (b,) emitted-token device array
        (async — the harvester syncs it)."""
        from ..engine import engine as _engine
        prog = self.step_program(len(slot_ids))
        st = self._state
        k, v, tok, ln, out = prog(self.params, st["k"], st["v"],
                                  st["tok"], st["len"], slot_ids)
        self._state = {"k": k, "v": v, "tok": tok, "len": ln}
        _engine.count_dispatch(1)
        return out

    def dispatch_prefill(self, slot: int, prompt: _np.ndarray, n: int):
        """ONE device program filling ``slot``'s KV pages from a padded
        prompt; returns the first generated token as a () device
        array."""
        from ..engine import engine as _engine
        prog = self.prefill_program(prompt.shape[0])
        st = self._state
        k, v, tok, ln, t0 = prog(self.params, st["k"], st["v"],
                                 st["tok"], st["len"],
                                 _np.int32(slot), prompt, _np.int32(n))
        self._state = {"k": k, "v": v, "tok": tok, "len": ln}
        _engine.count_dispatch(1)
        return t0

    def warm(self) -> "DecodeServable":
        """Pre-build + pre-run EVERY prefill and decode bucket (against
        the scratch slot), then reset the generation bookkeeping —
        after this, serve time never pays a trace."""
        cfg = self.config
        for lp in cfg.prompt_buckets:
            self.dispatch_prefill(cfg.slots,
                                  _np.zeros(lp, _np.int32), lp)
        for b in cfg.slot_buckets:
            self.dispatch_step(_np.full(b, cfg.slots, _np.int32))
        jax.block_until_ready(self._state["k"])
        # scratch-slot bookkeeping back to empty; the pool's warmed
        # garbage is masked by zero lengths and overwritten on reuse
        self._state["tok"] = jnp.zeros_like(self._state["tok"])
        self._state["len"] = jnp.zeros_like(self._state["len"])
        self.warmed = True
        return self

    def kv_state_bytes(self) -> int:
        """Current KV-state footprint (pool pages + token/length
        arrays) — the number that must stay FLAT across generations."""
        return sum(int(a.nbytes) for a in self._state.values())

    def kv_slot_bytes(self) -> int:
        """One slot's share of the KV pool (the scratch lane counts as
        a slot here — the pool is ``slots + 1`` lanes wide), i.e. the
        bytes a free slot represents as ADMISSION headroom."""
        return self.kv_state_bytes() // (self.config.slots + 1)


class _PendingGen:
    """One admitted generation request: prompt in, tokens accumulating
    out.  The pump owns its slot; the HARVESTER appends tokens, stamps
    per-token latency and flags completion; handler threads block in
    :meth:`result` / stream via :meth:`wait_new`."""

    __slots__ = ("prompt", "max_new", "eos_id", "trace_ctx", "submit_t",
                 "slot", "token_times", "_cv", "_tokens", "_done",
                 "_err", "_last_t")

    def __init__(self, prompt: List[int], max_new: int,
                 eos_id: Optional[int],
                 trace_ctx: Optional[Tuple[str, str]] = None):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.trace_ctx = trace_ctx
        self.submit_t = time.perf_counter()
        self.slot: Optional[int] = None
        self.token_times: List[float] = []   # per-token latency (s)
        self._cv = threading.Condition()
        self._tokens: List[int] = []
        self._done = False
        self._err: Optional[BaseException] = None
        self._last_t: Optional[float] = None

    # -- harvester side -----------------------------------------------------
    def _append(self, tok: int, now: float) -> Tuple[bool, bool]:
        """Record one harvested token; returns (appended, finished).
        Tokens arriving after completion (pipeline overrun) are
        dropped."""
        with self._cv:
            if self._done:
                return False, True
            base = self._last_t if self._last_t is not None \
                else self.submit_t
            self.token_times.append(now - base)
            self._last_t = now
            self._tokens.append(int(tok))
            finished = len(self._tokens) >= self.max_new or (
                self.eos_id is not None and int(tok) == self.eos_id)
            if finished:
                self._done = True
            self._cv.notify_all()
            return True, finished

    def _fail(self, err: BaseException) -> None:
        with self._cv:
            if not self._done:
                self._err = err
                self._done = True
            self._cv.notify_all()

    # -- consumer side ------------------------------------------------------
    def done(self) -> bool:
        with self._cv:
            return self._done

    def tokens_so_far(self) -> List[int]:
        with self._cv:
            return list(self._tokens)

    def wait_new(self, have: int, timeout: float
                 ) -> Tuple[List[int], bool]:
        """Block until more than ``have`` tokens exist (or the
        generation completes / the wait times out); returns (the tokens
        past ``have``, done)."""
        deadline = _fault.Deadline(timeout)
        with self._cv:
            while len(self._tokens) <= have and not self._done:
                remaining = deadline.remaining()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=min(0.05, remaining))
            return list(self._tokens[have:]), self._done

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block (bounded) for the whole generation; raises on engine
        failure or timeout."""
        timeout = _result_timeout(timeout)
        deadline = _fault.Deadline(timeout)
        with self._cv:
            while not self._done:
                remaining = deadline.remaining()
                if remaining <= 0:
                    raise MXNetError(
                        "serve: generation timed out after %.3gs "
                        "(%d/%d tokens)" % (timeout, len(self._tokens),
                                            self.max_new))
                self._cv.wait(timeout=min(0.1, remaining))
            if self._err is not None:
                raise self._err
            return list(self._tokens)


class DecodeBatcher:
    """The continuous-batching decode engine: admission queue + slot
    allocator + decode pump (pure dispatch) + token harvester (the only
    device→host reader)."""

    def __init__(self, servable: DecodeServable,
                 queue_cap: Optional[int] = None,
                 mode: str = "continuous", on_tick=None,
                 autostart: bool = True):
        if mode not in ("continuous", "request"):
            raise MXNetError("DecodeBatcher mode must be 'continuous' "
                             "or 'request', got %r" % (mode,))
        self._sv = servable
        if not servable.warmed:
            servable.warm()
        self._cap = int(queue_cap if queue_cap is not None else
                        get_env("MX_SERVE_QUEUE_CAP", 256, int))
        self._mode = mode
        self._on_tick = on_tick
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._slot_lk = threading.Lock()
        self._slots: List[Optional[_PendingGen]] = \
            [None] * servable.config.slots
        # bounded pump->harvester handoff: one step boundary emits at
        # most `slots` prefill items + 1 step item, so this bound can
        # never wedge a synchronous (autostart=False) driver, while in
        # threaded mode it caps how far the pump runs ahead of the
        # host-side token reads
        self._harvest_q: _queue.Queue = _queue.Queue(
            maxsize=servable.config.slots + 4)
        self._stop = threading.Event()
        reg = _telemetry.registry
        self._c_requests = reg.counter(
            "serve.decode.requests", doc="admitted generation requests")
        self._c_rejected = reg.counter(
            "serve.decode.rejected", doc="generation requests shed at "
            "admission (queue cap) or refused (prompt too long)")
        self._c_tokens = reg.counter(
            "serve.decode.tokens", doc="generated tokens harvested")
        self._c_steps = reg.counter(
            "serve.decode.steps", doc="decode-step device dispatches "
            "(exactly 1 per step regardless of the active count)")
        self._c_prefills = reg.counter(
            "serve.decode.prefills", doc="prefill device dispatches "
            "(one per admitted sequence)")
        self._c_seqs = reg.counter(
            "serve.decode.sequences", doc="generations retired complete")
        self._g_queue = reg.gauge(
            "serve.decode.queue", doc="generation requests queued")
        self._g_active = reg.gauge(
            "serve.decode.active_slots", doc="sequences in decode slots")
        # first-class capacity signals (ISSUE 17): the router and
        # autoscaler read these per-replica off the merged FLEET
        # snapshot — no more deriving load from occupancy histograms
        self._g_occupancy = reg.gauge(
            "serve.decode.slot_occupancy",
            doc="fraction of decode slots holding an active sequence "
                "(0..1; router load signal)")
        self._g_headroom = reg.gauge(
            "serve.decode.kv_headroom_bytes",
            doc="KV-pool bytes behind currently-FREE decode slots "
                "(admission headroom; router/autoscaler signal)")
        self._h_occ = reg.histogram(
            "serve.decode.occupancy", doc="active sequences per decode "
            "step", buckets=(1, 2, 4, 8, 16, 32, 64))
        self._h_token = reg.histogram(
            "serve.decode.token_seconds", doc="per-token latency: first "
            "token = submit->harvest (queue + prefill included), then "
            "inter-token gaps",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
        self._set_capacity_gauges(0)
        self._pump = threading.Thread(
            target=self._loop, daemon=True, name="mx-serve-decode-pump")
        self._harvester = threading.Thread(
            target=self._harvest_loop, daemon=True,
            name="mx-serve-decode-harvest")
        if autostart:
            self._pump.start()
            self._harvester.start()

    @property
    def servable(self) -> DecodeServable:
        return self._sv

    @property
    def version(self) -> int:
        return self._sv.version

    @property
    def mode(self) -> str:
        return self._mode

    # -- admission ----------------------------------------------------------
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q)

    def active_count(self) -> int:
        with self._slot_lk:
            return sum(1 for g in self._slots if g is not None)

    def _set_capacity_gauges(self, active: int) -> None:
        """Publish the per-replica capacity signals for ``active``
        occupied slots (called wherever occupancy changes)."""
        slots = self._sv.config.slots
        self._g_occupancy.set(active / float(slots) if slots else 0.0)
        self._g_headroom.set(
            max(0, slots - active) * self._sv.kv_slot_bytes())

    def submit(self, prompt: Sequence[int],
               max_new: Optional[int] = None,
               eos_id: Optional[int] = None,
               trace_ctx: Optional[Tuple[str, str]] = None
               ) -> _PendingGen:
        """Admit one generation request.  ``eos_id`` overrides the
        config's stop token for this request (stop tokens are
        per-request in real serving).  Raises :class:`Overloaded` when
        the bounded queue is full, MXNetError when the request can
        never be served (empty/over-bucket prompt, bad token ids)."""
        cfg = self._sv.config
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            self._c_rejected.inc()
            raise MXNetError("serve: GENERATE prompt must be a sequence "
                             "of token ids")
        if not prompt:
            self._c_rejected.inc()
            raise MXNetError("serve: GENERATE needs >= 1 prompt token")
        if any(t < 0 or t >= cfg.vocab for t in prompt):
            self._c_rejected.inc()
            raise MXNetError("serve: prompt token out of vocab range "
                             "[0, %d)" % cfg.vocab)
        if cfg.prompt_bucket_for(len(prompt)) is None:
            self._c_rejected.inc()
            raise MXNetError(
                "serve: prompt of %d tokens exceeds the top prompt "
                "bucket %d (MX_SERVE_DECODE_PROMPT_BUCKETS)"
                % (len(prompt), cfg.prompt_buckets[-1]))
        limit = cfg.max_tokens if max_new is None \
            else max(1, min(int(max_new), cfg.max_tokens))
        stop = cfg.eos_id if eos_id is None else int(eos_id)
        gen = _PendingGen(prompt, limit, stop, trace_ctx=trace_ctx)
        with self._cv:
            if len(self._q) >= self._cap:
                self._c_rejected.inc()
                raise Overloaded(
                    "serve: decode admission queue full (%d/%d; "
                    "MX_SERVE_QUEUE_CAP) - retry later or add replicas"
                    % (len(self._q), self._cap))
            self._q.append(gen)
            self._g_queue.set(len(self._q))
            self._cv.notify_all()
        self._c_requests.inc()
        return gen

    # -- the decode pump (mxlint hot-path roots) ----------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            idle = self._tick()
            if self._on_tick is not None:
                self._on_tick()
            if idle:
                with self._cv:
                    if not self._q:
                        self._cv.wait(timeout=0.01)
        # stop: refuse whatever is still queued so no handler thread is
        # left waiting on a generation nobody will advance
        with self._cv:
            leftover = list(self._q)
            self._q.clear()
            self._g_queue.set(0)
        with self._slot_lk:
            leftover += [g for g in self._slots if g is not None]
            self._slots = [None] * len(self._slots)
        for g in leftover:
            g._fail(MXNetError("serve: decode engine stopped"))

    def _tick(self) -> bool:
        """One step boundary: retire finished sequences, admit queued
        prefills into the freed slots, then ONE decode dispatch over
        the packed active set.  Returns True when there was nothing to
        do (idle)."""
        self._retire()
        self._admit()
        active = self._active()
        if not active:
            return True
        try:
            self._step(active)
        except BaseException as e:            # XLA failure: fail the set
            for _slot, g in active:
                g._fail(e)
        return False

    def _retire(self) -> None:
        """Step boundary, phase ``kv_evict``: free the slots of
        completed sequences.  Eviction is bookkeeping — the pool pages
        stay allocated (flat HBM); the next prefill into the slot
        resets its length and overwrites from position 0, and stale
        entries beyond the new length are masked, never read."""
        with self._slot_lk:
            done = [(i, g) for i, g in enumerate(self._slots)
                    if g is not None and g.done()]
        if not done:
            return
        with _telemetry.phase("kv_evict"):
            with self._slot_lk:
                for i, _g in done:
                    self._slots[i] = None
        self._c_seqs.inc(len(done))
        active = self.active_count()
        self._g_active.set(active)
        self._set_capacity_gauges(active)

    def _admit(self) -> None:
        """The slot allocator: fill free slots from the queue at the
        step boundary, one prefill dispatch each.  Request-level mode
        (the bench strawman) admits only when the whole previous batch
        has retired — exactly the behavior continuous batching
        exists to beat."""
        with self._slot_lk:
            free = [i for i, g in enumerate(self._slots) if g is None]
            occupied = len(self._slots) - len(free)
        if self._mode == "request" and occupied:
            return
        while free:
            with self._cv:
                if not self._q:
                    break
                gen = self._q.popleft()
                self._g_queue.set(len(self._q))
            slot = free.pop(0)
            gen.slot = slot
            with self._slot_lk:
                self._slots[slot] = gen
            try:
                self._dispatch_prefill(gen, slot)
            except BaseException as e:
                with self._slot_lk:
                    self._slots[slot] = None
                gen._fail(e)

    def _active(self) -> List[Tuple[int, _PendingGen]]:
        with self._slot_lk:
            return [(i, g) for i, g in enumerate(self._slots)
                    if g is not None and not g.done()]

    def _dispatch_prefill(self, gen: _PendingGen, slot: int) -> None:
        cfg = self._sv.config
        lp = cfg.prompt_bucket_for(len(gen.prompt))
        padded = _np.zeros(lp, _np.int32)
        padded[:len(gen.prompt)] = gen.prompt
        with _telemetry.phase("prefill") as span:
            if gen.trace_ctx is not None:
                span.event("request", req_trace=gen.trace_ctx[0],
                           req_span=gen.trace_ctx[1], slot=slot)
            t0 = self._sv.dispatch_prefill(slot, padded,
                                           len(gen.prompt))
        self._c_prefills.inc()
        active = self.active_count()
        self._g_active.set(active)
        self._set_capacity_gauges(active)
        self._hq_put(([gen], t0))

    def _step(self, active: List[Tuple[int, _PendingGen]]) -> None:
        """ONE decode dispatch: pack the active slots into the smallest
        covering bucket (padded lanes park on the scratch slot) — no
        host sync anywhere on this path; the emitted-token array goes
        to the harvester."""
        cfg = self._sv.config
        bucket = cfg.slot_bucket_for(len(active))
        ids = _np.full(bucket, cfg.slots, _np.int32)
        ids[:len(active)] = [slot for slot, _g in active]
        with _telemetry.phase("decode_step") as span:
            for _slot, g in active:
                if g.trace_ctx is not None:
                    span.event("request", req_trace=g.trace_ctx[0],
                               req_span=g.trace_ctx[1])
            out = self._sv.dispatch_step(ids)
        self._c_steps.inc()
        self._h_occ.observe(len(active))
        self._hq_put(([g for _slot, g in active], out))

    def _hq_put(self, item) -> None:
        """Bounded handoff to the harvester: the pump may run at most
        the queue depth ahead of the host-side token reads (that bound
        is what sizes the pool's overrun margin)."""
        while not self._stop.is_set():
            try:
                self._harvest_q.put(item, timeout=0.05)
                return
            except _queue.Full:
                continue

    # -- the harvester (the ONLY device->host reader) -----------------------
    def _harvest_loop(self) -> None:
        while not (self._stop.is_set() and self._harvest_q.empty()):
            self._harvest_once(block=True)

    def _harvest_once(self, block: bool = False) -> bool:
        """Read one dispatch's emitted tokens (the device sync lives
        HERE, overlapping the pump's next dispatch), append them to
        their generations, stamp per-token latency, flag EOS/limit
        completions for the next boundary's retire."""
        try:
            if block:
                gens, out = self._harvest_q.get(timeout=0.05)
            else:
                gens, out = self._harvest_q.get_nowait()
        except _queue.Empty:
            return False
        toks = _np.asarray(out).reshape(-1)
        now = time.perf_counter()
        appended = 0
        for g, t in zip(gens, toks[:len(gens)]):
            did, _finished = g._append(int(t), now)
            if did:
                appended += 1
                self._h_token.observe(g.token_times[-1])
        if appended:
            self._c_tokens.inc(appended)
        return True

    # -- synchronous driving (tests, the dispatch-count budget) -------------
    def step_sync(self) -> bool:
        """One boundary + dispatch + synchronous harvest — the
        deterministic test face (requires ``autostart=False``: no
        pipeline lag, token counts exact).  Returns False once idle
        with an empty queue."""
        idle = self._tick()
        while self._harvest_once(block=False):
            pass
        with self._cv:
            empty = not self._q
        return not (idle and empty)

    def drain_sync(self, max_ticks: int = 10000) -> None:
        """step_sync until idle (tests)."""
        for _ in range(max_ticks):
            if not self.step_sync():
                return
        raise MXNetError("decode: drain_sync did not converge in %d "
                         "ticks" % max_ticks)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "DecodeBatcher":
        if not self._pump.is_alive():
            self._pump.start()
        if not self._harvester.is_alive():
            self._harvester.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._pump.is_alive():
            self._pump.join(timeout=timeout)
        if self._harvester.is_alive():
            self._harvester.join(timeout=timeout)


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the decode engine's declared proofs.
# ``serve.decode`` covers every slot-bucket decode program:
#   * donation — all four KV-state leaves (k/v pools, token and length
#     arrays) alias input->output in the lowered executable, the static
#     form of "HBM stays flat across decode steps";
#   * trace closure — every active-set size 1..slots resolves to a
#     compiled slot bucket (zero serve-time retraces as a theorem).
# ``serve.prefill`` does the same over the prompt-length bucket set,
# with over-bucket prompts provably rejected at admission (resolve ->
# None).  Builders run only inside the contracts verifier.
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.lru_cache(maxsize=1)
def _decode_contract_built():
    from ..programs import ContractCase, ContractClosure
    cfg = DecodeConfig()
    sv = DecodeServable(config=cfg)
    params_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for k, v in sv.params.items()}
    pool_abs = jax.ShapeDtypeStruct(
        (cfg.layers, cfg.slots + 1, cfg.max_len, cfg.heads,
         cfg.head_dim), jnp.float32)
    tok_abs = jax.ShapeDtypeStruct((cfg.slots + 1,), jnp.int32)
    scalar_abs = jax.ShapeDtypeStruct((), jnp.int32)

    def step_args(bucket):
        return (params_abs, pool_abs, pool_abs, tok_abs, tok_abs,
                jax.ShapeDtypeStruct((bucket,), jnp.int32))

    def prefill_args(lp):
        return (params_abs, pool_abs, pool_abs, tok_abs, tok_abs,
                scalar_abs, jax.ShapeDtypeStruct((lp,), jnp.int32),
                scalar_abs)

    step_cases = [ContractCase("serve.decode.step.s%d" % b,
                               step_args(b), label="s%d" % b,
                               target=sv.step_program(b))
                  for b in cfg.slot_buckets]
    prefill_cases = [ContractCase("serve.decode.prefill.p%d" % lp,
                                  prefill_args(lp), label="p%d" % lp,
                                  target=sv.prefill_program(lp))
                     for lp in cfg.prompt_buckets]

    def resolve_step(n):
        # every active-set size packs to its covering slot bucket
        return step_args(cfg.slot_bucket_for(int(n)))

    def resolve_prefill(n):
        # prompts pad to their bucket; over-bucket prompts are refused
        # at admission (never reach a jit)
        lp = cfg.prompt_bucket_for(int(n))
        return None if lp is None else prefill_args(lp)

    step_closure = ContractClosure(range(1, cfg.slots + 1),
                                   resolve_step)
    prefill_closure = ContractClosure(
        range(1, cfg.prompt_buckets[-1] + 3), resolve_prefill)
    return step_cases, step_closure, prefill_cases, prefill_closure


def _declare_decode_contracts():
    from ..programs import declare_contract
    declare_contract(
        "serve.decode", lambda: _decode_contract_built()[0],
        donate_argnums=(1, 2, 3, 4),
        temp_budget_bytes=8 << 20,
        closure=lambda: _decode_contract_built()[1],
        description="decode-step slot-bucket table: KV pool pages + "
                    "per-slot token/length arrays donate in place "
                    "(flat HBM across steps); trace signatures closed "
                    "over every active-set size 1..slots")
    declare_contract(
        "serve.prefill", lambda: _decode_contract_built()[2],
        donate_argnums=(1, 2, 3, 4),
        temp_budget_bytes=8 << 20,
        closure=lambda: _decode_contract_built()[3],
        description="prefill prompt-bucket table: same donated KV "
                    "state; trace signatures closed over the "
                    "MX_SERVE_DECODE_PROMPT_BUCKETS admission set "
                    "(over-bucket prompts provably rejected)")


_declare_decode_contracts()
