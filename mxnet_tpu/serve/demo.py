"""Deterministic demo model for serve smokes/benches.

Both sides of a chaos run build this independently — the replicas host
it, the load driver (tools/serve_load.py) recomputes expected outputs
locally — so response *correctness* (not just arrival) is assertable
across processes.  Seeded init + pure-functional forward make the
parity exact.
"""
from __future__ import annotations

import numpy as _np

DEMO_SEED = 42
DEMO_IN = 16
DEMO_HIDDEN = 32
DEMO_OUT = 8


def demo_block():
    """The canonical demo MLP: 16 → 32(relu) → 8, Xavier(seed 42).
    HybridSequential so ``export()`` works (hot-swap tests export a
    mutated copy and SWAP replicas onto it)."""
    import mxnet_tpu as mx
    from ..gluon import nn
    mx.random.seed(DEMO_SEED)
    net = nn.HybridSequential()
    net.add(nn.Dense(DEMO_HIDDEN, in_units=DEMO_IN, activation="relu"))
    net.add(nn.Dense(DEMO_OUT, in_units=DEMO_HIDDEN))
    net.initialize(mx.init.Xavier())
    return net


def demo_example(rows: int = 1) -> list:
    """A warm/probe input batch of the demo signature."""
    return [_np.zeros((rows, DEMO_IN), _np.float32)]


# Compile-heavy conv demo (ISSUE 13 warm-spawn lane): a real convnet
# whose per-bucket XLA compile dwarfs interpreter+jax import, so the
# cold-vs-warm spawn bench measures what the compile cache buys — the
# TPU-realistic regime where replica ready-to-traffic time is compile
# bound.  Deterministic like the MLP (seeded init), so correctness
# stays assertable across processes.
DEMO_CONV_SHAPE = (3, 64, 64)
DEMO_CONV_CLASSES = 100


def demo_conv_block():
    """Seeded resnet18 @ 3x64x64 → 100 classes."""
    import mxnet_tpu as mx
    from ..gluon.model_zoo import vision
    from ..ndarray.ndarray import NDArray
    import jax.numpy as jnp
    mx.random.seed(DEMO_SEED)
    net = vision.resnet18_v1(classes=DEMO_CONV_CLASSES)
    net.initialize(mx.init.Xavier())
    # finish deferred init (BatchNorm shapes) before functionalize
    net(NDArray(jnp.zeros((1,) + DEMO_CONV_SHAPE, jnp.float32)))
    return net


def demo_conv_example(rows: int = 1) -> list:
    return [_np.zeros((rows,) + DEMO_CONV_SHAPE, _np.float32)]


def demo_requests(n: int, rows: int = 1, seed: int = 0) -> list:
    """Deterministic request stream: n single-input requests."""
    rng = _np.random.RandomState(seed)
    return [[rng.randn(rows, DEMO_IN).astype(_np.float32)]
            for _ in range(n)]


def demo_expected(x: _np.ndarray, net=None) -> _np.ndarray:
    """Reference forward through the demo block (eager, local) — what a
    correct replica must answer for ``x``.  Pass ``net`` to reuse one
    built block across many requests."""
    from ..ndarray.ndarray import NDArray
    import jax.numpy as jnp
    if net is None:
        net = demo_block()
    out = net(NDArray(jnp.asarray(x)))
    return _np.asarray(out._jax)
