"""Serving client: SEQ-tagged RPCs with cross-replica failover.

The dist_async client's resilience posture (reconnect + idempotent
replay under a ``RetryPolicy``) extended with *replica failover*: the
client sticks to one replica of ``MX_SERVE_ROOTS`` and, when a
connection drops or times out, rotates to the next and replays the same
request there (fresh replica, fresh replay cache — a PREDICT recomputes
harmlessly; the seq still protects the same-replica lost-reply case).
This is what makes "kill a replica mid-load" lose ZERO in-flight
requests: every request either gets its reply from the replica that
took it, or is replayed on a survivor.

Overload (``(False, "overloaded: ...")``) is NOT a failover trigger by
default — the replica is healthy and shedding load; the caller gets
:class:`~mxnet_tpu.serve.batcher.Overloaded` to back off or report.
Pass ``spill=True`` to try the other replicas first (queue-spill
routing) and raise only when every replica sheds.  A DRAINING replica
(``(False, "draining: ...")``, ISSUE 17 retirement) always rotates —
retirement is routine, not load to report — and raises only when every
replica is retiring.

Retry attempts back off on the jittered exponential
:class:`~mxnet_tpu.fault.RetryPolicy` schedule through the injectable
clock (ISSUE 17 satellite): a fleet-wide blip produces spread-out
replays instead of a synchronized retry storm, and every slept delay
lands on the ``serve.client_backoff_seconds`` histogram.
"""
from __future__ import annotations

import socket
import threading
import uuid
from typing import List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import telemetry as _telemetry
from ..kvstore.server import send_msg, recv_msg
from ..kvstore.wire_codec import decode_array, decode_text, encode_array
from .batcher import Overloaded

__all__ = ["ServeClient"]


def _roots(addrs) -> List[str]:
    if addrs is None:
        raw = get_env("MX_SERVE_ROOTS") or ""
        addrs = [a.strip() for a in str(raw).split(",") if a.strip()]
    if isinstance(addrs, str):
        addrs = [addrs]
    if not addrs:
        raise MXNetError("ServeClient needs replica addresses "
                         "(MX_SERVE_ROOTS or addrs=[...])")
    return list(addrs)


class ServeClient:
    """Client to one serving fleet; thread-safe (one RPC at a time)."""

    def __init__(self, addrs=None, timeout: Optional[float] = None):
        self._addrs = _roots(addrs)
        self._socks: List[Optional[socket.socket]] = \
            [None] * len(self._addrs)
        self._idx = 0                       # sticky current replica
        self._client_id = "serve:%s" % uuid.uuid4().hex[:12]
        self._timeout = float(timeout if timeout is not None else
                              get_env("MX_SERVE_TIMEOUT", 30.0, float)
                              or 30.0)
        self._lock = threading.Lock()
        self._seq = 0
        self._c_failover = _telemetry.registry.counter(
            "serve.client_failovers",
            doc="requests replayed on another replica after a "
                "connection failure/timeout")
        self._h_backoff = _telemetry.registry.histogram(
            "serve.client_backoff_seconds",
            doc="jittered exponential backoff slept between serve RPC "
                "retry/failover attempts (injectable clock)",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))

    @property
    def replicas(self) -> List[str]:
        return list(self._addrs)

    # -- plumbing -----------------------------------------------------------
    def _next_seq(self) -> int:
        self._seq += 1                      # caller holds self._lock
        return self._seq

    def _kill_sock(self, idx: int) -> None:
        s = self._socks[idx]
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
        self._socks[idx] = None

    def _ensure_sock(self, idx: int) -> socket.socket:
        s = self._socks[idx]
        if s is not None:
            return s
        host, port = self._addrs[idx].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=5)
        s.settimeout(self._timeout)
        self._socks[idx] = s
        return s

    def _rpc(self, *msg, idx: Optional[int] = None,
             failover: bool = True, on_stream=None):
        """One SEQ-enveloped RPC.  ``idx=None`` uses the sticky replica
        and rotates on connection failures; an explicit ``idx`` pins one
        replica (health probes) and never fails over.  ``on_stream``
        receives each ("STREAM", offset, tokens) frame a streaming
        GENERATE emits ahead of its terminal reply (frames are
        at-least-once across a failover — the offset dedupes)."""
        pinned = idx is not None
        policy = _fault.RetryPolicy.from_env()
        if msg[0] == "STOP":
            # shutdown is best-effort: a replica that is already gone
            # must not cost the caller a retry deadline per replica
            policy.deadline = min(policy.deadline, 1.0)
        elif pinned and msg[0] in ("HEALTH", "METRICS"):
            # pinned probes ARE liveness checks (fleet scrapes, health
            # sweeps): a dead replica should read as dead in seconds,
            # not burn the full recovery deadline per member
            policy.deadline = min(policy.deadline, 5.0)
        with self._lock:
            # ONE seq for every attempt: a same-replica retry must
            # replay the same (client_id, seq) so the server's
            # exactly-once cache answers it instead of re-executing
            seq = self._next_seq()
        with _telemetry.rpc_span("serve.client.%s" % msg[0]) as span:
            tctx = span.wire_context()
            start = _fault.now()
            attempt = 0
            while True:
                if attempt:
                    # the RetryPolicy schedule walked explicitly (same
                    # math as its iterator) so every slept backoff is
                    # OBSERVED: jittered delays de-synchronize a
                    # fleet-wide blip's replays, and the histogram
                    # makes the spread auditable
                    d = policy.delay(attempt - 1)
                    if _fault.now() + d - start > policy.deadline:
                        break   # next attempt would blow the deadline
                    self._h_backoff.observe(d)
                    _fault.sleep(d)
                attempt += 1
                with self._lock:
                    at = idx if pinned else self._idx
                    env = ("SEQ", self._client_id, seq, msg)
                    try:
                        sock = self._ensure_sock(at)
                        _fault.fire(
                            "serve.client.send",
                            on_close=lambda at=at: self._kill_sock(at))
                        send_msg(sock, env if tctx is None
                                 else env + (tctx,))
                        _fault.fire(
                            "serve.client.recv",
                            on_close=lambda at=at: self._kill_sock(at))
                        while True:
                            resp = recv_msg(sock, timeout=self._timeout)
                            if isinstance(resp, tuple) and resp and \
                                    resp[0] == "STREAM":
                                if on_stream is not None:
                                    on_stream(resp[1], resp[2])
                                continue      # chunk; terminal follows
                            ok, payload = resp
                            break
                    except (ConnectionError, OSError, TimeoutError) as e:
                        self._kill_sock(at)
                        policy.note(e)
                        if pinned or not failover:
                            span.event("retry", replica=at, seq=seq,
                                       error=str(e))
                            continue
                        self._idx = (at + 1) % len(self._addrs)
                        self._c_failover.inc()
                        span.event("failover", dead=at,
                                   to=self._idx, seq=seq, error=str(e))
                        continue
                return ok, payload
        raise MXNetError(
            "serve: %r unreachable on every replica %r for %.3gs "
            "(MX_KVSTORE_RETRY_DEADLINE); last error: %s"
            % (msg[0], self._addrs, policy.deadline, policy.last_error))

    # -- verbs --------------------------------------------------------------
    def predict(self, arrays: Sequence, spill: bool = False,
                model: Optional[str] = None
                ) -> Tuple[int, List[_np.ndarray]]:
        """One inference request: per-input row-batched arrays in,
        ``(servable_version, [output leaf, ...])`` out.  ``model``
        names which co-hosted model answers on a multi-model replica
        (ISSUE 20); None keeps the replica's default.  Raises
        :class:`Overloaded` when the fleet sheds it, MXNetError on a
        terminal failure."""
        payload = [encode_array(a) for a in arrays]
        tried = 0
        while True:
            ok, resp = self._rpc("PREDICT", payload) if model is None \
                else self._rpc("PREDICT", payload, str(model))
            if ok:
                version, outs = resp
                return int(version), [decode_array(t) for t in outs]
            if isinstance(resp, str) and resp.startswith(("overloaded",
                                                          "draining")):
                tried += 1
                # a DRAINING replica is retiring (ISSUE 17): always
                # move on — retirement is routine, not load to report;
                # overload spills only when the caller opted in
                if ((spill or resp.startswith("draining"))
                        and tried < len(self._addrs)):
                    with self._lock:      # shed here; try the next one
                        self._idx = (self._idx + 1) % len(self._addrs)
                    continue
                if resp.startswith("overloaded"):
                    raise Overloaded(resp)
            raise MXNetError("serve: %s" % resp)

    def generate(self, prompt: Sequence[int],
                 max_tokens: Optional[int] = None,
                 eos: Optional[int] = None, on_token=None,
                 spill: bool = False,
                 model: Optional[str] = None) -> Tuple[int, List[int]]:
        """One autoregressive generation: prompt token ids in,
        ``(servable_version, [generated token, ...])`` out, through the
        fleet's continuous-batching decode engine (ISSUE 15).

        ``on_token(tokens)`` arms STREAMING: the server emits token
        chunks as they are harvested and the callback receives each NEW
        token list exactly once in order (chunks re-sent after a
        failover are deduped by offset — the replayed generation is
        deterministic, so offsets line up).  The returned terminal list
        is always the complete sequence.  Raises :class:`Overloaded`
        when the fleet sheds it, MXNetError on a terminal failure."""
        opts = {"stream": on_token is not None}
        if max_tokens is not None:
            opts["max_tokens"] = int(max_tokens)
        if eos is not None:
            opts["eos"] = int(eos)
        if model is not None:
            opts["model"] = str(model)
        seen = [0]

        def _dedupe(offset, tokens):
            fresh = tokens[max(0, seen[0] - offset):]
            if offset > seen[0]:       # gap (failover skew): drop, the
                return                 # terminal reply has everything
            if fresh:
                seen[0] = offset + len(tokens)
                on_token([int(t) for t in fresh])

        tried = 0
        while True:
            ok, resp = self._rpc(
                "GENERATE", [int(t) for t in prompt], opts,
                on_stream=_dedupe if on_token is not None else None)
            if ok:
                version, tokens = resp
                return int(version), [int(t) for t in tokens]
            if isinstance(resp, str) and resp.startswith(("overloaded",
                                                          "draining")):
                tried += 1
                # draining => the session must move: re-prefill on the
                # next replica (deterministic decode reproduces the
                # sequence exactly); overload spills only on opt-in
                if ((spill or resp.startswith("draining"))
                        and tried < len(self._addrs)):
                    with self._lock:
                        self._idx = (self._idx + 1) % len(self._addrs)
                    continue
                if resp.startswith("overloaded"):
                    raise Overloaded(resp)
            raise MXNetError("serve: %s" % resp)

    def health(self, idx: Optional[int] = None) -> dict:
        """One replica's health dict (``idx`` pins; default = sticky)."""
        ok, resp = self._rpc("HEALTH", idx=idx)
        if not ok:
            raise MXNetError("serve: %s" % resp)
        return resp

    def decode_stats(self, idx: Optional[int] = None) -> Optional[dict]:
        """The replica's decode-engine section of HEALTH, or None when
        it hosts no decode engine.  On a paged replica (ISSUE 18,
        ``MX_SERVE_KV_PAGES`` > 0) this carries the page-level
        admission headroom — ``engine='paged'``, ``kv_free_pages``,
        ``shared_saved_bytes`` — that a load driver reads to assert
        sharing actually happened."""
        return self.health(idx=idx).get("decode")

    def metrics(self, idx: Optional[int] = None,
                fmt: str = "prometheus") -> str:
        """One replica's live telemetry snapshot — the Prometheus text
        exposition (or ``fmt='json'`` registry snapshot) over the serve
        wire, so a running fleet is scrapeable without a sidecar."""
        ok, resp = self._rpc("METRICS", fmt, idx=idx)
        if not ok:
            raise MXNetError("serve: %s" % resp)
        return decode_text(resp)

    def swap(self, prefix: str, epoch: int = 0,
             input_names: Sequence[str] = ("data",)) -> List[int]:
        """Hot-swap EVERY replica to the checkpoint at ``prefix``;
        returns the per-replica new version numbers."""
        versions = []
        for i in range(len(self._addrs)):
            ok, resp = self._rpc("SWAP", prefix, int(epoch),
                                 tuple(input_names), idx=i)
            if not ok:
                raise MXNetError("serve: replica %d %s" % (i, resp))
            versions.append(int(resp))
        return versions

    def drain(self, timeout: Optional[float] = None,
              idx: Optional[int] = None) -> dict:
        """Begin drain-not-kill retirement on one replica (``idx``
        pins; default = sticky): admission closes, in-flight work
        finishes against the bounded deadline, then the replica's serve
        loop exits cleanly (ISSUE 17).  Returns the replica's drain
        status dict."""
        ok, resp = self._rpc(
            "DRAIN", None if timeout is None else float(timeout),
            idx=idx)
        if not ok:
            raise MXNetError("serve: %s" % resp)
        return resp

    def stop(self) -> None:
        """Graceful STOP to every replica (best-effort)."""
        for i in range(len(self._addrs)):
            try:
                self._rpc("STOP", idx=i)
            except MXNetError:
                pass

    def close(self) -> None:
        with self._lock:
            for i in range(len(self._socks)):
                self._kill_sock(i)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
