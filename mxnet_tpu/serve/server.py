"""Serving RPC front: PREDICT / GENERATE / HEALTH / SWAP / STOP over
the kvstore wire.

Transport and envelope are the kvstore server's, verbatim: length-
prefixed pickles (``kvstore.server.send_msg/recv_msg``), requests
optionally wrapped ``("SEQ", client_id, seq, inner[, (trace_id,
span_id)])`` with an exactly-once replay cache — a client that
reconnects after a dropped reply replays the same seq and is answered
from the cache instead of re-executing (a replayed PREDICT must not
burn a second dispatch; a replayed SWAP must not double-bump the
version).  Tensors cross as numpy-only ``NPX`` tuples
(``kvstore.wire_codec.encode_array``), so the wire never carries a
device array and health tools never import the kernel stack.

Verbs::

  PREDICT  (PREDICT, [npx, ...])          -> (True, (version, [npx, ...]))
  GENERATE (GENERATE, [tok, ...], opts)   -> (True, (version, [tok, ...]))
           autoregressive decode through the continuous-batching engine
           (ISSUE 15); opts = {"max_tokens": N, "stream": bool,
           "eos": tok} (eos = per-request stop token).  With
           stream=True the terminal reply is preceded by zero or more
           ("STREAM", offset, [tok, ...]) frames as tokens are
           harvested — chunks are at-least-once (a failover replays
           from offset 0; the offset lets the client dedupe), the
           terminal (version, tokens) reply is exactly-once via the
           replay cache like PREDICT: a replayed COMPLETED sequence is
           answered from the cache, never re-generated.
  HEALTH   (HEALTH,)                      -> (True, {status, version, ...})
  METRICS  (METRICS[, fmt])               -> (True, (TXT, utf8-bytes)):
           the live Prometheus text exposition (fmt='json': the JSON
           registry snapshot) — a replica is scrapeable with no sidecar
  SWAP     (SWAP, prefix, epoch, inputs)  -> (True, new_version)
  DRAIN    (DRAIN[, timeout])             -> (True, {status, ...}):
           first-class retirement (ISSUE 17) — stop ADMITTING new work
           (fresh PREDICT/GENERATE get ``(False, "draining: ...")``),
           let in-flight requests and generations finish, then exit the
           serve loop cleanly.  Past the bounded drain deadline
           (``timeout`` or MX_SERVE_DRAIN_TIMEOUT) the stragglers'
           connections are severed with NO reply, so their clients fail
           over and re-prefill on a survivor — exactly the
           mid-generation-kill story, but only for the stragglers.
  STOP     (STOP,)                        -> (True, "stopping")

Overload is a NORMAL reply — ``(False, "overloaded: ...")`` — so the
client can distinguish load shedding (report/back off; the replica is
healthy) from a dead replica (fail over).  A DRAINING replica refuses
new work the same way (``(False, "draining: ...")``): the
router/client route the request to another replica instead of burning
a retry deadline here.

Tracing: the handler opens ``serve.server.<CMD>`` as a child of the
client's wire-propagated span, and hands its own (trace_id, span_id) to
the batcher with the request, so the batch's ``serve_dispatch`` span
events close the client → server → batcher → dispatch chain.

Chaos: every request passes the ``serve.request`` fault site —
``tools/launch.py --fault 'serve.request:crash:after=N'`` kills the
replica mid-load exactly like the worker-fit chaos lane, which is how
tools/chaos_smoke.sh proves failover + supervisor restart.
"""
from __future__ import annotations

import os
import socket
import socketserver
import threading
from typing import Dict, Optional, Sequence

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import telemetry as _telemetry
from ..kvstore.server import send_msg, recv_msg
from ..kvstore.wire_codec import (WireCodecError, decode_array,
                                  encode_array, encode_text)
from ..kvstore.wire_verbs import declare_verbs
from .batcher import Batcher, Overloaded, result_timeout
from .servable import BudgetExceeded, ModelHost, Servable

__all__ = ["ServeServer", "serve_forever"]

# The serving wire surface, DECLARED (ISSUE 11): mxlint's
# wire-verb-exhaustive rule pairs every ServeClient-emitted verb with
# an entry here, checks this file handles it, that 'replayable' verbs
# sit in the exactly-once replay set (_CACHED) and 'idempotent' ones do
# not, and that named codecs have encode_*/decode_* pairs in
# kvstore/wire_codec.py.  The serve router (ISSUE 17) speaks this SAME
# surface — it forwards client envelopes verbatim, so its manifest in
# router.py mirrors these rows and the replay semantics hold
# end-to-end through it.
WIRE_VERBS = declare_verbs("serve", {
    # one PREDICT = one dispatch, even replayed; one SWAP = one flip
    "PREDICT": {"semantics": "replayable", "replay": "cached",
                "codec": "array", "mutates": ("engine",)},
    "SWAP": {"semantics": "replayable", "replay": "cached",
             "codec": None, "mutates": ("model",)},
    # one GENERATE = one generated sequence: a replayed COMPLETED
    # sequence answers from the cache (tokens are plain int lists — no
    # tensor codec); fresh streaming runs emit STREAM frames ahead of
    # the terminal reply
    "GENERATE": {"semantics": "replayable", "replay": "cached",
                 "codec": None, "mutates": ("engine",),
                 "stream": "STREAM"},
    # STREAM is the server->client token-chunk frame of a streaming
    # GENERATE, not a request verb: a client SENDING it is answered
    # with an explicit error (see handle()), and chunks re-emitted
    # after a failover dedupe by offset — re-delivery is harmless
    "STREAM": {"semantics": "idempotent", "replay": "bypass",
               "codec": None, "mutates": ()},
    # probes and shutdown re-execute harmlessly on a retried envelope
    "HEALTH": {"semantics": "idempotent", "replay": "bypass",
               "codec": None, "mutates": ()},
    "METRICS": {"semantics": "idempotent", "replay": "bypass",
                "codec": "text", "mutates": ()},
    "STOP": {"semantics": "idempotent", "replay": "bypass",
             "codec": None, "mutates": ()},
    # drain-not-kill retirement (ISSUE 17): re-asserting an already-
    # draining replica is a no-op, so a retried DRAIN is harmless
    "DRAIN": {"semantics": "idempotent", "replay": "bypass",
              "codec": None, "mutates": ("lifecycle",)},
}, role="server", durable=False, handler="ServeServer.handle")


class ServeServer:
    """Verb handlers + replay cache over one (ModelHost, Batcher) pair,
    plus an optional continuous-batching decode engine (``decode=``, a
    :class:`~mxnet_tpu.serve.decode.DecodeBatcher`) behind the GENERATE
    verb."""

    # replies worth exactly-once semantics; HEALTH re-executes harmlessly
    _CACHED = ("PREDICT", "SWAP", "GENERATE")

    def __init__(self, host: Optional[ModelHost] = None,
                 batcher: Optional[Batcher] = None, decode=None,
                 **batcher_kw):
        self.host = host or ModelHost()
        self.batcher = batcher or Batcher(self.host, **batcher_kw)
        self.decode = decode
        # co-hosted decode engines join the host's engine map so the
        # budget packer counts their models (a speculative pair's
        # draft + target) and FLEET/HEALTH can enumerate them; never
        # mutated from a verb branch
        if decode is not None:
            self.host.engines.setdefault(decode.servable.name, decode)
        # client_id -> [seq, done Event, resp]  (same shape as the
        # kvstore server's cache; one in-flight entry per client).
        # Serving clients are ephemeral (every ServeClient is a fresh
        # uuid), unlike the kvstore's fixed worker population — without
        # eviction each dead client's last PREDICT response (a full
        # output tensor) would be retained forever.  Bounded per-client
        # LRU: dict insertion order IS recency order because every
        # touch (new seq or replay hit) moves the entry to the end;
        # over-cap inserts evict the least-recently-touched RESOLVED
        # entries, counted in serve.replay_evicted.
        try:
            raw_cap = get_env("MX_SERVE_REPLAY_CAP", 512, int)
            # values < 1 clamp to 1 (never silently back to the
            # default): the exactly-once contract requires at least the
            # in-flight entry, so 0 cannot mean "disabled"
            self._replay_cap = max(1, int(512 if raw_cap is None
                                          else raw_cap))
        except (TypeError, ValueError):
            self._replay_cap = 512
        self._replay: Dict[str, list] = {}
        self._replay_lock = threading.Lock()
        self._c_evicted = _telemetry.registry.counter(
            "serve.replay_evicted",
            doc="replay-cache entries dropped by the per-client LRU "
                "bound (MX_SERVE_REPLAY_CAP)")
        # drain-not-kill retirement (ISSUE 17): once set, admission is
        # closed (fresh PREDICT/GENERATE refused with "draining: ...")
        # while in-flight work finishes against the bounded deadline
        self._draining = threading.Event()
        self._drain_lock = threading.Lock()
        self._drain_deadline: Optional[_fault.Deadline] = None

    # -- multi-model lifecycle (ISSUE 20; startup/admin path, NOT a
    # verb branch — engines are never created inside handle()) --------------
    def add_model(self, servable: Servable, example=None,
                  **batcher_kw) -> Servable:
        """Deploy one more named model onto this replica: warm + budget
        admission through ``ModelHost.deploy`` (raises
        :class:`BudgetExceeded` on a bust, nothing retained), then give
        the non-default model its own micro-batcher in
        ``host.engines`` so PREDICTs carrying its name coalesce
        independently of the default lane."""
        sv = self.host.deploy(servable, example=example)
        if sv.name != self.host.default_model and \
                sv.name not in self.host.engines:
            self.host.engines[sv.name] = Batcher(
                self.host, model=sv.name, **batcher_kw)
        return sv

    # -- envelope (kvstore SEQ contract) ------------------------------------
    def handle_request(self, msg, stream_fn=None):
        """``stream_fn(offset, tokens)`` — provided by the socket
        handler — emits one ("STREAM", offset, tokens) frame ahead of
        the terminal reply; only a FRESH streaming GENERATE uses it
        (replays answer terminally from the cache)."""
        if isinstance(msg, tuple) and msg and msg[0] == "SEQ":
            cid, seq, inner = msg[1], msg[2], msg[3]
            tctx = msg[4] if len(msg) > 4 else None
            cmd = inner[0] if inner else None
            with _telemetry.rpc_span(
                    "serve.server.%s" % cmd,
                    trace_id=tctx[0] if tctx else None,
                    parent_id=tctx[1] if tctx else None) as span:
                return self._handle_seq(cid, seq, inner, cmd, span,
                                        stream_fn=stream_fn)
        return self.handle(msg, stream_fn=stream_fn)

    def _handle_seq(self, cid, seq, inner, cmd, span, stream_fn=None):
        if cmd not in self._CACHED:
            return self.handle(inner, span=span)
        with self._replay_lock:
            ent = self._replay.get(cid)
            if ent is not None and seq == ent[0]:
                dup = ent
                # LRU touch: a replaying client is alive — move it to
                # the recent end so churn from new clients cannot evict
                # its in-flight exactly-once entry
                self._replay[cid] = self._replay.pop(cid)
            elif ent is not None and seq < ent[0]:
                span.event("stale", seq=seq, server_at=ent[0])
                return False, ("stale request seq %s (server already at "
                               "%s)" % (seq, ent[0]))
            else:
                dup = None
                ent = [seq, threading.Event(), None]
                self._replay.pop(cid, None)   # re-insert at recent end
                self._replay[cid] = ent
                if len(self._replay) > self._replay_cap:
                    self._evict_replay_locked()
        if dup is not None:
            span.event("replay", seq=seq)
            _telemetry.registry.counter(
                "serve.server_replays",
                doc="PREDICT/SWAP requests answered from the "
                    "exactly-once replay cache").inc()
            timeout = (get_env("MX_SERVE_TIMEOUT", 30.0, float) or 30.0) + 5
            if not dup[1].wait(timeout=timeout):
                return False, "replayed request %s still in flight" % seq
            return dup[2]
        try:
            resp = self.handle(inner, span=span, stream_fn=stream_fn)
        except BaseException as e:
            ent[2] = (False, "serve error handling %r: %s" % (cmd, e))
            ent[1].set()
            raise
        ent[2] = resp
        ent[1].set()
        return resp

    def _evict_replay_locked(self) -> None:
        """Caller holds _replay_lock.  Drop least-recently-touched
        RESOLVED entries until back under the cap; in-flight entries
        (Event not set) are never evicted — their replay semantics are
        live.  Each eviction bumps serve.replay_evicted."""
        evicted = 0
        for cid in list(self._replay):
            if len(self._replay) <= self._replay_cap:
                break
            ent = self._replay[cid]
            if ent[1].is_set():
                del self._replay[cid]
                evicted += 1
        if evicted:
            self._c_evicted.inc(evicted)

    # -- verbs --------------------------------------------------------------
    def handle(self, msg, span=None, stream_fn=None):
        cmd = msg[0]
        if cmd == "PREDICT":
            # optional third element: the target model's name on a
            # multi-model replica (absent/None -> the default model)
            return self._predict(msg[1], span,
                                 model=msg[2] if len(msg) > 2 else None)
        if cmd == "GENERATE":
            opts = msg[2] if len(msg) > 2 else {}
            return self._generate(msg[1], opts or {}, span, stream_fn)
        if cmd == "STREAM":
            # server->client frame only; a client emitting it as a
            # request is a protocol error, answered explicitly
            return False, ("STREAM is a server-to-client token frame, "
                           "not a request verb")
        if cmd == "HEALTH":
            return True, self.health()
        if cmd == "METRICS":
            # live Prometheus scrape over the serve wire (ISSUE 10
            # satellite): no sidecar needed — the reply is the whole
            # instrument registry (serve.* counters, program census,
            # phase histograms) as one TXT payload
            fmt = msg[1] if len(msg) > 1 else "prometheus"
            reg = _telemetry.registry
            # a scrape self-describes the replica (ISSUE 12): the active
            # servable rides the exposition as a model-labeled version
            # gauge, which is where the fleet collector/federation get
            # their `model` label from (no extra HEALTH round-trip)
            for name in self.host.models():
                try:
                    sv = self.host.active(name)
                except MXNetError:
                    continue    # raced an empty host / retired model
                reg.gauge("serve.active_version",
                          doc="live servable version per hosted model",
                          labels={"model": sv.name}).set(sv.version)
            text = reg.to_json(indent=1) if fmt == "json" \
                else reg.to_prometheus()
            return True, encode_text(text)
        if cmd == "SWAP":
            _, prefix, epoch, input_names = msg
            try:
                version = self.swap(prefix, epoch, input_names)
            except BudgetExceeded as e:
                # typed in-band refusal (ISSUE 20): the packer said no —
                # the replica is healthy, the model just does not fit
                # under MX_SERVE_HBM_BUDGET; nothing was retained
                return False, "budget: %s" % e
            except Exception as e:      # incl. a broken model's trace
                # error: the old version stays live, the caller gets
                # the reason instead of a severed connection
                return False, "swap failed: %s" % e
            return True, version
        if cmd == "DRAIN":
            timeout = msg[1] if len(msg) > 1 else None
            return True, self.drain(timeout)
        if cmd == "STOP":
            return True, "stopping"
        return False, "unknown serve command %r" % (cmd,)

    # -- drain lifecycle (ISSUE 17) -----------------------------------------
    def drain(self, timeout=None) -> Dict:
        """Begin retirement: close admission, arm the bounded drain
        deadline (idempotent — a re-asserted DRAIN keeps the FIRST
        deadline so a retry cannot extend the retirement window), and
        report what is still in flight.  ``serve_forever`` watches
        :meth:`drain_idle` / :meth:`drain_expired` and exits the serve
        loop when the replica is empty or the deadline passes."""
        t = float(timeout if timeout is not None else
                  get_env("MX_SERVE_DRAIN_TIMEOUT", 30.0, float) or 30.0)
        with self._drain_lock:
            if self._drain_deadline is None:
                self._drain_deadline = _fault.Deadline(t)
            self._draining.set()
            remaining = self._drain_deadline.remaining()
        _telemetry.registry.counter(
            "serve.drains",
            doc="DRAIN retirements accepted by this replica").inc()
        status = {"status": "draining",
                  "deadline_seconds": remaining,
                  "queue_rows": self.batcher.queue_rows()}
        if self.decode is not None:
            status["active"] = self.decode.active_count()
            status["queued"] = self.decode.queue_depth()
        return status

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain_idle(self) -> bool:
        """True when nothing is left in flight inside the engines (the
        wire-level in-flight count is ``serve_forever``'s half)."""
        if self.batcher.queue_rows() > 0:
            return False
        if self.decode is not None and (
                self.decode.active_count() > 0
                or self.decode.queue_depth() > 0):
            return False
        return True

    def drain_expired(self) -> bool:
        with self._drain_lock:
            dl = self._drain_deadline
        return dl is not None and dl.expired()

    def _predict(self, payload: Sequence, span, model=None):
        if self._draining.is_set():
            # admission is closed: a NORMAL reply (not a severed
            # socket) so the router/client re-routes instead of
            # replaying a poison request against a retiring replica
            return False, ("draining: replica is retiring, not "
                           "admitting new work")
        try:
            arrays = [decode_array(t) for t in payload]
        except ValueError as e:
            return False, "bad PREDICT payload: %s" % e
        tctx = span.wire_context() if span is not None else None
        # model routing (ISSUE 20): a named non-default model rides its
        # own micro-batcher (host.engines, created at deploy, read-only
        # here); no/None/default name keeps the single-model fast path
        eng = self.batcher
        if model is not None and model != self.host.default_model:
            eng = self.host.engines.get(model)
            if not isinstance(eng, Batcher):
                return False, ("unknown model %r (hosted: %s)"
                               % (model,
                                  ", ".join(self.host.models()) or
                                  "none"))
        try:
            pending = eng.submit(arrays, trace_ctx=tctx) \
                if eng is not self.batcher \
                else self.batcher.submit(arrays, trace_ctx=tctx)
        except Overloaded as e:
            return False, "overloaded: %s" % e
        except MXNetError as e:
            return False, str(e)
        # server-side wait stays INSIDE the client's recv window (which
        # started earlier and includes network time), so a backlogged
        # replica sheds with an explicit reply instead of the client
        # timing out first and mistaking it for a dead replica
        timeout = max(1.0, result_timeout(None) - 2.0)
        try:
            version, outs = pending.result(timeout=timeout)
        except Exception as e:
            # ANY dispatch failure (XLA runtime error, OOM, a broken
            # foreign model's forward) must come back as a normal
            # (False, reason) reply — a severed connection would make
            # the client replay the poison request on every replica
            return False, "predict failed: %s: %s" % (type(e).__name__, e)
        return True, (version, [encode_array(o) for o in outs])

    def _generate(self, prompt, opts, span, stream_fn):
        """GENERATE: submit into the continuous-batching decode engine,
        optionally stream token chunks, answer the complete sequence.
        Like PREDICT, every failure is a normal (False, reason) reply —
        a severed connection would make the client replay a poison
        request on every replica."""
        if self._draining.is_set():
            # new generations (even from a session pinned here) are new
            # WORK: refuse so the router re-pins the session elsewhere;
            # generations already inside the pump keep running
            return False, ("draining: replica is retiring, not "
                           "admitting new sessions")
        if self.decode is None:
            return False, ("no decode engine deployed (start the "
                           "replica with --decode)")
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            return False, "bad GENERATE payload: prompt must be token ids"
        tctx = span.wire_context() if span is not None else None
        max_new = opts.get("max_tokens")
        # model routing (ISSUE 20): the envelope may name which hosted
        # LM to decode with; the default engine answers unnamed (and
        # its own name), other names resolve through host.engines
        model = opts.get("model")
        eng = self.decode
        if model is not None and model != self.decode.servable.name:
            cand = self.host.engines.get(model)
            if cand is None or isinstance(cand, Batcher) or \
                    not hasattr(cand, "submit"):
                return False, ("unknown model %r (decode engines: %s)"
                               % (model, self.decode.servable.name))
            eng = cand
        try:
            pending = eng.submit(prompt, max_new=max_new,
                                 eos_id=opts.get("eos"),
                                 trace_ctx=tctx) \
                if eng is not self.decode \
                else self.decode.submit(prompt, max_new=max_new,
                                        eos_id=opts.get("eos"),
                                        trace_ctx=tctx)
        except Overloaded as e:
            return False, "overloaded: %s" % e
        except MXNetError as e:
            return False, str(e)
        # like PREDICT: stay inside the client's recv window so a slow
        # generation sheds with an explicit reply, not a dead socket
        timeout = max(1.0, result_timeout(None) - 2.0)
        deadline = _fault.Deadline(timeout)
        try:
            if opts.get("stream") and stream_fn is not None:
                sent = 0
                while not deadline.expired():
                    chunk, done = pending.wait_new(sent, timeout=0.25)
                    if chunk:
                        stream_fn(sent, [int(t) for t in chunk])
                        sent += len(chunk)
                    if done:
                        break
            tokens = pending.result(timeout=max(0.001,
                                                deadline.remaining()))
        except Exception as e:
            return False, "generate failed: %s: %s" % (type(e).__name__,
                                                       e)
        return True, (eng.version, [int(t) for t in tokens])

    def health(self) -> Dict:
        reg = _telemetry.registry
        try:
            sv = self.host.active()
            status: Dict = {"status": "serving", "version": sv.version,
                            "model": sv.name,
                            "buckets": list(sv.buckets.sizes),
                            "retraces": sv.retraces,
                            "bucket_hits": sv.bucket_hits}
        except MXNetError:
            status = {"status": "empty", "version": 0}
        # multi-model packing (ISSUE 20): per-model versions/footprints
        # against the HBM budget, so the fleet can see what this
        # replica co-hosts and how much headroom it has left
        models = self.host.models()
        if len(models) > 1 or self.host.hbm_budget > 0 or \
                self.host.engines:
            status["packing"] = self.host.packing_report()
        if self.decode is not None:
            # a decode-only replica is serving even with an empty host
            dsv = self.decode.servable
            status["status"] = "serving"
            status["decode"] = {
                "model": dsv.name, "version": dsv.version,
                "engine": getattr(dsv, "engine", "flat"),
                "slots": dsv.config.slots,
                "active": self.decode.active_count(),
                "queued": self.decode.queue_depth(),
                "slot_buckets": list(dsv.config.slot_buckets),
                "prompt_buckets": list(dsv.config.prompt_buckets),
                "retraces": dsv.retraces,
                "tokens": reg.value("serve.decode.tokens"),
                "sequences": reg.value("serve.decode.sequences"),
            }
            # paged engine (ISSUE 18): page-level admission headroom +
            # prefix-sharing savings ride the same health dict
            page_stats = self.decode.page_stats()
            if page_stats is not None:
                status["decode"].update(page_stats)
        if self._draining.is_set():
            # a draining replica still ANSWERS (in-flight work, probes)
            # but must advertise that it admits nothing new
            status["status"] = "draining"
        status.update({
            "queue_rows": self.batcher.queue_rows(),
            "requests": reg.value("serve.requests"),
            "rejected": reg.value("serve.rejected"),
            "batches": reg.value("serve.batches"),
            "pid": os.getpid(),
        })
        return status

    def swap(self, prefix: str, epoch: int,
             input_names: Sequence[str]) -> int:
        """Load ``prefix`` as version N+1, warm it with the active
        version's signature, flip, drain — the wire face of
        ``ModelHost.deploy``."""
        new_version = self.host.version + 1
        kw = {}
        cur_name = self.host.default_model
        if cur_name is not None:
            # a SWAP replaces the DEFAULT model's version chain — same
            # name, next version — not a new co-hosted model (add_model
            # is the multi-model admission path)
            kw["name"] = cur_name
        sv = Servable.from_checkpoint(prefix, epoch=epoch,
                                     input_names=input_names,
                                     version=new_version, **kw)
        example = None
        try:
            want = self.host.active().warmed_signature
            if want is not None:
                import numpy as _np
                example = [_np.zeros((1,) + trail, dtype=dt)
                           for trail, dt in want]
        except MXNetError:
            pass
        self.host.deploy(sv, example=example)
        return new_version

    def close(self) -> None:
        self.batcher.close()
        if self.decode is not None:
            self.decode.close()


def serve_forever(port: Optional[int] = None,
                  state: Optional[ServeServer] = None,
                  ready_file: Optional[str] = None,
                  stop_event: Optional[threading.Event] = None,
                  abort_event: Optional[threading.Event] = None) -> None:
    """Run one serving replica's accept loop (modeled on
    ``kvstore.server.serve_forever``: threaded handlers, graceful STOP
    drain, surviving connections severed on the way out).

    ``abort_event`` is the chaos hook for in-process tests: setting it
    severs the listener and every live connection IMMEDIATELY — no
    drain, no replies — which is what a killed replica looks like to
    its clients (the subprocess lane uses the ``serve.request`` crash
    fault instead).
    """
    port = int(port if port is not None else get_env("MX_SERVE_PORT"))
    server_state = state or ServeServer()
    stop_event = stop_event or threading.Event()
    abort_event = abort_event or threading.Event()
    inflight_count = [0]
    inflight_lock = threading.Lock()
    conns = set()
    conns_lock = threading.Lock()

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            with conns_lock:
                conns.add(self.request)
            try:
                self._serve()
            finally:
                with conns_lock:
                    conns.discard(self.request)

        def _serve(self):
            while not abort_event.is_set():
                try:
                    msg = recv_msg(self.request, idle_block=True)
                except (ConnectionError, OSError, TimeoutError):
                    return
                with inflight_lock:
                    inflight_count[0] += 1
                sock = self.request

                def stream_fn(offset, tokens):
                    # token chunks of a streaming GENERATE ride ahead
                    # of the terminal reply on the same connection
                    send_msg(sock, ("STREAM", offset, tokens))

                try:
                    _fault.fire("serve.request")
                    ok, payload = server_state.handle_request(
                        msg, stream_fn=stream_fn)
                except SystemExit:      # injected crash: die mid-request
                    os._exit(17)
                except (_fault.FaultError, WireCodecError) as e:
                    # malformed wire frame: decoders raise before any
                    # state is touched, so reply a typed refusal on the
                    # same connection instead of severing it
                    ok, payload = False, str(e)
                finally:
                    with inflight_lock:
                        inflight_count[0] -= 1
                try:
                    send_msg(self.request, (ok, payload))
                except (ConnectionError, OSError):
                    return
                inner = msg[3] if isinstance(msg, tuple) and msg and \
                    msg[0] == "SEQ" else msg
                if inner and inner[0] == "STOP":
                    stop_event.set()
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    def _sever():
        with conns_lock:
            leftover = list(conns)
        for c in leftover:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    with Server(("0.0.0.0", port), Handler) as srv:
        if ready_file:
            with open(ready_file, "w") as f:
                f.write("%d" % srv.server_address[1])
        t = threading.Thread(target=srv.serve_forever, daemon=True,
                             name="mx-serve-accept")
        t.start()
        # idle until STOP (a replica's lifetime), a completed/expired
        # DRAIN retirement (ISSUE 17), or the chaos abort — the
        # supervisor owns killing an abandoned replica
        drain_overrun = False
        while not stop_event.is_set() and not abort_event.is_set():
            stop_event.wait(timeout=0.1)
            if server_state.draining:
                with inflight_lock:
                    wire_busy = inflight_count[0]
                if wire_busy == 0 and server_state.drain_idle():
                    break                   # drained clean: exit 0
                if server_state.drain_expired():
                    # bounded deadline passed with stragglers still in
                    # flight: sever them WITHOUT replies so their
                    # clients fail over and re-prefill on a survivor —
                    # the mid-generation-kill story, stragglers only
                    drain_overrun = True
                    break
        if drain_overrun:
            _sever()
            srv.shutdown()
            server_state.close()
            return
        if abort_event.is_set():
            # simulated crash: live connections die FIRST (no drain, no
            # replies — socketserver's shutdown() can block up to its
            # 0.5s poll interval, and a "killed" replica must not keep
            # answering in-flight requests through that window), then
            # the listener stops
            _sever()
            srv.shutdown()
            server_state.close()
            return
        srv.shutdown()                      # stop accepting
        drain_deadline = _fault.Deadline(5.0)
        while not drain_deadline.expired():
            with inflight_lock:
                if inflight_count[0] == 0:
                    break
            _fault.sleep(0.02)
        server_state.close()
        _sever()
