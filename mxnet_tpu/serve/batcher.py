"""Dynamic micro-batcher: admission queue → pad-to-bucket → one dispatch
→ scatter.

The serving latency/throughput trade lives here (TF-Serving's batching
layer, arxiv 1605.08695 §3.3): requests admit into a BOUNDED queue, the
batcher thread coalesces up to ``MX_SERVE_MAX_BATCH`` rows — holding an
under-full batch open at most ``MX_SERVE_MAX_DELAY_US`` for more
arrivals — pads the coalesced rows up to the smallest AOT bucket, and
issues ONE device dispatch.  Responses scatter back to the waiting RPC
handler threads through per-request futures; the device→host read
happens on the *handler* thread (the batcher never syncs — it is
already collecting the next batch while XLA runs this one).

Backpressure is explicit: a submit that would push the queue past
``MX_SERVE_QUEUE_CAP`` rows raises :class:`Overloaded` immediately
(counted in ``serve.rejected``) instead of absorbing load into
unbounded latency.

Concurrency/lint contract: ``Batcher._loop`` / ``_collect`` /
``_dispatch`` are mxlint hot-path roots — no host sync may land between
dequeue and dispatch (tools/mxlint rules.py HOT_PATH_ROOTS; the
reinjection test in tests/test_mxlint.py proves a blocking host read
there trips the rule).  The coalescing window rides the
``mxnet_tpu.fault`` injectable clock, so virtual-time tests drive it
deterministically — under ``use_virtual_time()`` the batcher charges
its wait ticks to the virtual clock the way the kvstore barrier park
does.

Telemetry: per-request ``queue_wait``, per-batch ``pad`` and
``serve_dispatch`` phases land in ``step_phase_seconds``; the
``serve_dispatch`` span carries one instant event per member request
with the request's wire-propagated (trace_id, span_id), so the merged
chrome trace shows client → batcher → dispatch as one causal chain.
``scatter`` is stamped by the future's resolver on the handler thread.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import telemetry as _telemetry

__all__ = ["Overloaded", "Batcher", "result_timeout"]


class Overloaded(MXNetError):
    """Admission rejected: the bounded queue is full (load shedding).

    Shared by both admission paths — this micro-batcher's PREDICT queue
    and the decode engine's generation queue (:mod:`.decode`, which
    batches per decode STEP instead of per request)."""


def result_timeout(timeout: Optional[float]) -> float:
    """Resolve a caller's request-wait bound: explicit value, else
    ``MX_SERVE_TIMEOUT`` — one rule for PREDICT futures and GENERATE
    pendings, so the client/server timeout budget stays consistent."""
    if timeout is not None:
        return float(timeout)
    return get_env("MX_SERVE_TIMEOUT", 30.0, float) or 30.0


class _Batch:
    """One dispatched micro-batch's device outputs, converted to host
    numpy AT MOST ONCE (first resolver pays the sync; the rest slice)."""

    __slots__ = ("_outs", "_np", "_lk", "version")

    def __init__(self, outs, version: int):
        self._outs = outs
        self._np: Optional[List[_np.ndarray]] = None
        self._lk = threading.Lock()
        self.version = version

    def host(self) -> List[_np.ndarray]:
        with self._lk:
            if self._np is None:
                self._np = [_np.asarray(o) for o in self._outs]
                self._outs = None
            return self._np


class _Pending:
    """One admitted request: inputs + a future the handler thread waits
    on.  Fulfilled by the batcher thread with (batch, row span)."""

    __slots__ = ("inputs", "rows", "sig", "trace_ctx", "enq_t",
                 "_event", "_lk", "_batch", "_span", "_err")

    def __init__(self, inputs: List[_np.ndarray], rows: int, sig: Tuple,
                 trace_ctx: Optional[Tuple[str, str]] = None):
        self.inputs = inputs
        self.rows = rows
        self.sig = sig
        self.trace_ctx = trace_ctx
        self.enq_t = time.perf_counter()
        self._event = threading.Event()
        self._lk = threading.Lock()
        self._batch: Optional[Tuple[_Batch, int, int]] = None
        self._err: Optional[BaseException] = None

    def _fulfill(self, batch: _Batch, start: int, stop: int) -> None:
        with self._lk:
            self._batch = (batch, start, stop)
        self._event.set()

    def _fail(self, err: BaseException) -> None:
        with self._lk:
            self._err = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None
               ) -> Tuple[int, List[_np.ndarray]]:
        """Block (bounded) for the dispatch, then scatter this request's
        rows out of the batch outputs: returns (version, [out_leaf...]).
        The device→host sync happens HERE, on the caller's thread."""
        timeout = result_timeout(timeout)
        if not self._event.wait(timeout=timeout):
            raise MXNetError("serve: request timed out after %.3gs in "
                             "the batcher" % timeout)
        with self._lk:
            err, ent = self._err, self._batch
        if err is not None:
            raise err
        batch, start, stop = ent
        with _telemetry.phase("scatter"):
            outs = [leaf[start:stop] for leaf in batch.host()]
        return batch.version, outs


class Batcher:
    """The dispatch loop: one daemon thread per serving process."""

    def __init__(self, host, max_batch: Optional[int] = None,
                 max_delay_us: Optional[float] = None,
                 queue_cap: Optional[int] = None,
                 on_tick=None, autostart: bool = True,
                 model: Optional[str] = None):
        self._host = host
        self._model = model
        self._max_batch = int(max_batch if max_batch is not None else
                              get_env("MX_SERVE_MAX_BATCH", 16, int))
        delay_us = max_delay_us if max_delay_us is not None else \
            get_env("MX_SERVE_MAX_DELAY_US", 2000.0, float)
        self._max_delay = max(0.0, float(delay_us) / 1e6)
        self._cap = int(queue_cap if queue_cap is not None else
                        get_env("MX_SERVE_QUEUE_CAP", 256, int))
        self._on_tick = on_tick
        self._q: deque = deque()
        self._qrows = 0
        self._cv = threading.Condition()
        self._stop = threading.Event()
        reg = _telemetry.registry
        self._c_requests = reg.counter(
            "serve.requests", doc="admitted predict requests")
        self._c_rejected = reg.counter(
            "serve.rejected", doc="requests shed at admission "
            "(queue cap) or refused (too large / bad signature)")
        self._c_rows = reg.counter(
            "serve.rows", doc="admitted request rows (examples)")
        self._c_pad_rows = reg.counter(
            "serve.padding_rows", doc="pad rows dispatched (bucket "
            "minus occupancy — the padding waste)")
        self._g_depth = reg.gauge(
            "serve.queue_rows", doc="rows currently queued")
        self._h_occupancy = reg.histogram(
            "serve.batch_occupancy", doc="real rows per dispatched "
            "micro-batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="mx-serve-batcher")
        if autostart:
            self._thread.start()

    # -- admission ----------------------------------------------------------
    def queue_rows(self) -> int:
        with self._cv:
            return self._qrows

    def submit(self, arrays: Sequence,
               trace_ctx: Optional[Tuple[str, str]] = None) -> _Pending:
        """Admit one request (per-input row-batched arrays).  Raises
        :class:`Overloaded` when the bounded queue is full, MXNetError
        when the request cannot ever be served (too many rows for the
        bucket table, signature mismatch with the warmed servable)."""
        from .servable import Servable
        inputs = [_np.ascontiguousarray(a) for a in arrays]
        if not inputs or any(i.ndim < 1 for i in inputs):
            self._c_rejected.inc()
            raise MXNetError("serve: a request needs >=1 row-batched "
                             "input array")
        rows = int(inputs[0].shape[0])
        if any(int(i.shape[0]) != rows for i in inputs):
            self._c_rejected.inc()
            raise MXNetError("serve: input leading (batch) dims disagree")
        sv = self._host.active(self._model)
        if sv.buckets.bucket_for(rows) is None:
            self._c_rejected.inc()
            raise MXNetError(
                "serve: request of %d rows exceeds the top bucket %d "
                "(MX_SERVE_BUCKETS)" % (rows, sv.buckets.max_size))
        sig = Servable.signature_of(inputs)
        want = sv.warmed_signature
        if want is not None and sig != want:
            self._c_rejected.inc()
            raise MXNetError(
                "serve: input signature %r does not match the deployed "
                "model's %r" % (sig, want))
        p = _Pending(inputs, rows, sig, trace_ctx=trace_ctx)
        with self._cv:
            if self._qrows + rows > self._cap:
                self._c_rejected.inc()
                raise Overloaded(
                    "serve: admission queue full (%d/%d rows; "
                    "MX_SERVE_QUEUE_CAP) - retry later or add replicas"
                    % (self._qrows, self._cap))
            self._q.append(p)
            self._qrows += rows
            self._g_depth.set(self._qrows)
            self._cv.notify_all()
        self._c_requests.inc()
        self._c_rows.inc(rows)
        # per-model labeled twins (ISSUE 20): the unlabeled aggregates
        # stay; fleet.py rolls the labeled series up per hosted model
        reg = _telemetry.registry
        lbl = {"model": sv.name}
        reg.counter("serve.requests", doc="admitted predict requests",
                    labels=lbl).inc()
        reg.counter("serve.rows", doc="admitted request rows "
                    "(examples)", labels=lbl).inc(rows)
        return p

    # -- the dispatch loop (mxlint hot-path root) ---------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self._collect()
            if batch:
                self._dispatch(batch)
            if self._on_tick is not None:
                self._on_tick()
        # drain on stop: refuse whatever is still queued so no handler
        # thread is left waiting on a future nobody will fulfill
        with self._cv:
            leftover = list(self._q)
            self._q.clear()
            self._qrows = 0
            self._g_depth.set(0)
        for p in leftover:
            p._fail(MXNetError("serve: batcher stopped"))

    def _effective_max(self) -> int:
        try:
            top = self._host.active(self._model).buckets.max_size
        except MXNetError:
            return self._max_batch
        return max(1, min(self._max_batch, top))

    def _collect(self) -> List[_Pending]:
        """Pop the next coalesced batch: same-signature requests from the
        queue head, up to the effective max rows, holding the window
        open ``max_delay`` for stragglers.  Returns [] on an idle tick
        (so the loop can heartbeat)."""
        eff = self._effective_max()
        with self._cv:
            if not self._q:
                self._cv.wait(timeout=0.05)
                if not self._q:
                    return []
            head = self._q[0]
            if self._max_delay > 0 and head.rows < eff:
                # hold the batch open for more arrivals — on the
                # injectable clock, so a virtual-time test drives the
                # window without real sleeping (the batcher is the
                # elected pumper for its own deadline, like the kvstore
                # barrier park)
                deadline = _fault.Deadline(self._max_delay)
                while not self._stop.is_set():
                    if self._qrows >= eff:
                        break
                    remaining = deadline.remaining()
                    if remaining <= 0:
                        break
                    tick = min(0.002, remaining)
                    if _fault.is_virtual():
                        self._cv.wait(timeout=0.001)
                        _fault.sleep(tick)
                    else:
                        self._cv.wait(timeout=tick)
            take: List[_Pending] = []
            taken = 0
            while self._q:
                p = self._q[0]
                if p.sig != head.sig and take:
                    break              # next batch gets the new shape
                if take and taken + p.rows > eff:
                    break
                self._q.popleft()
                take.append(p)
                taken += p.rows
                if taken >= eff:
                    break
            self._qrows -= taken
            self._g_depth.set(self._qrows)
            return take

    def _dispatch(self, take: List[_Pending]) -> None:
        """Pad the coalesced rows to the smallest bucket and launch ONE
        program; fulfill the members' futures with (batch, row span).
        No device→host read happens here — scatter syncs on the handler
        threads while this loop collects the next batch."""
        rows = sum(p.rows for p in take)
        sv = None
        while sv is None:
            sv = self._host.active(self._model)
            if not sv.begin():         # raced a hot-swap drain: re-read
                sv = None
        try:
            # admission validated against the servable that was active
            # THEN; a hot-swap may have changed the signature or bucket
            # table since.  Re-check here so a straggler can never
            # force a serve-time retrace (or a shape crash) through the
            # new version — it gets an explicit retryable error instead
            want = sv.warmed_signature
            if want is not None and take[0].sig != want:
                raise MXNetError(
                    "serve: model hot-swapped to an incompatible input "
                    "signature (%r -> %r) while this request was "
                    "queued; resubmit" % (take[0].sig, want))
            bucket = sv.buckets.bucket_for(rows)
            if bucket is None:
                raise MXNetError("serve: %d rows exceed the deployed "
                                 "bucket table" % rows)
            now_t = time.perf_counter()
            for p in take:
                _telemetry.observe_phase("queue_wait", now_t - p.enq_t)
            with _telemetry.phase("pad"):
                pad_rows = bucket - rows
                padded = []
                for i, (trail, dt) in enumerate(take[0].sig):
                    parts = [p.inputs[i] for p in take]
                    if pad_rows:
                        parts.append(_np.zeros((pad_rows,) + trail,
                                               dtype=dt))
                    padded.append(parts[0] if len(parts) == 1
                                  else _np.concatenate(parts, axis=0))
            with _telemetry.phase("serve_dispatch") as span:
                # link each member request's wire-propagated span into
                # the batch: req_trace/req_span (Span.event reserves the
                # bare trace_id/span_id names for the batch span's own)
                for p in take:
                    if p.trace_ctx is not None:
                        span.event("request", req_trace=p.trace_ctx[0],
                                   req_span=p.trace_ctx[1],
                                   rows=p.rows)
                outs = sv.dispatch(bucket, padded)
            self._h_occupancy.observe(rows)
            self._c_pad_rows.inc(pad_rows)
            _telemetry.registry.histogram(
                "serve.batch_occupancy", doc="real rows per dispatched "
                "micro-batch", labels={"model": sv.name},
                buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256)).observe(rows)
            batch = _Batch(outs, sv.version)
            offset = 0
            for p in take:
                p._fulfill(batch, offset, offset + p.rows)
                offset += p.rows
        except BaseException as e:
            for p in take:
                p._fail(e)
        finally:
            sv.release()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Batcher":
        if not self._thread.is_alive():
            self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
