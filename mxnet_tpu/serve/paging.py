"""Host-side paged KV-cache bookkeeping (ISSUE 18): the page allocator
and the prefix hash table behind the paged decode engine.

The device side of the paged pool is two donated heaps
``(layers, kv_pages, kv_page_len, heads, head_dim)`` owned by
:class:`~mxnet_tpu.serve.decode.PagedDecodeServable`; THIS module is
everything the pump needs to decide, without touching the device,
which physical pages a session's logical positions live in:

* :class:`PageAllocator` — free-list allocator over the heap's page
  ids with REFCOUNTED sharing.  Page 0 is reserved as the scratch page
  (padded decode lanes and masked prefill rows scatter into it, the
  paged analogue of the flat pool's scratch slot).  A released page
  whose content is published under a prefix hash is not freed — it
  parks in an LRU cache so a later session with the same prefix can
  adopt it; cached pages are reclaimed lazily when the free list runs
  dry.  Admission is therefore bounded by ``free_pages()`` (free +
  evictable), not by slot count.

* **Prefix hashing** — :func:`chain_hash` / :func:`page_hashes` roll a
  content hash over token ids at full-page boundaries.  ``hashes[i]``
  covers the ENTIRE prompt through page ``i``, so hash equality means
  the whole prefix is identical and the donor's KV pages can be
  adopted bit-for-bit (greedy decode stays exact).  Publication is
  strictly after the pages' prefill chunks have been dispatched
  (device-ordered), so an adopted page can never be read before it is
  written.

Concurrency: the pump thread is the only mutator; handler threads read
:meth:`PageAllocator.stats` for the health/fleet surface, so every
public method takes the allocator lock.  All methods are mxlint
hot-path roots (they sit between dequeue and dispatch in the pump) —
no host sync, no device touch, pure python/numpy bookkeeping.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from ..base import MXNetError

__all__ = ["PageAllocator", "chain_hash", "page_hashes",
           "SCRATCH_PAGE"]

#: page id 0 is never allocated: padded decode lanes and masked prefill
#: rows need somewhere harmless to scatter (the flat engine's scratch
#: slot, shrunk to one page)
SCRATCH_PAGE = 0

# 61-bit Mersenne-prime rolling hash: cheap in python ints, collision
# odds ~2^-61 per pair — and a collision only ever SHARES a page
# between prefixes, it cannot corrupt one, so the failure mode is a
# wrong (but deterministic) generation caught by the parity tests
_HASH_MOD = (1 << 61) - 1
_HASH_MULT = 1048583
HASH_SEED = 1469598103


def chain_hash(prev: int, tokens: Sequence[int]) -> int:
    """Extend a rolling content hash over ``tokens``.  Chained page by
    page, so equal hashes mean the ENTIRE prefix matches, not just the
    last page."""
    h = int(prev)
    for t in tokens:
        h = (h * _HASH_MULT + int(t) + 1) % _HASH_MOD
    return h


def page_hashes(prompt: Sequence[int], page_len: int) -> List[int]:
    """Chain hash at every FULL-page boundary of ``prompt``:
    ``hashes[i]`` covers ``prompt[:(i + 1) * page_len]``.  A trailing
    partial page is never hashed — only read-only full pages are
    shareable."""
    out: List[int] = []
    h = HASH_SEED
    for i in range(len(prompt) // page_len):
        h = chain_hash(h, prompt[i * page_len:(i + 1) * page_len])
        out.append(h)
    return out


class PageAllocator:
    """Refcounted free-list allocator over ``n_pages`` physical page
    ids (page 0 reserved: the scratch page).

    Lifecycle of one page::

        FREE --alloc--> HELD(ref=1) --publish--> HELD+HASHED
          ^                |  ^                      |
          |          release|  +--lookup (ref+=1) ---+ ... ref drops
          |                v                         v
          +---------- (unhashed)              CACHED (ref=0, in LRU)
          +<------- evicted when the free list runs dry ------+
    """

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise MXNetError("PageAllocator: need >= 2 pages (page 0 "
                             "is the reserved scratch page)")
        self.n_pages = int(n_pages)
        self._lk = threading.Lock()
        self._free: List[int] = list(range(self.n_pages - 1, 0, -1))
        self._refs: List[int] = [0] * self.n_pages
        self._page_of_hash: Dict[int, int] = {}
        self._hash_of_page: Dict[int, int] = {}
        # cached pages: ref == 0 but hashed; OrderedDict as an LRU
        # (oldest first -> evicted first)
        self._lru: "OrderedDict[int, bool]" = OrderedDict()
        self.shared_hits = 0          # lookup() hits (pages adopted)
        self.evictions = 0            # cached pages reclaimed

    # -- allocation (pump thread; mxlint hot-path root) ---------------------
    def alloc(self, k: int) -> Optional[List[int]]:
        """Take ``k`` pages (ref = 1 each), evicting cached prefix
        pages LRU-first if the free list runs dry.  Returns None —
        allocating NOTHING — when even eviction cannot cover ``k``:
        admission then waits, it never half-allocates."""
        with self._lk:
            if k > len(self._free) + len(self._lru):
                return None
            out: List[int] = []
            for _ in range(k):
                if self._free:
                    page = self._free.pop()
                else:
                    page, _ = self._lru.popitem(last=False)
                    h = self._hash_of_page.pop(page)
                    self._page_of_hash.pop(h, None)
                    self.evictions += 1
                self._refs[page] = 1
                out.append(page)
            return out

    def lookup(self, chain_h: int) -> Optional[int]:
        """Adopt the page published under ``chain_h`` (ref += 1), or
        None.  A cached page leaves the LRU — it is live again."""
        with self._lk:
            page = self._page_of_hash.get(chain_h)
            if page is None:
                return None
            self._refs[page] += 1
            self._lru.pop(page, None)
            self.shared_hits += 1
            return page

    def publish(self, chain_h: int, page: int) -> bool:
        """Expose a HELD page's content under its prefix hash.  First
        writer wins: if the hash is already published (a concurrent
        admission of the same prefix), the existing donor keeps it and
        this page simply stays private."""
        with self._lk:
            if chain_h in self._page_of_hash or page in self._hash_of_page:
                return False
            self._page_of_hash[chain_h] = page
            self._hash_of_page[page] = chain_h
            return True

    def release(self, page: int) -> None:
        """Drop one reference.  At ref 0 a hashed page parks in the
        LRU cache (still adoptable); an unhashed one returns to the
        free list."""
        with self._lk:
            r = self._refs[page] - 1
            if r < 0:
                raise MXNetError("PageAllocator: double release of "
                                 "page %d" % page)
            self._refs[page] = r
            if r == 0:
                if page in self._hash_of_page:
                    self._lru[page] = True
                else:
                    self._free.append(page)

    # -- read-only surface (any thread) -------------------------------------
    def free_pages(self) -> int:
        """Admission headroom: truly-free pages plus evictable cached
        ones."""
        with self._lk:
            return len(self._free) + len(self._lru)

    def shared_extra_refs(self) -> int:
        """Pages of HBM that sharing is currently saving: every
        reference past the first on a hashed page is a prefill the
        adopter did not pay and a page it did not allocate."""
        with self._lk:
            return sum(self._refs[p] - 1 for p in self._hash_of_page
                       if self._refs[p] > 1)

    def stats(self) -> Dict[str, int]:
        with self._lk:
            cached = len(self._lru)
            return {
                "n_pages": self.n_pages,
                "free": len(self._free) + cached,
                "cached": cached,
                "held": self.n_pages - 1 - len(self._free) - cached,
                "hashed": len(self._hash_of_page),
                "shared_hits": self.shared_hits,
                "evictions": self.evictions,
            }
