"""``python -m mxnet_tpu.serve`` — run one serving replica.

The process face of the serving engine: load a servable (an exported /
foreign ``<prefix>-symbol.json`` + ``.params`` checkpoint, or the
built-in deterministic demo model), AOT-warm every batch bucket, then
serve PREDICT/HEALTH/SWAP on a TCP port until a STOP arrives.

Multi-replica serving rides ``tools/launch.py``: with ``--port-base P``
each supervised rank binds ``P + MX_PROCESS_ID``, and when the launcher
provisions ``MX_HEARTBEAT_FILE`` the batcher loop beats it (throttled)
so ``--hang-timeout`` health-gates restarts — a wedged replica is
killed and respawned with its original env, a crashed one (e.g. the
``serve.request`` chaos fault) restarts and warms back up while clients
fail over to the survivors.

Examples::

  python -m mxnet_tpu.serve --demo --port 9700
  python -m mxnet_tpu.serve --decode --port 9700     # GENERATE lane
  python tools/launch.py -n 2 --restart on-failure -- \\
      python -m mxnet_tpu.serve --demo --port-base 9700
  python -m mxnet_tpu.serve --model /ckpt/resnet --epoch 3 \\
      --inputs data --example-shape 3,224,224
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time

import numpy as _np


def _build_servables(args):
    """Every --demo/--demo-conv/--model spec as (servable, example) —
    multi-model co-hosting (ISSUE 20): the FIRST spec is the default
    model, the rest are admitted through ``ServeServer.add_model``
    under the MX_SERVE_HBM_BUDGET packer and addressed by the wire
    envelope's model field."""
    from .servable import BucketTable, Servable
    buckets = BucketTable([int(b) for b in args.buckets.split(",")]) \
        if args.buckets else None
    specs = []
    if args.demo:
        from .demo import demo_block, demo_example
        specs.append((Servable(demo_block(), name="demo-mlp",
                               version=1, buckets=buckets),
                      demo_example()))
    if args.demo_conv:
        from .demo import demo_conv_block, demo_conv_example
        specs.append((Servable(demo_conv_block(), name="demo-conv",
                               version=1, buckets=buckets),
                      demo_conv_example()))
    for prefix in (args.model or ()):
        sv = Servable.from_checkpoint(prefix, epoch=args.epoch,
                                      input_names=args.inputs.split(","),
                                      version=1, buckets=buckets)
        if not args.example_shape:
            raise SystemExit("serve: --model needs --example-shape "
                             "(comma dims per input, ';' between "
                             "inputs)")
        example = []
        for part in args.example_shape.split(";"):
            trail = tuple(int(d) for d in part.split(",") if d.strip())
            example.append(_np.zeros((1,) + trail,
                                     _np.dtype(args.dtype)))
        specs.append((sv, example))
    return specs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--model", action="append", default=None,
                    metavar="PREFIX",
                    help="checkpoint prefix (PREFIX-symbol.json + "
                         "PREFIX-%%04d.params, the export/foreign "
                         "lane); repeatable — extra models co-host on "
                         "this replica under MX_SERVE_HBM_BUDGET and "
                         "route by the wire envelope's model field")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--inputs", default="data",
                    help="comma-separated model input names")
    ap.add_argument("--example-shape", default=None, metavar="DIMS",
                    help="per-row input dims, e.g. '3,224,224' "
                         "(';'-separated for multi-input models)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--demo", action="store_true",
                    help="serve the built-in deterministic demo MLP "
                         "(smokes/benches; tools/serve_load.py verifies "
                         "its outputs)")
    ap.add_argument("--demo-conv", action="store_true",
                    help="serve the compile-heavy deterministic conv "
                         "demo (resnet18 @ 64x64) — the warm-spawn "
                         "bench lane's compile-bound replica")
    ap.add_argument("--decode", action="store_true",
                    help="also host the deterministic demo LM behind "
                         "the GENERATE verb (continuous-batching "
                         "decode engine; can serve alone or alongside "
                         "--demo)")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--port-base", type=int, default=None,
                    help="bind port-base + MX_PROCESS_ID (multi-replica "
                         "serving under tools/launch.py)")
    ap.add_argument("--buckets", default=None,
                    help="override MX_SERVE_BUCKETS for this replica")
    ap.add_argument("--ready-file", default=None,
                    help="write the bound port here once accepting")
    args = ap.parse_args(argv)

    from ..base import get_env
    from ..health import Heartbeat
    from .server import ServeServer, serve_forever

    port = args.port
    if port is None and args.port_base is not None:
        rank = int(get_env("MX_PROCESS_ID") or
                   os.environ.get("DMLC_WORKER_ID") or 0)
        port = args.port_base + rank
    if port is None:
        port = int(get_env("MX_SERVE_PORT"))

    # heartbeat-file liveness (launch.py --hang-timeout): beat from the
    # batcher loop, throttled — an IDLE replica is healthy, so the beat
    # must not depend on traffic
    tick = None
    hb_path = get_env("MX_HEARTBEAT_FILE", "")
    if hb_path:
        hb = Heartbeat(hb_path)
        last = [0.0]

        def tick():
            now = time.monotonic()
            if now - last[0] >= 1.0:
                last[0] = now
                hb.beat(0, 0)

        hb.beat(0, 0)

    decode_engine = None
    t_warm0 = time.perf_counter()
    if args.decode:
        # the GENERATE lane: demo LM + continuous-batching decode pump
        # (ISSUE 15); warm() pre-builds every prefill/decode bucket so
        # serve time pays zero traces.  MX_SERVE_KV_PAGES > 0 selects
        # the PAGED engine (ISSUE 18): shared page heap + block tables,
        # hash-shared prefixes, chunked prefill — same wire surface.
        paged = int(get_env("MX_SERVE_KV_PAGES", 0, int) or 0) > 0
        draft_layers = int(get_env("MX_SERVE_DRAFT", 0, int) or 0)
        if draft_layers > 0:
            # speculative decoding (ISSUE 20): a shallow draft proposes
            # MX_SERVE_SPEC_K tokens per window, the paged target
            # verifies them in ONE multi-position dispatch; co-hosted
            # draft+target share the page heap budget
            if not paged:
                raise SystemExit("serve: MX_SERVE_DRAFT needs the "
                                 "paged engine (set MX_SERVE_KV_PAGES)")
            from .decode import (DecodeConfig, DraftDecodeServable,
                                 PagedDecodeServable,
                                 SpeculativeDecodeBatcher,
                                 demo_spec_pair)
            cfg = DecodeConfig()
            tparams, dcfg, dparams = demo_spec_pair(
                cfg, draft_layers=draft_layers)
            decode_engine = SpeculativeDecodeBatcher(
                PagedDecodeServable(params=tparams, config=cfg),
                DraftDecodeServable(params=dparams, config=dcfg,
                                    name="demo-lm-draft"),
                on_tick=tick)
        elif paged:
            from .decode import PagedDecodeBatcher, PagedDecodeServable
            decode_engine = PagedDecodeBatcher(PagedDecodeServable(),
                                               on_tick=tick)
        else:
            from .decode import DecodeBatcher, DecodeServable
            decode_engine = DecodeBatcher(DecodeServable(),
                                          on_tick=tick)
    state = ServeServer(on_tick=tick, decode=decode_engine)
    sv = None
    specs = _build_servables(args)
    if specs:
        sv, example = specs[0]
        state.host.deploy(sv, example=example)
        for extra_sv, extra_ex in specs[1:]:
            state.add_model(extra_sv, example=extra_ex, on_tick=tick)
    elif not args.decode:
        raise SystemExit("serve: need --model PREFIX, --demo or "
                         "--decode")
    warm_s = time.perf_counter() - t_warm0
    # warm-start visibility (ISSUE 13): with MX_COMPILE_CACHE set, a
    # respawned replica deserializes its whole bucket table instead of
    # compiling it — the banner (and the METRICS verb the fleet/bench
    # scrape) carries the receipts
    from ..compile_cache import stats as _cc_stats
    cs = _cc_stats()
    if sv is not None:
        print("serve: %s v%d warm on %d bucket(s) %r in %.2fs "
              "(compile-cache%s hits=%d misses=%d), port %d"
              % (sv.name, sv.version, len(sv.buckets.sizes),
                 list(sv.buckets.sizes), warm_s,
                 "" if cs["enabled"] else " off",
                 cs["hits"], cs["misses"], port),
              file=sys.stderr, flush=True)
        if len(specs) > 1:
            rep = state.host.packing_report()
            print("serve: co-hosting %d models %r (used=%d budget=%s)"
                  % (len(rep["models"]), sorted(rep["models"]),
                     rep["used_bytes"],
                     rep["hbm_budget_bytes"] or "off"),
                  file=sys.stderr, flush=True)
    if decode_engine is not None:
        dsv = decode_engine.servable
        ps = decode_engine.page_stats()
        if ps is not None:
            spec = ""
            if ps.get("engine") == "speculative":
                spec = ", speculative: k=%d draft=%s" \
                    % (ps["spec_k"], ps["draft_model"])
            print("serve: decode %s v%d warm (paged: %d pages x %d "
                  "tok, chunk=%d, share=%s%s) in %.2fs (slots=%d, "
                  "max_tokens=%d), port %d"
                  % (dsv.name, dsv.version, ps["kv_pages"],
                     ps["kv_page_len"], ps["prefill_chunk"],
                     "on" if ps["prefix_share"] else "off", spec,
                     warm_s, dsv.config.slots, dsv.config.max_tokens,
                     port),
                  file=sys.stderr, flush=True)
        else:
            print("serve: decode %s v%d warm on %d prompt + %d slot "
                  "bucket(s) in %.2fs (slots=%d, max_tokens=%d, "
                  "page=%d), port %d"
                  % (dsv.name, dsv.version,
                     len(dsv.config.prompt_buckets),
                     len(dsv.config.slot_buckets), warm_s,
                     dsv.config.slots, dsv.config.max_tokens,
                     dsv.config.page, port),
                  file=sys.stderr, flush=True)

    serve_forever(port=port, state=state, ready_file=args.ready_file)
    print("serve: stopped", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
