"""Inference serving engine (ISSUE 9 tentpole).

The ROADMAP's north star is "heavy traffic from millions of users", and
until this package every code path in the repo terminated in ``fit()``.
``mxnet_tpu.serve`` is the subsystem that turns the training stack into
the product: it hosts trained models behind an RPC front and keeps the
accelerator busy with batched, AOT-compiled forward programs.

Architecture (TensorFlow-Serving's shape — arxiv 1605.08695 — rebuilt
over this repo's own substrates):

* **Servable** (:mod:`.servable`) — one immutable model *version*:
  parameters + an AOT **bucketed program table**.  Loading reuses the
  existing import lanes (a live Gluon block, a ``save_parameters`` file,
  or a foreign ``symbol.json`` + ``.params`` pair via
  ``SymbolBlock.imports``); compilation reuses the ``CompiledStep``
  trace machinery forward-only (param-override trace of the block under
  ``autograd.predict_mode``), pre-traced at every configured batch-size
  bucket (``MX_SERVE_BUCKETS``) so **no request ever pays a trace at
  serve time**.

* **ModelHost** (:mod:`.servable`) — versioned hot-swap: load v(N+1),
  warm every bucket, atomically flip the active pointer, drain v(N)'s
  in-flight dispatches.  A request only ever sees a fully-warmed
  version.

* **Batcher** (:mod:`.batcher`) — the dynamic micro-batcher: bounded
  admission queue → coalesce up to ``MX_SERVE_MAX_BATCH`` rows or
  ``MX_SERVE_MAX_DELAY_US`` → pad to bucket → ONE dispatch → scatter
  responses to the waiting handler threads.  Overload is an explicit
  rejection at admission (``MX_SERVE_QUEUE_CAP``), never unbounded
  latency.  The dispatch loop is an mxlint hot-path root: no host sync
  may land between dequeue and dispatch.

* **RPC front** (:mod:`.server` / :mod:`.client`) — PREDICT / HEALTH /
  SWAP / STOP verbs over the kvstore SEQ-retry wire envelope
  (length-prefixed pickles, numpy-only tensors via
  ``kvstore.wire_codec.encode_array``), with the exactly-once replay
  cache and wire-propagated trace context, so one request is one causal
  trace client → batcher → dispatch.  The client fails over across
  ``MX_SERVE_ROOTS`` replicas.

* **Multi-replica serving** — ``python -m mxnet_tpu.serve`` runs one
  replica; under ``tools/launch.py --restart on-failure`` each rank
  serves on ``--port-base + rank``, beats its ``MX_HEARTBEAT_FILE``
  from the batcher loop (health-gated restarts), and the chaos smoke
  (tools/chaos_smoke.sh) kills one of two replicas mid-load proving
  traffic drains to the survivor with zero lost requests.

* **Fleet front-tier** (:mod:`.router`, ISSUE 17) — the decode-aware
  session router: one address fronting a DYNAMIC replica set, speaking
  the same SEQ wire surface and forwarding client envelopes verbatim
  (so the replicas' exactly-once replay caches keep working end-to-end
  with zero router-side replay state).  Sessions pin to a replica
  (moving a decode session costs a re-prefill), routing reads the
  fleet collector's merged load signals, and replica retirement is a
  first-class DRAIN — stop admitting, finish in-flight, sever only the
  stragglers past a bounded deadline.  ``tools/launch.py --route``
  supervises router + replicas and ``--autoscale MIN:MAX`` resizes the
  fleet against SLO burn with hysteresis.

* **Autoregressive decode** (:mod:`.decode`, ISSUE 15) — the
  sequence-generation workload behind the GENERATE verb: prefill and
  decode as separately bucketed AOT programs, a device-resident
  donated KV-cache pool (owner-tagged ``kv_cache`` in the buffer
  census, flat HBM across generations), and CONTINUOUS batching — the
  decode pump admits and retires sequences per decode step, not per
  request, so long generations never block short ones.
"""
from __future__ import annotations

from .servable import BucketTable, ModelHost, Servable
from .batcher import Batcher, Overloaded
from .server import ServeServer, serve_forever
from .client import ServeClient
from .decode import DecodeBatcher, DecodeConfig, DecodeServable

__all__ = ["BucketTable", "Servable", "ModelHost", "Batcher",
           "Overloaded", "ServeServer", "serve_forever", "ServeClient",
           "DecodeBatcher", "DecodeConfig", "DecodeServable",
           "ServeRouter", "serve_router_forever"]


def __getattr__(name):
    # lazy (PEP 562): ``python -m mxnet_tpu.serve.router`` must not
    # find the router module pre-imported by its own package (runpy's
    # double-execution warning), so the package face resolves these on
    # first touch instead of at import
    if name in ("ServeRouter", "serve_router_forever"):
        from . import router
        return getattr(router, name)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
