"""Servables: versioned models behind AOT-compiled bucketed programs.

Reference: TF-Serving's loader/servable/version-manager split (arxiv
1605.08695 §3) — a *servable* is one immutable version of one model; the
*host* owns the version lifecycle (load → warm → flip → drain).  The
compilation lane reuses the repo's whole-step trace machinery
(``CompiledStep._make_forward``'s param-override trace) forward-only:
the block runs once under ``autograd.predict_mode`` per (bucket, input
signature) to build a jitted program, and every configured batch bucket
is pre-traced at deploy time (:meth:`Servable.warm`) so serve time is
pure cached-executable dispatch — the ``serve.retraces`` counter pins
"zero retraces after warmup" in bench and the dispatch-budget harness.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import telemetry as _telemetry

__all__ = ["BucketTable", "Servable", "ModelHost", "BudgetExceeded"]


class BudgetExceeded(MXNetError):
    """Raised when admitting a servable would bust the host's HBM
    budget (``MX_SERVE_HBM_BUDGET``) — the wire layer maps this to the
    typed in-band ``(False, "budget: ...")`` refusal, so a client can
    tell "this replica is full" from a crash."""


class BucketTable:
    """The configured batch-size buckets, sorted ascending.

    ``bucket_for(n)`` returns the smallest bucket >= n (pad-to-bucket
    target), or None when n exceeds the top bucket — the admission path
    rejects those instead of compiling an unplanned shape at serve time.
    """

    def __init__(self, sizes: Sequence[int]):
        uniq = sorted({int(s) for s in sizes})
        if not uniq or uniq[0] < 1:
            raise MXNetError("BucketTable needs positive bucket sizes, "
                             "got %r" % (sizes,))
        self.sizes: Tuple[int, ...] = tuple(uniq)

    @classmethod
    def from_env(cls) -> "BucketTable":
        raw = get_env("MX_SERVE_BUCKETS") or "1,2,4,8,16"
        return cls([int(p) for p in str(raw).split(",") if p.strip()])

    @property
    def max_size(self) -> int:
        return self.sizes[-1]

    def bucket_for(self, n: int) -> Optional[int]:
        for s in self.sizes:
            if s >= n:
                return s
        return None

    def __iter__(self):
        return iter(self.sizes)

    def __repr__(self):
        return "BucketTable%r" % (self.sizes,)


def _counter(name, doc):
    return _telemetry.registry.counter(name, doc=doc)


class Servable:
    """One immutable model version: parameters + AOT program table.

    ``block`` is any Gluon/Symbol block whose forward maps row-batched
    inputs to row-batched outputs (leading axis = batch on every input
    and output leaf) — the padding contract depends on row independence
    of the *slots*, i.e. padding rows changes nothing about real rows.

    Programs are keyed ``(bucket, input signature)`` where the signature
    is the per-input (trailing shape, dtype) tuple; :meth:`warm`
    pre-builds and pre-runs every bucket for one signature so the jit
    cache, the XLA executable AND the first-dispatch autotuning are all
    paid before the version goes live.
    """

    def __init__(self, block, name: str = "model", version: int = 1,
                 buckets: Optional[BucketTable] = None):
        from ..gluon.block import functionalize
        self.block = block
        self.name = str(name)
        self.version = int(version)
        self.buckets = buckets or BucketTable.from_env()
        self._pure, self._param_values = functionalize(block)
        # buffer-census attribution (ISSUE 10): this version's parameter
        # arrays show up under the "serve" owner bucket
        from .. import programs as _programs
        _programs.track_buffers(
            "serve", self,
            lambda sv: list(sv._param_values.values()))
        self._lock = threading.Lock()
        self._programs: Dict[Tuple, object] = {}
        self._warm_sig: Optional[Tuple] = None
        self.retraces = 0            # program builds (trace+compile)
        self.bucket_hits = 0         # dispatches served from the table
        self._c_retrace = _counter(
            "serve.retraces", "serve-side program builds (should be 0 "
            "after warmup; warm() pays them at deploy)")
        self._c_hits = _counter(
            "serve.bucket_hits", "dispatches answered by a pre-built "
            "bucket program")
        self._c_batches = _counter(
            "serve.batches", "micro-batch dispatches")
        # per-model twins (ISSUE 20): the aggregate series above stay
        # for every existing gate; the labeled ones give the fleet
        # plane a per-model breakdown on a multi-model replica
        self._c_batches_m = _telemetry.registry.counter(
            "serve.batches", doc="micro-batch dispatches",
            labels={"model": self.name})
        # in-flight dispatch tracking for the host's drain
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._closed = False

    # -- loaders ------------------------------------------------------------
    @staticmethod
    def from_block(block, params_file: Optional[str] = None, ctx=None,
                   **kwargs) -> "Servable":
        """Host a live Gluon block, optionally restoring a
        ``save_parameters`` checkpoint first."""
        if params_file:
            block.load_parameters(params_file, ctx=ctx)
        return Servable(block, **kwargs)

    @staticmethod
    def from_checkpoint(prefix: str, epoch: int = 0,
                        input_names: Sequence[str] = ("data",),
                        **kwargs) -> "Servable":
        """Host an exported/foreign ``<prefix>-symbol.json`` +
        ``<prefix>-%04d.params`` artifact through the existing
        ``SymbolBlock.imports`` lane (the deploy format every MXNet-era
        tool emits)."""
        from ..gluon.block import SymbolBlock
        sym_file = "%s-symbol.json" % prefix
        params_file = "%s-%04d.params" % (prefix, int(epoch))
        if not os.path.exists(params_file):
            params_file = None
        block = SymbolBlock.imports(sym_file, list(input_names),
                                    params_file)
        kwargs.setdefault("name", os.path.basename(prefix))
        return Servable(block, **kwargs)

    # -- program table ------------------------------------------------------
    @staticmethod
    def signature_of(arrays: Sequence) -> Tuple:
        """Per-input (trailing shape, dtype) — the part of the aval the
        bucket does not normalize.  Inputs must be ndarray-like (shape/
        dtype attributes): the admission path hands the batcher numpy
        arrays by contract, and shape reads never sync a device."""
        return tuple((tuple(int(s) for s in a.shape[1:]), str(a.dtype))
                     for a in arrays)

    def _build(self, key):
        """One jit program per (bucket, signature) key.  Kept explicit —
        rather than one jax.jit whose aval cache we cannot see — so
        retrace/hit accounting is exact and 'no serve-time retraces' is
        a checkable number, not a hope.  Routed through the program
        census (ISSUE 10) as ``serve.<model>.b<bucket>`` so every bucket
        program's compile time and memory footprint are registry
        outputs."""
        pure = self._pure

        def run_infer(param_values, xs):
            outs = pure(param_values, *xs, training=False)
            leaves = jax.tree_util.tree_leaves(outs)
            return tuple(leaves)

        from .. import programs as _programs
        return _programs.register_program(
            "serve.%s.b%d" % (self.name, int(key[0])), run_infer)

    def program(self, bucket: int, sig: Tuple):
        key = (int(bucket), sig)
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self.bucket_hits += 1
        if prog is not None:
            self._c_hits.inc()
            return prog
        with _telemetry.phase("retrace"):
            prog = self._build(key)
        with self._lock:
            # two racing builders: first one in wins, identical programs
            prog = self._programs.setdefault(key, prog)
            self.retraces += 1
        self._c_retrace.inc()
        return prog

    def warm(self, example: Sequence, outputs_expected: bool = True):
        """Pre-trace + pre-run EVERY bucket for `example`'s signature
        (`example` = per-input arrays; leading batch dim arbitrary).
        Returns self so ``deploy(Servable(...).warm(x))`` chains.

        Warm start (ISSUE 13): with ``MX_COMPILE_CACHE`` set, each
        bucket's executable deserializes from the persistent store
        instead of compiling; a deserialized bucket skips its per-
        bucket proving run — one end-to-end validation dispatch (the
        smallest bucket) still proves the model answers — so replica
        ready-to-traffic time is deserialize-bound, not compile- or
        compute-bound."""
        example = [_np.asarray(a) for a in example]
        sig = self.signature_of(example)
        validated = False
        for bucket in self.buckets:
            zeros = [_np.zeros((bucket,) + trail, dtype=dt)
                     for trail, dt in sig]
            prog = None
            if validated:
                prog = self.program(bucket, sig)
                ensure = getattr(prog, "ensure_compiled", None)
                # "hit" is per-Program-instance, per-signature — a
                # concurrent deploy's cache traffic cannot make a
                # cold-compiled bucket skip its proving run
                if ensure is not None and \
                        ensure(self._param_values, tuple(zeros)) == "hit":
                    continue    # deserialized: skip the proving run
            # hand the already-resolved program through so the probe
            # never double-counts bucket_hits (exact accounting is the
            # table's contract)
            outs = self.dispatch(bucket, zeros, warming=True, _prog=prog)
            if outputs_expected:
                for o in outs:
                    jax.block_until_ready(o)
            validated = True
        with self._lock:
            self._warm_sig = sig
        return self

    @property
    def warmed_signature(self) -> Optional[Tuple]:
        with self._lock:
            return self._warm_sig

    # -- dispatch -----------------------------------------------------------
    def dispatch(self, bucket: int, padded_inputs: Sequence,
                 warming: bool = False, _prog=None) -> Tuple:
        """Run the bucket program over already-padded inputs; returns the
        output leaves as jax arrays (async — callers sync when they
        scatter).  One device-program launch, counted.  ``_prog`` lets
        warm() pass its already-resolved program so the warm probe does
        not inflate bucket-hit accounting."""
        from ..engine import engine as _engine
        prog = _prog
        if prog is None:
            sig = self.signature_of(padded_inputs)
            prog = self.program(bucket, sig)
        outs = prog(self._param_values, tuple(padded_inputs))
        _engine.count_dispatch(1)
        if not warming:
            self._c_batches.inc()
            self._c_batches_m.inc()
        return outs

    # -- footprint (the HBM bin-packer's measurement; ISSUE 20) -------------
    def program_prefix(self) -> str:
        """The program-registry name prefix this servable's programs
        register under (``memory_analysis`` bytes aggregate by it)."""
        return "serve.%s." % self.name

    def live_bytes(self) -> int:
        """Bytes of device arrays this servable holds LIVE (the same
        arrays its ``buffer_census()`` owner tags claim)."""
        return sum(int(getattr(a, "nbytes", 0))
                   for a in self._param_values.values())

    def footprint_bytes(self) -> int:
        """Measured HBM footprint for budget admission: live bytes
        (params + any device state) plus the peak transient bytes any
        of its registered programs needs at dispatch.  Meaningful after
        :meth:`warm` — warming is what populates ``memory_analysis``
        in the program registry, which is why the packer admits AFTER
        the warm."""
        from .. import programs as _programs
        mem = _programs.program_memory_bytes(self.program_prefix())
        return self.live_bytes() + int(mem["temp_bytes_peak"])

    # -- lifecycle ----------------------------------------------------------
    def begin(self) -> bool:
        """Claim one in-flight dispatch slot; False once retired."""
        with self._inflight_cv:
            if self._closed:
                return False
            self._inflight += 1
            return True

    def release(self) -> None:
        with self._inflight_cv:
            self._inflight = max(0, self._inflight - 1)
            self._inflight_cv.notify_all()

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait (bounded) until no dispatch is in flight, then retire:
        new begin() calls fail, the program table is dropped.  Returns
        False if in-flight work outlived the budget (retire anyway —
        outstanding jax arrays stay valid; only NEW dispatches die)."""
        deadline = _fault.Deadline(timeout)
        ok = True
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline.remaining()
                if remaining <= 0:
                    ok = False
                    break
                self._inflight_cv.wait(timeout=min(0.05, remaining))
            self._closed = True
        with self._lock:
            self._programs.clear()
        return ok


class ModelHost:
    """Versioned servable lifecycle, MULTI-MODEL (ISSUE 20): one host
    co-hosts N named models, each with its own version chain (load
    v(N+1) → warm → atomic flip → drain v(N)), under one HBM budget.

    ``active(model)`` is what a batcher dereferences per batch — one
    lock acquisition, never blocked by a deploy in progress (warming
    happens entirely BEFORE the flip, draining entirely after), so
    hot-swap under load serves every request from exactly one complete
    version per model.  ``active()`` with no argument keeps the
    single-model API: the DEFAULT model (first deployed).

    **Census-driven bin-packing.**  With ``MX_SERVE_HBM_BUDGET`` > 0
    (bytes), :meth:`deploy` measures the candidate's footprint AFTER
    its warm — live param/state bytes (the arrays its
    ``buffer_census()`` owner tags claim) plus the peak
    ``memory_analysis`` temp bytes of its registered programs — and
    refuses admission with :class:`BudgetExceeded` when hosted + new
    would bust the budget (a same-name redeploy gets its
    predecessor's bytes back first).  The refusal is typed so the wire
    layer can answer in-band instead of dying.
    """

    def __init__(self, hbm_budget: Optional[int] = None):
        self._lock = threading.Lock()
        self._servables: Dict[str, Servable] = {}
        self._default: Optional[str] = None
        self._history: List[Tuple[int, str]] = []
        self.hbm_budget = int(
            hbm_budget if hbm_budget is not None else
            get_env("MX_SERVE_HBM_BUDGET", 0, int))
        #: per-model engines (micro-batchers), managed by the serving
        #: layer; lives on the host so the wire layer's model routing
        #: stays a read off the one object that owns model lifecycle
        self.engines: Dict[str, object] = {}

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._servables)

    def active(self, model: Optional[str] = None) -> Servable:
        with self._lock:
            name = model if model is not None else self._default
            sv = self._servables.get(name) if name is not None else None
        if sv is None:
            if model is None:
                raise MXNetError("ModelHost: no servable deployed")
            raise MXNetError(
                "ModelHost: unknown model %r (hosted: %s)"
                % (model, ", ".join(sorted(self._servables)) or "none"))
        return sv

    @property
    def default_model(self) -> Optional[str]:
        with self._lock:
            return self._default

    @property
    def version(self) -> int:
        """The DEFAULT model's live version (single-model API)."""
        with self._lock:
            name = self._default
            sv = self._servables.get(name) if name is not None else None
            return sv.version if sv is not None else 0

    def version_of(self, model: str) -> int:
        with self._lock:
            sv = self._servables.get(model)
            return sv.version if sv is not None else 0

    def _engine_servables(self) -> Dict[str, object]:
        """Decode-engine servables co-hosted on this replica (target +
        draft of a speculative pair ride ``engines``), excluding names
        already counted as deployed servables — these share the HBM
        budget with the predict-lane models."""
        with self._lock:
            hosted = set(self._servables)
            engines = list(self.engines.values())
        out: Dict[str, object] = {}
        for eng in engines:
            for sv in (getattr(eng, "servable", None),
                       getattr(eng, "draft", None)):
                if sv is None or not hasattr(sv, "footprint_bytes"):
                    continue
                if sv.name in hosted or sv.name in out:
                    continue
                out[sv.name] = sv
        return out

    def used_bytes(self) -> int:
        """Measured footprint of every hosted servable (recomputed —
        the census reads live handles, so this tracks reality, not an
        admission-time estimate), plus any co-hosted decode engines'
        models (a speculative draft/target pair shares the budget)."""
        with self._lock:
            svs = list(self._servables.values())
        svs.extend(self._engine_servables().values())
        return sum(sv.footprint_bytes() for sv in svs)

    def packing_report(self) -> Dict[str, object]:
        """The bin-packer's health/FLEET surface: per-model measured
        footprints against the budget."""
        with self._lock:
            svs = dict(self._servables)
            default = self._default
        per_model = {name: {"version": sv.version,
                            "footprint_bytes": sv.footprint_bytes()}
                     for name, sv in svs.items()}
        for name, sv in self._engine_servables().items():
            per_model[name] = {"version": sv.version,
                               "footprint_bytes": sv.footprint_bytes(),
                               "engine": getattr(sv, "engine", "decode")}
        used = sum(m["footprint_bytes"] for m in per_model.values())
        return {
            "hbm_budget_bytes": self.hbm_budget,
            "used_bytes": used,
            "free_bytes": (self.hbm_budget - used
                           if self.hbm_budget > 0 else None),
            "default_model": default,
            "models": per_model,
        }

    def deploy(self, servable: Servable, example: Optional[Sequence] = None,
               drain_timeout: float = 30.0) -> Servable:
        """Warm `servable` (when an example is given and it is not
        already warm), admit it against the HBM budget, flip it live
        under its name, drain the same-name predecessor.  Raises
        :class:`BudgetExceeded` (servable NOT retained) on a budget
        bust."""
        if example is not None and servable.warmed_signature is None:
            servable.warm(example)
        name = servable.name
        with self._lock:
            prev = self._servables.get(name)
            if prev is not None and servable.version <= prev.version:
                raise MXNetError(
                    "ModelHost: version %d is not newer than the active "
                    "%d" % (servable.version, prev.version))
        if self.hbm_budget > 0:
            # admission AFTER warm: the footprint is measured, not
            # estimated — warm populated memory_analysis and the params
            # /state are resident
            new_bytes = servable.footprint_bytes()
            with self._lock:
                others = [sv for n, sv in self._servables.items()
                          if n != name]
            others.extend(sv for n, sv in
                          self._engine_servables().items() if n != name)
            used = sum(sv.footprint_bytes() for sv in others)
            if used + new_bytes > self.hbm_budget:
                raise BudgetExceeded(
                    "ModelHost: admitting %r v%d (%d bytes) would use "
                    "%d of %d budget bytes (MX_SERVE_HBM_BUDGET; %d "
                    "hosted: %s)"
                    % (name, servable.version, new_bytes,
                       used + new_bytes, self.hbm_budget, len(others),
                       ", ".join(sorted(sv.name for sv in others))
                       or "none"))
        with self._lock:
            prev = self._servables.get(name)
            if prev is not None and servable.version <= prev.version:
                raise MXNetError(
                    "ModelHost: version %d is not newer than the active "
                    "%d" % (servable.version, prev.version))
            old = prev
            self._servables[name] = servable
            if self._default is None:
                self._default = name
            self._history.append((servable.version, name))
        if old is not None:
            old.drain(timeout=drain_timeout)
        return servable

    def history(self) -> List[Tuple[int, str]]:
        with self._lock:
            return list(self._history)


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the serve bucket table's declared
# trace-closure proof.  One contract covers every `serve.demo.b<N>`
# bucket program of the canonical demo servable under the CONFIGURED
# bucket table (MX_SERVE_BUCKETS): the verifier lowers each bucket
# program device-free and proves the admission path is CLOSED — every
# admissible batch size pads to a bucket whose signature is in the
# compiled set, and over-bucket sizes are rejected before the jit — so
# "zero serve-time retraces" is a static theorem, not a bench
# observation.  Builders run only inside the contracts verifier.
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.lru_cache(maxsize=1)
def _demo_contract_built():
    from ..programs import ContractCase, ContractClosure
    from .demo import demo_block, DEMO_IN
    table = BucketTable.from_env()
    sv = Servable(demo_block(), name="demo", version=1, buckets=table)
    params_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for k, v in sv._param_values.items()}
    sig = (((DEMO_IN,), "float32"),)

    def args_for(bucket):
        return (params_abs,
                (jax.ShapeDtypeStruct((bucket, DEMO_IN), _np.float32),))

    cases = [ContractCase("serve.demo.b%d" % b, args_for(b),
                          label="b%d" % b, target=sv.program(b, sig))
             for b in table]

    def resolve(rows):
        # mirror the runtime admission/padding path exactly: the
        # batcher pads a rows-row batch up to bucket_for(rows), and
        # over-bucket batches are refused at admission (never reach a
        # jit) — resolving to None
        bucket = table.bucket_for(int(rows))
        return None if bucket is None else args_for(bucket)

    closure = ContractClosure(range(1, table.max_size + 3), resolve)
    return cases, closure


def _declare_serve_contracts():
    from ..programs import declare_contract
    declare_contract(
        "serve.demo", lambda: _demo_contract_built()[0],
        donate_argnums=(),
        temp_budget_bytes=1 << 20,
        closure=lambda: _demo_contract_built()[1],
        description="demo servable's AOT bucket table: no donations "
                    "(params are shared across dispatches), trace "
                    "signatures closed over the MX_SERVE_BUCKETS "
                    "admission set")


_declare_serve_contracts()
