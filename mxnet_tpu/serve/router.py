"""Serving fleet front-tier: a decode-aware session router (ISSUE 17).

One ``ServeRouter`` process fronts a DYNAMIC set of serving replicas
and speaks the exact same wire surface as a replica
(``serve/server.py``): length-prefixed pickles, requests optionally
wrapped ``("SEQ", client_id, seq, inner[, tctx])``.  Clients point
``MX_SERVE_ROOTS`` at the router and nothing else changes — the router
forwards each client envelope VERBATIM to the replica it picks, so the
replica's exactly-once replay cache keys on the client's own
``(client_id, seq)`` and the end-to-end semantics survive the extra
hop with ZERO router-side replay state:

* a client retry that reaches the SAME replica (the common case:
  pinned session, lost reply) is answered from that replica's replay
  cache — no second dispatch, no second prefill;
* a retry that must move (the pinned replica died) re-executes on a
  survivor exactly like the direct client's failover — the seq still
  protects the same-replica lost-reply case there from then on.

Routing is SESSION-routing, not request-routing: the first request of
a ``client_id`` picks the least-loaded live replica (by the fleet
plane's merged signals — queue depth, decode admission queue, decode
slot occupancy; unknown load ties break round-robin) and PINS the
session there.  Decode sessions especially must stick — moving a
generation costs a re-prefill — so a pin is only abandoned when its
replica dies, starts draining, or sheds (then the request spills to
the next-best replica and the session re-pins).  Pins are a bounded
LRU (``MX_ROUTER_PIN_CAP``): serving clients are ephemeral uuids, and
an evicted pin costs locality, never correctness.

Replica lifecycle (the router's side of drain-not-kill)::

     up ──(forward fails)──▶ dead ──(probe connects)──▶ up
     up ──(left replicas-file / replied "draining:")──▶ draining
     draining ──(forward fails / gone from file)──▶ dead / forgotten

``up`` takes new sessions; ``draining`` takes nothing new (the replica
itself also refuses — the router just stops wasting the round trip);
``dead`` is probed for revival each refresh tick.  Membership comes
from ``--replicas`` / ``MX_ROUTER_REPLICAS`` (static) plus an optional
``--replicas-file`` the autoscaler (tools/launch.py) rewrites as it
spawns and retires replicas; load signals come from the fleet
collector's merged FLEET snapshot (``--fleet`` /MX_ROUTER_FLEET``,
projected through :func:`mxnet_tpu.fleet.replica_signals`).

The router itself drains the same way a replica does: DRAIN closes
admission for NEW sessions (pinned sessions keep flowing), the serve
loop exits once the wire is idle, and past the bounded deadline the
stragglers' connections are severed so their clients replay elsewhere.

Chaos sites: ``router.request`` (crash = kill the router mid-load —
clients reconnect and replay through the restarted router) and
``router.forward`` (error/close = a dead-replica look-alike on the
upstream hop — MUST trigger router-side failover, never a double
dispatch).

Run it::

  python -m mxnet_tpu.serve.router --port 9800 \\
      --replicas 127.0.0.1:9700,127.0.0.1:9701 --fleet 127.0.0.1:9137
"""
from __future__ import annotations

import os
import socket
import socketserver
import threading
from typing import Any, Dict, List, Optional

from ..base import MXNetError, get_env
from .. import fault as _fault
from .. import fleet as _fleet
from .. import telemetry as _telemetry
from ..kvstore.server import send_msg, recv_msg
from ..kvstore.wire_verbs import declare_verbs
from ..kvstore.wire_codec import encode_text

__all__ = ["ServeRouter", "serve_router_forever", "main"]

# The ROUTE-side wire surface, DECLARED: the same rows as the replica
# manifest in serve/server.py, because the router forwards client
# envelopes verbatim — replay semantics are the REPLICA's (this file
# keeps no replay set on purpose: adding one here would mean the router
# caches replies, and then a retry could be answered with a reply the
# replica never burned a dispatch for... or worse, re-dispatch what the
# replica already cached).  mxlint's wire-verb-exhaustive rule checks
# every row is handled below.
WIRE_VERBS = declare_verbs("router", {
    # forwarded verbatim to the pinned/least-loaded replica; replay
    # exactly-once lives in the REPLICA's cache, keyed on the client's
    # own (client_id, seq) because the envelope crosses unmodified
    "PREDICT": {"semantics": "replayable", "replay": "forward",
                "codec": "array", "mutates": ()},
    "GENERATE": {"semantics": "replayable", "replay": "forward",
                 "codec": None, "mutates": (), "stream": "STREAM"},
    # fan-out: one client SWAP flips every live replica
    "SWAP": {"semantics": "replayable", "replay": "forward",
             "codec": None, "mutates": ()},
    # server->client token frame of a streaming GENERATE, passed
    # through unmodified (offset-deduped by the client on re-delivery);
    # a client SENDING it is answered locally with an explicit error
    "STREAM": {"semantics": "idempotent", "replay": "local",
               "codec": None, "mutates": ()},
    # answered by the ROUTER itself (fleet-tier state, not replica
    # state) — probing the tier must work with zero live replicas
    "HEALTH": {"semantics": "idempotent", "replay": "local",
               "codec": None, "mutates": ()},
    "METRICS": {"semantics": "idempotent", "replay": "local",
                "codec": "text", "mutates": ()},
    # retire the ROUTER: new sessions refused, pinned sessions finish
    "DRAIN": {"semantics": "idempotent", "replay": "local",
              "codec": None, "mutates": ("lifecycle",)},
    # stop the fleet: forwarded best-effort to every replica, then the
    # router itself exits
    "STOP": {"semantics": "idempotent", "replay": "forward",
             "codec": None, "mutates": ()},
}, role="router", handler="serve_router_forever.Handler._dispatch")

def _split_addrs(raw) -> List[str]:
    if raw is None:
        return []
    if isinstance(raw, str):
        return [a.strip() for a in raw.split(",") if a.strip()]
    return [str(a).strip() for a in raw if str(a).strip()]


class ServeRouter:
    """Session-pinning load balancer state + forwarding engine.

    Thread-safety: ``_lock`` is the one (leaf) lock over membership,
    pins, and signals; upstream sockets are per-connection-handler
    (owned by the socket thread that forwards on them), so no socket is
    ever shared across threads.
    """

    def __init__(self, replicas=None, replicas_file: Optional[str] = None,
                 fleet_addr: Optional[str] = None,
                 refresh: Optional[float] = None,
                 timeout: Optional[float] = None, on_tick=None):
        self._lock = threading.Lock()
        self._replicas: Dict[str, str] = {}   # addr -> up|draining|dead
        self._pins: Dict[str, str] = {}       # client_id -> addr (LRU)
        self._signals: Dict[str, Dict[str, Any]] = {}
        self._rr = 0
        self._stop = threading.Event()
        self._refresh_thread: Optional[threading.Thread] = None
        self._on_tick = on_tick
        self._replicas_file = replicas_file or \
            get_env("MX_ROUTER_REPLICAS_FILE", "") or None
        self._fleet_addr = fleet_addr or \
            get_env("MX_ROUTER_FLEET", "") or None
        self._refresh = float(refresh if refresh is not None else
                              get_env("MX_ROUTER_REFRESH", 1.0, float)
                              or 1.0)
        self._timeout = float(timeout if timeout is not None else
                              get_env("MX_SERVE_TIMEOUT", 30.0, float)
                              or 30.0)
        try:
            raw_cap = get_env("MX_ROUTER_PIN_CAP", 4096, int)
            self._pin_cap = max(1, int(4096 if raw_cap is None
                                       else raw_cap))
        except (TypeError, ValueError):
            self._pin_cap = 4096
        # router drain mirrors the replica's: first deadline wins
        self._draining = threading.Event()
        self._drain_lock = threading.Lock()
        self._drain_deadline: Optional[_fault.Deadline] = None
        reg = _telemetry.registry
        self._c_requests = reg.counter(
            "router.requests", doc="requests accepted by the router")
        self._c_failovers = reg.counter(
            "router.failovers",
            doc="upstream forwards replayed on another replica after a "
                "connection failure/timeout (the dead replica's pinned "
                "sessions are unpinned)")
        self._c_spills = reg.counter(
            "router.spills",
            doc="requests re-routed after an overloaded/draining "
                "refusal from the first-choice replica")
        self._c_unpinned = reg.counter(
            "router.sessions_unpinned",
            doc="session pins dropped because their replica died or "
                "started draining")
        self._g_up = reg.gauge(
            "router.replicas_up", doc="replicas in state 'up'")
        self._g_sessions = reg.gauge(
            "router.sessions", doc="sessions currently pinned")
        seed = _split_addrs(replicas if replicas is not None
                            else get_env("MX_ROUTER_REPLICAS", ""))
        for addr in seed:
            self._replicas[addr] = "up"
        self._reconcile_file()

    # -- membership ---------------------------------------------------------
    def set_replicas(self, addrs) -> None:
        """Reconcile membership against the authoritative list: new
        addrs join as ``up`` (optimistic — the first failed forward
        demotes them), members that left start ``draining`` (nothing
        new routed there; the autoscaler DRAINs the replica itself),
        and dead members that left are forgotten entirely."""
        want = set(_split_addrs(addrs))
        dropped = 0
        with self._lock:
            for addr in want:
                if addr not in self._replicas:
                    self._replicas[addr] = "up"
            for addr in list(self._replicas):
                if addr in want:
                    continue
                if self._replicas[addr] == "dead":
                    del self._replicas[addr]
                elif self._replicas[addr] != "draining":
                    self._replicas[addr] = "draining"
                    dropped += self._unpin_addr_locked(addr)
        if dropped:
            self._c_unpinned.inc(dropped)

    def _reconcile_file(self) -> None:
        if not self._replicas_file:
            return
        try:
            with open(self._replicas_file) as f:
                addrs = [ln.strip() for ln in f if ln.strip()
                         and not ln.startswith("#")]
        except OSError:
            return          # missing/mid-rewrite: keep current view
        self.set_replicas(addrs)

    def _probe_dead(self) -> None:
        """One connect-probe per dead replica per refresh tick: a
        supervisor-restarted replica rejoins as soon as it binds."""
        with self._lock:
            dead = [a for a, st in self._replicas.items() if st == "dead"]
        for addr in dead:
            host, port = addr.rsplit(":", 1)
            try:
                s = socket.create_connection((host, int(port)),
                                             timeout=0.5)
                s.close()
            except OSError:
                continue
            with self._lock:
                if self._replicas.get(addr) == "dead":
                    self._replicas[addr] = "up"

    def _refresh_loop(self) -> None:
        while not self._stop.is_set():
            if self._on_tick is not None:
                self._on_tick()
            self._reconcile_file()
            self._probe_dead()
            if self._fleet_addr:
                try:
                    snap = _fleet.fetch_fleet(self._fleet_addr)
                    sig = _fleet.replica_signals(snap)
                except (MXNetError, OSError, ValueError):
                    sig = None      # collector blip: keep last signals
                if sig is not None:
                    with self._lock:
                        self._signals = sig
            with self._lock:
                up = sum(1 for st in self._replicas.values()
                         if st == "up")
                sessions = len(self._pins)
            self._g_up.set(up)
            self._g_sessions.set(sessions)
            self._stop.wait(timeout=self._refresh)

    def start(self) -> None:
        if self._refresh_thread is None:
            self._refresh_thread = threading.Thread(
                target=self._refresh_loop, daemon=True,
                name="mx-router-refresh")
            self._refresh_thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._refresh_thread
        if t is not None:
            t.join(timeout=2)

    # -- routing ------------------------------------------------------------
    @staticmethod
    def _load_of(sig) -> float:
        """Queue-ish load from one replica's merged fleet signals; a
        replica the plane has not scraped yet scores 0 (a fresh spawn
        IS idle)."""
        if not sig:
            return 0.0
        return (float(sig.get("queue_rows", 0) or 0)
                + float(sig.get("decode_queue", 0) or 0)
                + float(sig.get("active_slots", 0) or 0))

    def _unpin_addr_locked(self, addr: str) -> int:
        stale = [cid for cid, a in self._pins.items() if a == addr]
        for cid in stale:
            del self._pins[cid]
        return len(stale)

    def route(self, cid: Optional[str], avoid=()) -> Optional[str]:
        """Pick the replica for one request: the session's pin when it
        is still ``up``, else the least-loaded up replica (round-robin
        rotation breaks ties), re-pinning the session there."""
        with self._lock:
            if cid is not None:
                pin = self._pins.get(cid)
                if pin and pin not in avoid and \
                        self._replicas.get(pin) == "up":
                    # LRU touch: an active session must not be evicted
                    self._pins[cid] = self._pins.pop(cid)
                    return pin
            up = [a for a, st in self._replicas.items()
                  if st == "up" and a not in avoid]
            if not up:
                return None
            self._rr += 1
            k = self._rr % len(up)
            order = up[k:] + up[:k]
            best = min(order,
                       key=lambda a: self._load_of(self._signals.get(a)))
            if cid is not None:
                self._pins.pop(cid, None)
                self._pins[cid] = best
                while len(self._pins) > self._pin_cap:
                    # oldest-touched pin pays the locality cost
                    oldest = next(iter(self._pins))
                    del self._pins[oldest]
            return best

    def unpin(self, cid: Optional[str]) -> None:
        if cid is None:
            return
        with self._lock:
            self._pins.pop(cid, None)

    def mark_dead(self, addr: str) -> None:
        """A failed forward: demote the replica and unpin its sessions
        (they fail over on their next request — involuntary retire)."""
        with self._lock:
            if addr in self._replicas:
                self._replicas[addr] = "dead"
            dropped = self._unpin_addr_locked(addr)
        if dropped:
            self._c_unpinned.inc(dropped)

    def mark_draining(self, addr: str) -> None:
        """The replica refused with "draining: ..." — believe it before
        the membership file catches up, and move its sessions."""
        with self._lock:
            if self._replicas.get(addr) == "up":
                self._replicas[addr] = "draining"
            dropped = self._unpin_addr_locked(addr)
        if dropped:
            self._c_unpinned.inc(dropped)

    def live_replicas(self, include_draining: bool = False) -> List[str]:
        with self._lock:
            return [a for a, st in self._replicas.items()
                    if st == "up" or (include_draining
                                      and st == "draining")]

    # -- router drain (mirrors the replica's) -------------------------------
    def drain(self, timeout=None) -> Dict:
        t = float(timeout if timeout is not None else
                  get_env("MX_ROUTER_DRAIN_TIMEOUT", 30.0, float)
                  or 30.0)
        with self._drain_lock:
            if self._drain_deadline is None:
                self._drain_deadline = _fault.Deadline(t)
            self._draining.set()
            remaining = self._drain_deadline.remaining()
        with self._lock:
            sessions = len(self._pins)
        return {"status": "draining", "deadline_seconds": remaining,
                "sessions": sessions}

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain_expired(self) -> bool:
        with self._drain_lock:
            dl = self._drain_deadline
        return dl is not None and dl.expired()

    def admits(self, cid: Optional[str]) -> bool:
        """While draining, only already-pinned sessions flow."""
        if not self._draining.is_set():
            return True
        if cid is None:
            return False
        with self._lock:
            return cid in self._pins

    # -- local verbs --------------------------------------------------------
    def health(self) -> Dict:
        reg = _telemetry.registry
        with self._lock:
            reps = dict(self._replicas)
            sessions = len(self._pins)
        return {
            "status": "draining" if self._draining.is_set()
            else "routing",
            "role": "router",
            "replicas": reps,
            "up": sum(1 for st in reps.values() if st == "up"),
            "sessions": sessions,
            "requests": reg.value("router.requests"),
            "failovers": reg.value("router.failovers"),
            "spills": reg.value("router.spills"),
            "pid": os.getpid(),
        }

    def metrics(self, fmt: str = "prometheus"):
        reg = _telemetry.registry
        text = reg.to_json(indent=1) if fmt == "json" \
            else reg.to_prometheus()
        return encode_text(text)

    # -- forwarding ---------------------------------------------------------
    def _upstream(self, ups: Dict[str, socket.socket],
                  addr: str) -> socket.socket:
        s = ups.get(addr)
        if s is not None:
            return s
        host, port = addr.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=5)
        s.settimeout(self._timeout)
        ups[addr] = s
        return s

    @staticmethod
    def _drop_upstream(ups: Dict[str, socket.socket], addr: str) -> None:
        s = ups.pop(addr, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def forward(self, env, cid: Optional[str], ups: Dict, client_sock):
        """Forward one client envelope verbatim with failover + spill.

        Connection failures mark the replica dead, unpin its sessions,
        and replay the SAME envelope on the next pick under the
        jittered :class:`~mxnet_tpu.fault.RetryPolicy` schedule;
        overloaded/draining refusals spill to the next-best replica
        (each replica tried at most once per request).  STREAM frames
        pass through to the client unmodified."""
        policy = _fault.RetryPolicy.from_env()
        start = _fault.now()
        attempt = 0
        refused = set()
        last_refusal = None
        while True:
            if attempt:
                d = policy.delay(attempt - 1)
                if _fault.now() + d - start > policy.deadline:
                    break
                _fault.sleep(d)
            attempt += 1
            addr = self.route(cid, avoid=refused)
            if addr is None:
                if refused and last_refusal is not None:
                    # every live replica refused: hand the refusal back
                    # (the client backs off / reports Overloaded)
                    return last_refusal
                policy.note(MXNetError("no live replicas"))
                continue
            try:
                up = self._upstream(ups, addr)
                _fault.fire(
                    "router.forward",
                    on_close=lambda a=addr: self._drop_upstream(ups, a))
                send_msg(up, env)
                while True:
                    resp = recv_msg(up, timeout=self._timeout)
                    if isinstance(resp, tuple) and resp and \
                            resp[0] == "STREAM":
                        send_msg(client_sock, resp)   # passthrough
                        continue
                    break
            except (ConnectionError, OSError, TimeoutError) as e:
                self._drop_upstream(ups, addr)
                self.mark_dead(addr)
                policy.note(e)
                self._c_failovers.inc()
                continue
            ok, payload = resp
            if (not ok and isinstance(payload, str)
                    and payload.startswith(("overloaded", "draining"))):
                if payload.startswith("draining"):
                    self.mark_draining(addr)
                refused.add(addr)
                last_refusal = resp
                self.unpin(cid)
                if self.live_replicas():
                    self._c_spills.inc()
                    continue
                return resp
            return resp
        return False, (
            "router: no live replica answered for %.3gs "
            "(MX_KVSTORE_RETRY_DEADLINE); last error: %s"
            % (policy.deadline, policy.last_error))

    def fan_out(self, env, ups: Dict, verb_timeout: Optional[float] = None):
        """SWAP/STOP fan-out: the client's envelope goes verbatim to
        EVERY live replica (draining included — a retiring replica
        finishing in-flight work should still flip models / stop).
        Returns the per-addr ``(ok, payload)`` map."""
        results: Dict[str, Any] = {}
        for addr in self.live_replicas(include_draining=True):
            try:
                up = self._upstream(ups, addr)
                if verb_timeout is not None:
                    up.settimeout(verb_timeout)
                send_msg(up, env)
                results[addr] = recv_msg(
                    up, timeout=verb_timeout or self._timeout)
            except (ConnectionError, OSError, TimeoutError) as e:
                self._drop_upstream(ups, addr)
                self.mark_dead(addr)
                results[addr] = (False, "unreachable: %s" % e)
            finally:
                if verb_timeout is not None and addr in ups:
                    ups[addr].settimeout(self._timeout)
        return results

    def handle_local(self, cmd: str, inner):
        """Verbs the ROUTER answers itself; None = not local."""
        if cmd == "HEALTH":
            return True, self.health()
        if cmd == "METRICS":
            fmt = inner[1] if len(inner) > 1 else "prometheus"
            return True, self.metrics(fmt)
        if cmd == "DRAIN":
            timeout = inner[1] if len(inner) > 1 else None
            return True, self.drain(timeout)
        if cmd == "STREAM":
            return False, ("STREAM is a server-to-client token frame, "
                           "not a request verb")
        return None


def serve_router_forever(port: int,
                         router: Optional[ServeRouter] = None,
                         ready_file: Optional[str] = None,
                         stop_event: Optional[threading.Event] = None,
                         abort_event: Optional[threading.Event] = None
                         ) -> None:
    """Run the router's accept loop (same skeleton as the replica's
    ``serve_forever``: threaded handlers, drain watch, abort = sever
    everything immediately like a kill)."""
    rt = router or ServeRouter()
    rt.start()
    stop_event = stop_event or threading.Event()
    abort_event = abort_event or threading.Event()
    inflight_count = [0]
    inflight_lock = threading.Lock()
    conns = set()
    conns_lock = threading.Lock()

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            with conns_lock:
                conns.add(self.request)
            try:
                self._serve()
            finally:
                with conns_lock:
                    conns.discard(self.request)

        def _serve(self):
            # upstream sockets are OWNED by this handler thread: one
            # client connection maps to at most one socket per replica,
            # and a streaming forward never interleaves with another
            # thread's frames
            ups: Dict[str, socket.socket] = {}
            try:
                while not abort_event.is_set():
                    try:
                        msg = recv_msg(self.request, idle_block=True)
                    except (ConnectionError, OSError, TimeoutError):
                        return
                    with inflight_lock:
                        inflight_count[0] += 1
                    try:
                        _fault.fire("router.request")
                        reply = self._dispatch(msg, ups)
                    except SystemExit:   # injected crash: die mid-route
                        os._exit(17)
                    except _fault.FaultError as e:
                        reply = (False, str(e))
                    finally:
                        with inflight_lock:
                            inflight_count[0] -= 1
                    try:
                        send_msg(self.request, reply)
                    except (ConnectionError, OSError):
                        return
                    inner = msg[3] if isinstance(msg, tuple) and msg \
                        and msg[0] == "SEQ" else msg
                    if inner and inner[0] == "STOP":
                        stop_event.set()
                        return
            finally:
                for a in list(ups):
                    ServeRouter._drop_upstream(ups, a)

        def _dispatch(self, msg, ups):
            rt._c_requests.inc()
            if isinstance(msg, tuple) and msg and msg[0] == "SEQ":
                cid, inner = msg[1], msg[3]
            else:
                cid, inner = None, msg
            cmd = inner[0] if isinstance(inner, tuple) and inner \
                else None
            local = rt.handle_local(cmd, inner) if cmd else None
            if local is not None:
                return local
            if cmd == "STOP":
                # stop the FLEET: every replica best-effort (a replica
                # already gone must not cost a full recv timeout), then
                # the router itself (the caller sees one clean reply)
                rt.fan_out(msg, ups, verb_timeout=1.0)
                return True, "stopping"
            if cmd == "SWAP":
                results = rt.fan_out(msg, ups)
                versions = []
                for addr, resp in sorted(results.items()):
                    r_ok, r_payload = resp
                    if not r_ok:
                        return False, ("swap failed on %s: %s"
                                       % (addr, r_payload))
                    versions.append(int(r_payload))
                if not versions:
                    return False, "swap failed: no live replicas"
                return True, max(versions)
            if cmd in ("PREDICT", "GENERATE"):
                if not rt.admits(cid):
                    return False, ("draining: router is retiring, not "
                                   "admitting new sessions")
                with _telemetry.rpc_span("router.%s" % cmd):
                    return rt.forward(msg, cid, ups, self.request)
            return False, "unknown route command %r" % (cmd,)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    def _sever():
        with conns_lock:
            leftover = list(conns)
        for c in leftover:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    try:
        with Server(("0.0.0.0", port), Handler) as srv:
            if ready_file:
                with open(ready_file, "w") as f:
                    f.write("%d" % srv.server_address[1])
            t = threading.Thread(target=srv.serve_forever, daemon=True,
                                 name="mx-router-accept")
            t.start()
            drain_overrun = False
            while not stop_event.is_set() and not abort_event.is_set():
                stop_event.wait(timeout=0.1)
                if rt.draining:
                    with inflight_lock:
                        wire_busy = inflight_count[0]
                    if wire_busy == 0:
                        break               # drained clean: exit 0
                    if rt.drain_expired():
                        drain_overrun = True
                        break
            if drain_overrun or abort_event.is_set():
                # stragglers (or a simulated kill): sever with NO
                # replies — clients replay through their retry policy
                _sever()
                srv.shutdown()
                return
            srv.shutdown()                  # stop accepting
            wire_deadline = _fault.Deadline(5.0)
            while not wire_deadline.expired():
                with inflight_lock:
                    if inflight_count[0] == 0:
                        break
                _fault.sleep(0.02)
            _sever()
    finally:
        rt.stop()


def main(argv=None) -> int:
    import argparse
    import sys
    import time

    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serve.router",
        description="serving fleet front-tier: decode-aware session "
                    "router (forwards the serve wire surface verbatim "
                    "across a dynamic replica set)")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--replicas", default=None,
                    help="comma-separated static replica addrs "
                         "(host:port,...)")
    ap.add_argument("--replicas-file", default=None,
                    help="file with one replica addr per line, "
                         "re-read every refresh tick (the autoscaler "
                         "rewrites it as the fleet resizes)")
    ap.add_argument("--fleet", default=None,
                    help="fleet collector addr for merged load signals")
    ap.add_argument("--refresh", type=float, default=None,
                    help="membership/signal refresh interval seconds")
    ap.add_argument("--ready-file", default=None,
                    help="write the bound port here once accepting")
    args = ap.parse_args(argv)

    port = args.port
    if port is None:
        port = int(get_env("MX_ROUTER_PORT", 9800, int) or 9800)

    # heartbeat-file liveness under tools/launch.py --hang-timeout:
    # beaten from the refresh loop, throttled, traffic-independent
    tick = None
    hb_path = get_env("MX_HEARTBEAT_FILE", "")
    if hb_path:
        from ..health import Heartbeat
        hb = Heartbeat(hb_path)
        last = [0.0]

        def tick():
            now = time.monotonic()
            if now - last[0] >= 1.0:
                last[0] = now
                hb.beat(0, 0)

        hb.beat(0, 0)

    rt = ServeRouter(replicas=args.replicas,
                     replicas_file=args.replicas_file,
                     fleet_addr=args.fleet, refresh=args.refresh,
                     on_tick=tick)
    n = len(rt.live_replicas(include_draining=True))
    print("router: fronting %d replica(s)%s%s, port %d"
          % (n,
             " file=%s" % rt._replicas_file if rt._replicas_file else "",
             " fleet=%s" % rt._fleet_addr if rt._fleet_addr else "",
             port),
          file=sys.stderr, flush=True)
    serve_router_forever(port=port, router=rt,
                         ready_file=args.ready_file)
    print("router: stopped", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
