"""Symbol API: light DAG + symbol.json serialization + executor surface.

Reference: python/mxnet/symbol/symbol.py (class Symbol, Symbol.tojson,
Symbol.load, Symbol.simple_bind), src/nnvm (nnvm::Symbol / nnvm::Graph
serialization), python/mxnet/executor.py (class Executor).

TPU-native design (SURVEY.md §7.0): the *compute* graph IR of the rebuild is
the jaxpr — XLA owns optimization.  This module keeps only what SURVEY says
must be kept: the **serialization format** (`symbol.json`), the composition
API (`mx.sym.Variable` + op calls mirroring `mx.nd.*` through the same op
registry), and the executor-shaped wrapper so `HybridBlock.export()` /
`SymbolBlock.imports()` round-trip deploy artifacts.  Execution of a loaded
symbol is a topo-order walk dispatching each node through the eager op
registry (`ndarray.invoke`) — i.e. it rides the per-op jit cache, and a
bound Executor's forward can additionally be wrapped in one `jax.jit`.

JSON format notes: same container layout as MXNet (`nodes` with
``[[id, out_idx, version]]`` inputs, ``arg_nodes``, ``node_row_ptr``,
``heads``, stringified ``attrs``), with this rebuild's op names (the op
registry is the authority, like nnvm's registry was).
"""
from __future__ import annotations

import ast
import json
import numbers
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .device import Context, current_context, cpu
from .ops.registry import get_op, list_ops, cached_jit
from .ndarray import ndarray as _nd_mod
from .ndarray.ndarray import NDArray

__all__ = ["Symbol", "Variable", "var", "Group", "AttrScope",
           "load", "loads",
           "evaluate", "symbol_json_from_block", "Executor"]

_MXNET_VERSION = 20000  # era tag written into symbol.json attrs


# ---------------------------------------------------------------------------
# graph nodes
# ---------------------------------------------------------------------------

class _SymNode:
    """One graph node. op == "null" → variable (argument); "_const" → an
    inlined literal (attrs["value"])."""
    __slots__ = ("op", "name", "attrs", "inputs")

    def __init__(self, op: str, name: str, attrs: Dict[str, str],
                 inputs: List[Tuple["_SymNode", int]]):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs


def _attr_str(v: Any) -> str:
    """Stringify an op param the MXNet way (attrs are str→str in json)."""
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "True" if v else "False"
    if isinstance(v, (list, tuple)):
        return "(" + ", ".join(_attr_str(x) for x in v) + \
            (",)" if len(v) == 1 else ")")
    return str(v)


def _attr_parse(s: str) -> Any:
    """Parse a stringified attr back to a python value (best effort)."""
    if not isinstance(s, str):
        return s
    if s == "None":
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _topo(heads: Sequence[Tuple[_SymNode, int]]) -> List[_SymNode]:
    order: List[_SymNode] = []
    seen: Dict[int, bool] = {}
    stack = [(n, False) for n, _ in reversed(heads)]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen[id(node)] = True
        stack.append((node, True))
        for inp, _ in reversed(node.inputs):
            if id(inp) not in seen:
                stack.append((inp, False))
    return order


# ---------------------------------------------------------------------------
# Symbol
# ---------------------------------------------------------------------------

class Symbol:
    """Immutable handle on one or more graph outputs (reference:
    python/mxnet/symbol/symbol.py (class Symbol))."""

    def __init__(self, heads: List[Tuple[_SymNode, int]]):
        self._heads = list(heads)

    # -- construction helpers ------------------------------------------------
    @property
    def name(self) -> str:
        return self._heads[0][0].name

    def __iter__(self):
        return (Symbol([h]) for h in self._heads)

    def __len__(self):
        return len(self._heads)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            for n, i in self._heads:
                if n.name == idx:
                    return Symbol([(n, i)])
            raise ValueError("no output named %r" % idx)
        return Symbol([self._heads[idx]] if isinstance(idx, int)
                      else self._heads[idx])

    def get_internals(self) -> "Symbol":
        """All intermediate outputs (reference: Symbol.get_internals)."""
        return Symbol([(n, 0) for n in _topo(self._heads)])

    def get_children(self) -> Optional["Symbol"]:
        """Direct input symbols of the head op (reference:
        Symbol.get_children; None for leaf variables)."""
        kids = []
        for n, _i in self._heads:
            kids.extend(n.inputs)
        if not kids:
            return None
        return Symbol([(c, i) for c, i in kids])

    def list_outputs(self) -> List[str]:
        return ["%s_output" % n.name if n.op != "null" else n.name
                for n, _ in self._heads]

    def list_arguments(self) -> List[str]:
        return [n.name for n in _topo(self._heads)
                if n.op == "null" and not _is_aux_name(n.name)]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in _topo(self._heads)
                if n.op == "null" and _is_aux_name(n.name)]

    def list_inputs(self) -> List[str]:
        return [n.name for n in _topo(self._heads) if n.op == "null"]

    def attr(self, key: str) -> Optional[str]:
        return self._heads[0][0].attrs.get(key)

    def list_attr(self) -> Dict[str, str]:
        return dict(self._heads[0][0].attrs)

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        return {n.name: dict(n.attrs) for n in _topo(self._heads) if n.attrs}

    # -- arithmetic ----------------------------------------------------------
    def _binop(self, op: str, other, reverse: bool = False) -> "Symbol":
        if isinstance(other, numbers.Number):
            other = _const(other)
        elif not isinstance(other, Symbol):
            raise TypeError("unsupported operand: %r" % (other,))
        a, b = (other, self) if reverse else (self, other)
        return _make_op_symbol(op, [a, b], {})

    def __add__(self, o):  return self._binop("broadcast_add", o)
    def __radd__(self, o): return self._binop("broadcast_add", o, True)
    def __sub__(self, o):  return self._binop("broadcast_sub", o)
    def __rsub__(self, o): return self._binop("broadcast_sub", o, True)
    def __mul__(self, o):  return self._binop("broadcast_mul", o)
    def __rmul__(self, o): return self._binop("broadcast_mul", o, True)
    def __truediv__(self, o):  return self._binop("broadcast_div", o)
    def __rtruediv__(self, o): return self._binop("broadcast_div", o, True)
    def __pow__(self, o):  return self._binop("broadcast_power", o)
    def __neg__(self):     return _make_op_symbol("negative", [self], {})

    def __repr__(self):
        return "<Symbol %s>" % " ".join(n.name for n, _ in self._heads)

    # -- serialization -------------------------------------------------------
    def tojson(self) -> str:
        nodes = _topo(self._heads)
        nid = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            out_nodes.append({
                "op": n.op if n.op != "null" else "null",
                "name": n.name,
                **({"attrs": dict(n.attrs)} if n.attrs else {}),
                "inputs": [[nid[id(i)], idx, 0] for i, idx in n.inputs],
            })
        return json.dumps({
            "nodes": out_nodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.op == "null"],
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": [[nid[id(n)], idx, 0] for n, idx in self._heads],
            "attrs": {"mxnet_version": ["int", _MXNET_VERSION]},
        }, indent=2)

    def save(self, fname: str) -> None:
        with open(fname, "w") as f:
            f.write(self.tojson())

    @staticmethod
    def load(fname: str) -> "Symbol":
        return load(fname)

    @staticmethod
    def load_json(js: str) -> "Symbol":
        return loads(js)

    # -- shape/type inference ------------------------------------------------
    def infer_shape(self, **known):
        """Returns (arg_shapes, out_shapes, aux_shapes) ordered like
        list_arguments()/list_outputs()/list_auxiliary_states()."""
        return self._infer(known, want="shape")

    def infer_shape_partial(self, **known):
        """Reference: Symbol.infer_shape_partial — like infer_shape but
        arguments/outputs the rules cannot reach come back as () instead
        of raising (the classic pre-bind diagnostic)."""
        return self._infer(known, want="shape", partial=True)

    def infer_type(self, **known):
        return self._infer(known, want="dtype")

    def _infer(self, known, want: str, partial: bool = False):
        nodes = _topo(self._heads)
        avals: Dict[int, List[jax.ShapeDtypeStruct]] = {}
        for n in nodes:
            if n.op == "null":
                if n.name in known:
                    v = known[n.name]
                    if want == "shape":
                        aval = jax.ShapeDtypeStruct(tuple(v), jnp.float32)
                    else:
                        aval = jax.ShapeDtypeStruct((), _np.dtype(v))
                    avals[id(n)] = [aval]
                else:
                    shp = _attr_parse(n.attrs.get("__shape__", "None"))
                    dt = n.attrs.get("__dtype__", "float32")
                    if shp is None and want == "shape":
                        # defer: a consuming op may determine it (the
                        # reference's backward shape inference — FC/conv
                        # weights from data shape + attrs)
                        avals[id(n)] = None
                        continue
                    avals[id(n)] = [jax.ShapeDtypeStruct(
                        tuple(shp or ()), _np.dtype(dt))]
            elif n.op == "_const":
                val = _np.asarray(_attr_parse(n.attrs["value"]), _np.float32)
                avals[id(n)] = [jax.ShapeDtypeStruct(val.shape, val.dtype)]
            else:
                if partial:
                    # backward param rules derive weight shapes from the
                    # FIRST (data) input: with it unknown — or any op
                    # input unknown — this node's outputs stay unknown
                    first_unknown = bool(n.inputs) and \
                        avals.get(id(n.inputs[0][0])) is None
                    op_unknown = any(avals.get(id(c)) is None
                                     for c, _i in n.inputs
                                     if c.op not in ("null",))
                    if first_unknown or op_unknown:
                        avals[id(n)] = None      # unknown propagates
                        continue
                _infer_param_inputs(n, avals)
                if partial and any(avals.get(id(c)) is None
                                   for c, _i in n.inputs):
                    avals[id(n)] = None
                    continue
                avals[id(n)] = _node_eval_shape(n, avals)
        if partial:
            unknown = jax.ShapeDtypeStruct((), jnp.float32)
            for n in nodes:
                if avals.get(id(n)) is None:
                    avals[id(n)] = [unknown]
        for n in nodes:
            if avals.get(id(n)) is None:
                raise MXNetError(
                    "infer_shape: missing shape for argument %r (no "
                    "backward-inference rule reached it)" % n.name)
        args = [avals[id(n)][0] for n in nodes
                if n.op == "null" and not _is_aux_name(n.name)]
        auxs = [avals[id(n)][0] for n in nodes
                if n.op == "null" and _is_aux_name(n.name)]
        outs = [avals[id(n)][i] for n, i in self._heads]
        pick = (lambda a: tuple(a.shape)) if want == "shape" else \
            (lambda a: _np.dtype(str(a.dtype)))
        return ([pick(a) for a in args], [pick(a) for a in outs],
                [pick(a) for a in auxs])

    # -- execution -----------------------------------------------------------
    def eval(self, ctx: Optional[Context] = None, **kwargs):
        """Evaluate with NDArray feeds (reference: Symbol.eval)."""
        out = evaluate(self, kwargs, {}, ctx=ctx)
        return out if isinstance(out, list) else [out]

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None) -> "Executor":
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx)

    def simple_bind(self, ctx=None, grad_req="write", **shapes) -> "Executor":
        """Allocate arguments from shapes and bind (reference:
        Symbol.simple_bind → GraphExecutor::Init; here allocation is plain
        NDArray zeros — XLA owns memory planning, SURVEY §2.1)."""
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**shapes)
        args = {}
        for name, shp in zip(self.list_arguments(), arg_shapes):
            args[name] = _nd_mod.zeros(shp, ctx=ctx)
        aux = {}
        for name, shp in zip(self.list_auxiliary_states(), aux_shapes):
            aux[name] = _nd_mod.zeros(shp, ctx=ctx)
        grads = None
        if grad_req != "null":
            grads = {n: _nd_mod.zeros(s, ctx=ctx)
                     for n, s in zip(self.list_arguments(), arg_shapes)}
        return Executor(self, ctx, args, grads, grad_req, aux)


def _is_aux_name(name: str) -> bool:
    """Aux-state heuristic: BatchNorm-style running statistics (the rebuild
    has no per-op aux declaration in the graph; naming is the convention)."""
    return ("running_mean" in name or "running_var" in name
            or "moving_mean" in name or "moving_var" in name)


# ---------------------------------------------------------------------------
# op-call composition (mx.sym.<op>(...) mirrors mx.nd.<op>(...))
# ---------------------------------------------------------------------------

def _gen_name(op: str) -> str:
    # auto names route through mx.name's scoped NameManager (reference:
    # name.NameManager — Prefix scopes prepend to every generated name)
    from .name import current as _current_namer
    base = op.lower().lstrip("_")
    return _current_namer().get(None, base)


def _const(value) -> Symbol:
    node = _SymNode("_const", _gen_name("const"),
                    {"value": _attr_str(value)}, [])
    return Symbol([(node, 0)])


# symbol-mode output counts for attr-determined (num_outputs=0) ops
def _split_nout(a):
    if "num_outputs" not in a:
        raise MXNetError("split/SliceChannel needs num_outputs in symbol "
                         "mode (the output count shapes the graph)")
    return int(a["num_outputs"])


_ATTR_NOUT = {
    "split": _split_nout,
    "split_v2": lambda a: int(a["sections"]) if int(a.get("sections", 0))
    else len(tuple(a.get("indices", ()) or ())) + 1,
}


def _make_op_symbol(op_name: str, inputs: List[Symbol],
                    params: Dict[str, Any], name: Optional[str] = None) -> Symbol:
    op = get_op(op_name)   # raises if unknown
    attrs = dict(AttrScope.current_attrs())
    attrs.update({k: _attr_str(v) for k, v in params.items()
                  if v is not None})
    # classic-API positional attrs, same convention as nd dispatch
    # (shared helper; defaultless slots keep numbers as _const operands
    # for the s + 2-style arithmetic helpers)
    pos_attrs: Dict[str, Any] = {}
    inputs = list(op.split_pos_attrs(tuple(inputs), pos_attrs, Symbol))
    for k, v in pos_attrs.items():
        if k in attrs:
            raise TypeError("%s: got multiple values for %r" % (op_name, k))
        attrs[k] = _attr_str(v)
    in_heads: List[Tuple[_SymNode, int]] = []
    for s in inputs:
        if isinstance(s, numbers.Number):
            s = _const(s)
        if not isinstance(s, Symbol):
            raise TypeError("%s: inputs must be Symbols, got %r"
                            % (op_name, type(s)))
        if len(s._heads) != 1:
            raise MXNetError("%s: cannot use a multi-output symbol directly "
                             "as input; index it first" % op_name)
        in_heads.append(s._heads[0])
    node = _SymNode(op.name, name or _gen_name(op_name), attrs, in_heads)
    if op.num_outputs == -1:
        # variadic fleet ops (multi_sgd_update & co): their output count
        # depends on runtime input lists, which the symbol DAG cannot carry
        raise MXNetError(
            "%s has a variadic output count (num_outputs=-1) and is not "
            "supported in symbol mode; call it imperatively via mx.nd"
            % op_name)
    if op.num_outputs == 0:
        # attr-determined output count (split family)
        derive = _ATTR_NOUT.get(op.name)
        if derive is None:
            raise MXNetError(
                "%s: output count depends on attrs and no symbol-mode "
                "rule derives it" % op_name)
        n_out = derive({k: _attr_parse(v) for k, v in attrs.items()})
        if n_out == 1:
            return Symbol([(node, 0)])
        return Symbol([(node, i) for i in range(n_out)])
    n_out = op.num_outputs
    if op.aux_writeback and not callable(op.aux_writeback):
        n_out = n_out - len(op.aux_writeback)
    elif callable(op.aux_writeback):
        n_out = n_out - len(op.aux_writeback(attrs))
    if n_out == 1:
        return Symbol([(node, 0)])
    return Symbol([(node, i) for i in range(n_out)])


class AttrScope:
    """Scoped symbol attributes (reference: python/mxnet/attribute.py
    class AttrScope) — most importantly ``ctx_group`` for manual model
    parallelism: ``with mx.AttrScope(ctx_group='dev1'):`` stamps
    ``__ctx_group__`` onto every node created inside, and
    ``Module.bind(group2ctx={'dev1': ctx})`` / ``Executor`` place those
    nodes' compute on the mapped device."""

    _current = threading.local()

    def __init__(self, **kwargs):
        self._attrs = {"__%s__" % k: _attr_str(v)
                       for k, v in kwargs.items() if v is not None}

    @classmethod
    def current_attrs(cls) -> Dict[str, str]:
        stack = getattr(cls._current, "stack", None)
        if not stack:
            return {}
        merged: Dict[str, str] = {}
        for scope in stack:
            merged.update(scope._attrs)
        return merged

    def __enter__(self):
        if not hasattr(AttrScope._current, "stack"):
            AttrScope._current.stack = []
        AttrScope._current.stack.append(self)
        return self

    def __exit__(self, *exc):
        AttrScope._current.stack.pop()
        return False


def Variable(name: str, shape=None, dtype=None, init=None,
             **kwargs) -> Symbol:
    attrs = dict(AttrScope.current_attrs())
    if shape is not None:
        attrs["__shape__"] = _attr_str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if init is not None:
        # serialized like the reference (attrs['__init__'] = init.dumps())
        # so Module.init_params can re-create it via initializer.create
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    attrs.update({k: _attr_str(v) for k, v in kwargs.items()})
    return Symbol([(_SymNode("null", name, attrs, []), 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


# Declared tensor inputs of the classic layer ops (reference: each op's
# ListArguments).  Enables the two v1.x symbolic-API conventions the
# positional form alone cannot express: inputs passed by KEYWORD
# (sym.FullyConnected(data=net, ...)) and AUTO-CREATED parameter
# variables named {node}_{input} for slots the caller omits
# (sym.Convolution(data=x, num_filter=32, kernel=(3,3), name='conv1')
# materializes conv1_weight/conv1_bias; backward shape inference in
# _infer_param_inputs sizes them).  The second element gates creation:
# True = always; a callable decides from the op attrs.
_always = lambda attrs: True                                  # noqa: E731
_unless_no_bias = lambda attrs: not _attr_parse(               # noqa: E731
    str(attrs.get("no_bias", "False")))
_never = lambda attrs: False                                  # noqa: E731
_BN_INPUTS = (("data", _always), ("gamma", _always), ("beta", _always),
              ("moving_mean", _always), ("moving_var", _always))
_INPUT_DECLS = {
    "FullyConnected": (("data", _always), ("weight", _always),
                       ("bias", _unless_no_bias)),
    "Convolution": (("data", _always), ("weight", _always),
                    ("bias", _unless_no_bias)),
    "Deconvolution": (("data", _always), ("weight", _always),
                      ("bias", _unless_no_bias)),
    "BatchNorm": _BN_INPUTS,
    "BatchNormWithReLU": _BN_INPUTS,
    "Embedding": (("data", _always), ("weight", _always)),
    "LayerNorm": (("data", _always), ("gamma", _always),
                  ("beta", _always)),
    "GroupNorm": (("data", _always), ("gamma", _always),
                  ("beta", _always)),
    "InstanceNorm": (("data", _always), ("gamma", _always),
                     ("beta", _always)),
    "RMSNorm": (("data", _always), ("gamma", _always)),
    "LeakyReLU": (("data", _always),
                  ("gamma", lambda attrs: str(
                      attrs.get("act_type", "leaky")) == "prelu")),
    "Activation": (("data", _always),),
    "Pooling": (("data", _always),),
    "Dropout": (("data", _always),),
    "LRN": (("data", _always),),
    "softmax": (("data", _always),),
    "log_softmax": (("data", _always),),
    "SoftmaxActivation": (("data", _always),),
    "SoftmaxOutput": (("data", _always), ("label", _always)),
    "LinearRegressionOutput": (("data", _always), ("label", _always)),
    "MAERegressionOutput": (("data", _always), ("label", _always)),
    "LogisticRegressionOutput": (("data", _always), ("label", _always)),
    "SVMOutput": (("data", _always), ("label", _always)),
    "MakeLoss": (("data", _always),),
    "RNN": (("data", _always), ("parameters", _always),
            ("state", _always),
            ("state_cell", lambda attrs: str(
                attrs.get("mode", "lstm")) == "lstm"),
            ("sequence_length", _never)),
}


def _fn_input_names(op):
    """Positional parameter names of the kernel fn (minus the injected rng
    key) — the keyword→slot map for ops without a declared input table."""
    import inspect
    names = [p.name for p in inspect.signature(op.fn).parameters.values()
             if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                           inspect.Parameter.POSITIONAL_OR_KEYWORD)]
    if op.needs_rng and names and names[0] == "key":
        names = names[1:]
    return names


def _assemble_inputs(op, op_name, node_name, inputs, sym_kwargs, params):
    decl = _INPUT_DECLS.get(op.name)
    if decl is not None:
        names = [d[0] for d in decl]
    else:
        names = _fn_input_names(op)
    slots = [None] * len(names)
    for k, v in sym_kwargs.items():
        if k not in names:
            if k == "data" and names:
                # the classic API's universal first-input keyword
                slots[0] = v
                continue
            raise MXNetError(
                "%s: unknown tensor input %r (declared inputs: %s)"
                % (op_name, k, names))
        slots[names.index(k)] = v
    # positional inputs are LEADING (reference convention): positional i
    # binds slot i, and colliding with a keyword is an error, not a
    # silent shift into the next free slot
    for i, v in enumerate(inputs):
        if i >= len(slots):
            raise MXNetError("%s: too many inputs (%d given, %d declared)"
                             % (op_name, len(inputs), len(slots)))
        if slots[i] is not None:
            raise MXNetError(
                "%s: input %r passed both positionally and as a keyword"
                % (op_name, names[i]))
        slots[i] = v
    if decl is not None:
        for i, (nm, want) in enumerate(decl):
            if slots[i] is None and want(params):
                slots[i] = Variable("%s_%s" % (node_name, nm))
    while slots and slots[-1] is None:
        slots.pop()
    for i, v in enumerate(slots):
        if v is None:
            raise MXNetError(
                "%s: missing tensor input %r (pass it positionally or as "
                "a keyword)" % (op_name, names[i]))
    return slots


def __getattr__(name: str):
    """mx.sym.<op> for every registered op (module __getattr__, PEP 562)."""
    try:
        get_op(name)
    except KeyError:
        raise AttributeError("module 'symbol' has no attribute %r" % name)
    op_name = name

    def op_call(*inputs, name=None, **params):
        op = get_op(op_name)
        sym_kwargs = {k: params.pop(k) for k in list(params)
                      if isinstance(params[k], Symbol)}
        if sym_kwargs or (op.name in _INPUT_DECLS
                          and len(inputs) < len(_INPUT_DECLS[op.name])):
            node_name = name or _gen_name(op_name)
            merged = _assemble_inputs(op, op_name, node_name, list(inputs),
                                      sym_kwargs, params)
            return _make_op_symbol(op_name, merged, params, name=node_name)
        return _make_op_symbol(op_name, list(inputs), params, name=name)
    op_call.__name__ = op_name
    return op_call


# ---------------------------------------------------------------------------
# serialization: load / loads
# ---------------------------------------------------------------------------

def loads(js: str) -> Symbol:
    g = json.loads(js)
    raw_nodes = g["nodes"]
    built: List[_SymNode] = []
    for rn in raw_nodes:
        attrs = dict(rn.get("attrs", rn.get("param", {})))
        inputs = [(built[i], idx) for i, idx, *_ in rn.get("inputs", [])]
        built.append(_SymNode(rn["op"], rn["name"], attrs, inputs))
    heads = g.get("heads")
    if not heads:
        heads = [[len(built) - 1, 0, 0]]
    return Symbol([(built[i], idx) for i, idx, *_ in heads])


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return loads(f.read())


# ---------------------------------------------------------------------------
# execution: evaluate / Executor
# ---------------------------------------------------------------------------

def _cross_device(x: NDArray, tgt: Context) -> NDArray:
    """Differentiable device transfer (reference: the _CrossDeviceCopy op
    GraphExecutor inserts for group2ctx edges).  Forward device_puts to the
    target; the vjp moves the cotangent back to the source device so the
    tape stays connected across groups."""
    from . import autograd
    moved = jax.device_put(x._jax, tgt.jax_device)
    if autograd.is_recording():
        src_dev = x.context.jax_device

        def vjp(ct):
            return (jax.device_put(ct, src_dev),)
        return autograd.record_custom(vjp, [x], moved, tgt,
                                      name="_cross_device_copy")
    return NDArray(moved, ctx=tgt)


def whole_graph_jit_enabled() -> bool:
    """One guard for every whole-graph-jit fast path (Module's fused
    train step AND bare Executor inference): MX_MODULE_JIT=0 disables
    both, and active AMP keeps the per-op dispatcher (its cast policy
    lives there)."""
    from .base import get_env
    if get_env("MX_MODULE_JIT") == "0":
        return False
    from . import amp as _amp_mod
    return _amp_mod.current_state() is None


class NotJittableGraph(Exception):
    """Raised when a symbol graph cannot become one pure jax function
    (dynamic-shape/no_jit ops, in-place optimizer ops, device groups)."""


def build_pure_fn(sym: Symbol, is_train: bool = False):
    """One PURE jax function for the whole graph (reference role:
    GraphExecutor compiles the graph once; here the whole-graph jaxpr is
    handed to XLA as a single executable instead of per-node dispatch).

    Returns fn(values: dict name → jax.Array, key) →
    (head_arrays: list, aux_updates: dict name → jax.Array).
    aux_updates carries aux-writeback results (BatchNorm moving stats)
    keyed by the source VARIABLE name; the caller owns applying them.
    """
    nodes = _topo(sym._heads)
    plan = []
    for n in nodes:
        if n.op in ("null", "_const"):
            plan.append((n, None, None))
            continue
        op = get_op(n.op)
        if op.no_jit or op.mutates_input is not None:
            raise NotJittableGraph("%s (%s)" % (n.name, n.op))
        kw = {k: _attr_parse(v) for k, v in n.attrs.items()
              if not k.startswith("__")}
        if "training" not in kw and _accepts_training(op):
            kw["training"] = bool(is_train)
        plan.append((n, op, kw))
    if any(n.attrs.get("__ctx_group__") for n, _, _ in plan):
        raise NotJittableGraph("ctx_group placement")

    def fn(values, key):
        vals: Dict[int, list] = {}
        aux_updates: Dict[str, Any] = {}
        for idx, (n, op, kw) in enumerate(plan):
            if n.op == "null":
                vals[id(n)] = [values[n.name]]
                continue
            if n.op == "_const":
                vals[id(n)] = [jnp.asarray(_attr_parse(n.attrs["value"]),
                                           jnp.float32)]
                continue
            ins = [vals[id(i)][j] for i, j in n.inputs]
            if op.needs_rng:
                out = op.fn(jax.random.fold_in(key, idx), *ins, **kw)
            else:
                out = op.fn(*ins, **kw)
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            if not op.differentiable:
                # the eager tape records only differentiable ops —
                # gradients STOP here (reference FGradient-absent ops)
                outs = [jax.lax.stop_gradient(o) for o in outs]
            awb = op.aux_writeback(kw) if callable(op.aux_writeback) \
                else op.aux_writeback
            if awb:
                visible = []
                for oi, o in enumerate(outs):
                    tgt = awb.get(oi)
                    if tgt is None:
                        visible.append(o)
                        continue
                    src_node = n.inputs[tgt][0]
                    if src_node.op == "null":
                        aux_updates[src_node.name] = o
                outs = visible
            vals[id(n)] = outs
        heads = [vals[id(n)][i] for n, i in sym._heads]
        return heads, aux_updates
    fn.needs_rng = any(op is not None and op.needs_rng
                       for _, op, _ in plan)
    return fn


_ACCEPTS_TRAINING: Dict[str, bool] = {}


def _accepts_training(op) -> bool:
    hit = _ACCEPTS_TRAINING.get(op.name)
    if hit is None:
        import inspect
        try:
            hit = "training" in inspect.signature(op.fn).parameters
        except (TypeError, ValueError):
            hit = False
        _ACCEPTS_TRAINING[op.name] = hit
    return hit


def evaluate(sym: Symbol, feeds: Dict[str, Any], params: Dict[str, Any],
             ctx: Optional[Context] = None, group2ctx=None,
             is_train: bool = False):
    """Topo-order execution through the eager op registry (each node rides
    the per-op jit cache; reference: GraphExecutor::RunOps role).

    ``group2ctx`` (reference: GraphExecutor's PlaceDevice over
    ``__ctx_group__``): nodes stamped by ``AttrScope(ctx_group=...)`` run
    on the mapped device; inputs crossing a group boundary are moved —
    manual model parallelism."""
    ctx = ctx or current_context()
    values: Dict[int, List[NDArray]] = {}
    nodes = _topo(sym._heads)

    def node_ctx(n):
        if group2ctx:
            grp = n.attrs.get("__ctx_group__")
            if grp is not None and grp in group2ctx:
                return group2ctx[grp]
        return ctx

    for n in nodes:
        tgt = node_ctx(n)
        if n.op == "null":
            v = feeds.get(n.name, params.get(n.name))
            if v is None:
                raise MXNetError("evaluate: missing value for argument %r"
                                 % n.name)
            if not isinstance(v, NDArray):
                v = _nd_mod.array(v, ctx=tgt)
            values[id(n)] = [v]
        elif n.op == "_const":
            values[id(n)] = [_nd_mod.array(
                _attr_parse(n.attrs["value"]), ctx=tgt)]
        else:
            ins = [values[id(i)][idx] for i, idx in n.inputs]
            if group2ctx:
                # cross-group edges become device transfers (the
                # reference inserts _CrossDeviceCopy nodes here); the
                # transfer must be ON THE TAPE with a device-moving vjp or
                # gradients die at every group boundary
                ins = [_cross_device(x, tgt) if isinstance(x, NDArray)
                       and x.context != tgt else x for x in ins]
            kw = {k: _attr_parse(v) for k, v in n.attrs.items()
                  if not k.startswith("__")}
            # mode flag (BatchNorm batch-vs-moving stats, Dropout on/off):
            # graph attrs don't carry it — the executor's is_train does
            # (reference: GraphExecutor forward(is_train))
            if "training" not in kw and _accepts_training(get_op(n.op)):
                kw["training"] = bool(is_train)
            out = _nd_mod.invoke(n.op, *ins, **kw)
            values[id(n)] = out if isinstance(out, list) else [out]
    outs = [values[id(n)][i] for n, i in sym._heads]
    return outs if len(outs) != 1 else outs[0]


def _infer_param_inputs(n: _SymNode, avals) -> None:
    """Backward shape inference for parameter inputs (reference: each op's
    FInferShape fills unknown arg shapes; here a rule table covers the
    param-bearing ops so Module/simple_bind work from data shapes alone)."""
    unresolved = [pos for pos, (i, _idx) in enumerate(n.inputs)
                  if avals.get(id(i)) is None]
    if not unresolved:
        return
    kw = {k: _attr_parse(v) for k, v in n.attrs.items()
          if not k.startswith("__")}

    def dshape(pos=0):
        i, idx = n.inputs[pos]
        a = avals.get(id(i))
        if a is None:
            raise MXNetError("infer_shape: input %d of %r unknown"
                             % (pos, n.name))
        return a[idx].shape

    shapes: Dict[int, tuple] = {}
    op = n.op
    if op == "FullyConnected":
        nh = int(kw["num_hidden"])
        d = dshape()
        in_units = int(_np.prod(d[1:])) if kw.get("flatten", True) else d[-1]
        shapes = {1: (nh, in_units), 2: (nh,)}
    elif op in ("Convolution", "Deconvolution"):
        kern = tuple(kw["kernel"]) if not isinstance(kw["kernel"], int) \
            else (kw["kernel"],)
        nf = int(kw["num_filter"])
        ng = int(kw.get("num_group", 1))
        cin = dshape()[1]
        if op == "Convolution":
            shapes = {1: (nf, cin // ng) + kern, 2: (nf,)}
        else:
            shapes = {1: (cin, nf // ng) + kern, 2: (nf,)}
    elif op in ("BatchNorm", "InstanceNorm"):
        c = dshape()[int(kw.get("axis", 1))]
        shapes = {1: (c,), 2: (c,), 3: (c,), 4: (c,)}
    elif op == "GroupNorm":
        c = dshape()[1]
        shapes = {1: (c,), 2: (c,)}
    elif op == "LayerNorm":
        c = dshape()[int(kw.get("axis", -1))]
        shapes = {1: (c,), 2: (c,)}
    elif op == "RMSNorm":
        c = dshape()[-1]
        shapes = {1: (c,)}
    elif op == "Embedding":
        shapes = {1: (int(kw["input_dim"]), int(kw["output_dim"]))}
    elif op == "RNN":
        from .ops.rnn import rnn_param_size
        T_, N_, I_ = dshape()
        H_ = int(kw["state_size"])
        L_ = int(kw.get("num_layers", 1))
        bi_ = bool(kw.get("bidirectional", False))
        dirs = 2 if bi_ else 1
        blob = rnn_param_size(L_, I_, H_, str(kw.get("mode", "lstm")),
                              bi_)
        shapes = {1: (blob,), 2: (L_ * dirs, N_, H_),
                  3: (L_ * dirs, N_, H_)}
    elif op == "SoftmaxOutput":
        shapes = {1: dshape()[:-1]}           # label: data minus class axis
    elif op in ("LinearRegressionOutput", "MAERegressionOutput",
                "LogisticRegressionOutput"):
        shapes = {1: dshape()}                # label: same as data
    for pos in unresolved:
        if pos not in shapes:
            continue
        node, _ = n.inputs[pos]
        avals[id(node)] = [jax.ShapeDtypeStruct(shapes[pos], jnp.float32)]


def _node_eval_shape(n: _SymNode, avals) -> List[jax.ShapeDtypeStruct]:
    op = get_op(n.op)
    kw = {k: _attr_parse(v) for k, v in n.attrs.items()
          if not k.startswith("__")}
    for pos, (i, _idx) in enumerate(n.inputs):
        if avals.get(id(i)) is None:
            raise MXNetError(
                "infer_shape: missing shape for argument %r (input %d of "
                "%r; no backward-inference rule covers it)"
                % (i.name, pos, n.name))
    ins = [avals[id(i)][idx] for i, idx in n.inputs]
    fn = cached_jit(op.name, kw)
    if op.needs_rng:
        from .ops import random as _rnd
        key = _rnd.next_key()
        ins = [jax.ShapeDtypeStruct(key.shape, key.dtype)] + ins
    out = jax.eval_shape(fn, *ins)
    return list(out) if isinstance(out, (list, tuple)) else [out]


class Executor:
    """Bound computation (reference: python/mxnet/executor.py (Executor),
    src/executor/graph_executor.cc — memory planning here is XLA's job)."""

    def __init__(self, sym: Symbol, ctx, args, args_grad=None,
                 grad_req="write", aux_states=None, group2ctx=None):
        self._sym = sym
        self._ctx = ctx or current_context()
        if isinstance(args, (list, tuple)):
            args = dict(zip(sym.list_arguments(), args))
        self.arg_dict: Dict[str, NDArray] = dict(args)
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(sym.list_arguments(), args_grad))
        self.grad_dict: Dict[str, NDArray] = dict(args_grad or {})
        self.aux_dict: Dict[str, NDArray] = dict(aux_states or {})
        self._grad_req = grad_req
        self._group2ctx = dict(group2ctx or {})
        self.outputs: List[NDArray] = []
        self._pure_ok = None      # None=untried, False=not jittable
        self._pure_jit = None

    def forward(self, is_train: bool = False, **feeds):
        from . import autograd
        vals = dict(self.arg_dict)
        vals.update(self.aux_dict)
        for k, v in feeds.items():
            if not isinstance(v, NDArray):
                v = _nd_mod.array(v, ctx=self._ctx)
            self.arg_dict[k] = v
            vals[k] = v
        if not is_train and not self._group2ctx and self._pure_ok is not False:
            # inference rides ONE compiled executable when the graph
            # allows it (same strategy as Module's fused train step)
            out = self._fast_infer(vals)
            if out is not None:
                self.outputs = out
                return self.outputs
        if is_train and self._grad_req != "null":
            for name, arr in self.arg_dict.items():
                if name in self.grad_dict:
                    arr.attach_grad(self._grad_req)
            with autograd.record():
                out = evaluate(self._sym, vals, {}, ctx=self._ctx,
                               group2ctx=self._group2ctx, is_train=True)
        else:
            out = evaluate(self._sym, vals, {}, ctx=self._ctx,
                           group2ctx=self._group2ctx,
                           is_train=bool(is_train))
        self.outputs = out if isinstance(out, list) else [out]
        return self.outputs

    def _fast_infer(self, vals):
        if not whole_graph_jit_enabled():
            return None
        if self._pure_jit is None:
            try:
                pure = build_pure_fn(self._sym, is_train=False)
            except NotJittableGraph:
                self._pure_ok = False
                return None

            def run(values, key):
                heads, _aux = pure(values, key)
                return tuple(heads)
            from .programs import register_program
            self._pure_jit = register_program("symbol.infer", run)
        jvals = {}
        for k, v in vals.items():
            jvals[k] = v._jax if isinstance(v, NDArray) else jnp.asarray(v)
        if self._rng_needed():
            from .ops.random import next_key
            key = next_key()
        else:
            key = jax.random.PRNGKey(0)
        outs = self._pure_jit(jvals, key)
        return [_nd_mod.NDArray(o, ctx=self._ctx) for o in outs]

    # rng: draw from the global stream ONLY when the graph has random
    # ops — a deterministic graph must not advance the seed state the
    # eager path leaves untouched
    def _rng_needed(self) -> bool:
        if getattr(self, "_rng_flag", None) is None:
            from .ops.registry import get_op as _gop
            self._rng_flag = any(
                n.op not in ("null", "_const") and _gop(n.op).needs_rng
                for n in _topo(self._sym._heads))
        return self._rng_flag

    def backward(self, out_grads=None):
        from . import autograd
        heads = self.outputs
        if out_grads is None:
            autograd.backward(heads)
        else:
            if not isinstance(out_grads, (list, tuple)):
                out_grads = [out_grads]
            autograd.backward(heads, list(out_grads))
        for name, arr in self.arg_dict.items():
            g = arr.grad
            if g is not None and name in self.grad_dict:
                tgt = self.grad_dict[name]
                tgt._set_jax(g._jax)
                # overlap scheduling (ISSUE 5): this argument's gradient
                # is final — let a registered consumer (bucketed exchange)
                # launch without waiting for the remaining copies
                if tgt._grad_hook is not None:
                    tgt._grad_hook()

    @property
    def grad_arrays(self) -> List[NDArray]:
        return [self.grad_dict.get(n) for n in self._sym.list_arguments()]

    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._sym.list_arguments()]

    def copy_params_from(self, arg_params, aux_params=None):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_jax(v._jax if isinstance(v, NDArray)
                                          else jnp.asarray(v))
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._set_jax(v._jax if isinstance(v, NDArray)
                                          else jnp.asarray(v))


# ---------------------------------------------------------------------------
# block → symbol tracing (HybridBlock.export backbone)
# ---------------------------------------------------------------------------

class _Tracer:
    """Records every `ndarray.invoke` into graph nodes during a concrete
    forward run (the reference traces hybrid_forward with Symbol proxies;
    here the imperative run itself is the trace — same graphs, no proxy
    type, because every NDArray op routes through one dispatcher)."""

    def __init__(self):
        self.node_of: Dict[int, Tuple[_SymNode, int]] = {}  # id(NDArray) →
        self.names = {}

    def add_variable(self, arr: NDArray, name: str) -> None:
        node = _SymNode("null", name, {}, [])
        self.node_of[id(arr)] = (node, 0)

    def _lookup(self, x) -> Tuple[_SymNode, int]:
        if isinstance(x, NDArray):
            hit = self.node_of.get(id(x))
            if hit is not None:
                return hit
            # NDArray produced outside the recorded region (e.g. a python
            # view) — inline it as a constant
            if x.size > 1 << 16:
                raise MXNetError(
                    "symbol trace: large untracked input (%s elements); "
                    "ops feeding export()ed graphs must flow through the "
                    "op registry" % x.size)
            node = _SymNode("_const", _gen_name("const"),
                            {"value": _attr_str(
                                _np.asarray(x.asnumpy()).tolist())}, [])
            self.node_of[id(x)] = (node, 0)
            return (node, 0)
        # python scalar / numpy value
        node = _SymNode("_const", _gen_name("const"),
                        {"value": _attr_str(
                            _np.asarray(x).tolist() if not isinstance(
                                x, numbers.Number) else x)}, [])
        return (node, 0)

    def record(self, op_name: str, params: Dict[str, Any], inputs, ret):
        op = get_op(op_name)
        attrs = {k: _attr_str(v) for k, v in params.items()
                 if v is not None and k not in ("ctx", "name")}
        in_heads = [self._lookup(x) for x in inputs if x is not None]
        node = _SymNode(op.name, _gen_name(op_name), attrs, in_heads)
        outs = ret if isinstance(ret, (list, tuple)) else [ret]
        for i, o in enumerate(outs):
            if isinstance(o, NDArray):
                self.node_of[id(o)] = (node, i)


def trace_block(block, *inputs, input_names: Optional[List[str]] = None
                ) -> Symbol:
    """Run `block` once on `inputs` recording the op graph; parameters
    become named Variables (structural names), inputs become `data`
    variables. Returns the output Symbol."""
    from . import autograd
    from .gluon.block import Block

    tracer = _Tracer()
    params = block.collect_params()
    ctx = None
    for x in inputs:
        if isinstance(x, NDArray):
            ctx = x.context
            break
    ctx = ctx or cpu()
    for name, p in params.items():
        if p._data is not None:
            tracer.add_variable(p.data(ctx), name)
    if input_names is None:
        input_names = ["data"] if len(inputs) == 1 else \
            ["data%d" % i for i in range(len(inputs))]
    for x, nm in zip(inputs, input_names):
        if isinstance(x, NDArray):
            tracer.add_variable(x, nm)
    prev = _nd_mod._sym_tracer
    _nd_mod._sym_tracer = tracer
    try:
        with autograd.pause():
            # run the un-hybridized path: the imperative ops ARE the trace
            out = Block._call_impl(block, *inputs)
    finally:
        _nd_mod._sym_tracer = prev
    outs = out if isinstance(out, (list, tuple)) else [out]
    heads = []
    for o in outs:
        hit = tracer.node_of.get(id(o))
        if hit is None:
            raise MXNetError("symbol trace: block output was not produced "
                             "by registry ops; cannot export")
        heads.append(hit)
    return Symbol(heads)


def symbol_json_from_block(block) -> str:
    """Serialize a HybridBlock's traced graph (reference:
    HybridBlock.export → Symbol.tojson). Requires the block to have been
    run at least once (shapes known)."""
    shapes = getattr(block, "_last_input_avals", None)
    if shapes is None:
        raise MXNetError(
            "export: run the block on real inputs at least once before "
            "export() (the reference requires hybridize()+forward too)")
    # trace on whatever device the parameters live on — a TPU-resident net
    # must export without a copy to host
    ctx = cpu()
    for p in block.collect_params().values():
        if p._data is not None:
            ctx = p.list_ctx()[0]
            break
    inputs = [_nd_mod.zeros(s, dtype=d, ctx=ctx) for s, d in shapes]
    return trace_block(block, *inputs).tojson()
