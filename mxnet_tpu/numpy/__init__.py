"""mx.np — the NumPy-compatible array API (2.x era).

Reference: ``python/mxnet/numpy/multiarray.py`` (mx.np.ndarray + the numpy
function surface) and ``python/mxnet/numpy/linalg.py``/``random.py``.

Design decision (TPU-first): the reference maintains TWO array types —
legacy ``mx.nd.NDArray`` and ``mx.np.ndarray`` — because its C++ storage
distinguishes legacy ops from numpy-semantics ops.  This rebuild has one
substrate (jax.Array) whose semantics ARE numpy's, so ``mx.np`` exposes
the numpy function surface over the SAME array type as ``mx.nd``
(``mx.np.ndarray is mx.nd.NDArray``).  Code written against either API
interoperates; ``npx.set_np()`` is a compatibility flag, not a mode
switch.

Functions whose MXNet op exists route through the op registry (per-op jit
cache, autograd tape); the numpy-only tail wraps jnp directly — still
traced/differentiated when recording, because recording happens at the
``invoke`` layer for registry ops and these wrappers stay out of autograd
(matching the reference, where mx.np creation/query ops are not
differentiable either).
"""
from __future__ import annotations

import sys
from types import ModuleType
from typing import Any

import numpy as _onp
import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray, invoke, from_jax, array as _nd_array
from ..ndarray import ndarray as _nd
from ..device import current_context

ndarray = NDArray          # one array type (see module docstring)

pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
euler_gamma = _onp.euler_gamma

float32 = _onp.float32
float64 = _onp.float64
float16 = _onp.float16
int8 = _onp.int8
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_


# -- helpers ------------------------------------------------------------------

def _unwrap(x):
    if isinstance(x, NDArray):
        return x._jax
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(out, ctx=None):
    if isinstance(out, (list, tuple)):
        return type(out)(_wrap(o, ctx) for o in out)
    if hasattr(out, "dtype") and hasattr(out, "shape"):
        return from_jax(jnp.asarray(out), ctx=ctx or current_context())
    return out


def _jnp_fn(jfn):
    def f(*args, **kwargs):
        return _wrap(jfn(*[_unwrap(a) for a in args],
                         **{k: _unwrap(v) for k, v in kwargs.items()}))
    f.__name__ = jfn.__name__
    f.__doc__ = "mx.np.%s — numpy-compatible wrapper over jnp.%s" % (
        jfn.__name__, jfn.__name__)
    return f


def _op_fn(op_name, pyname=None):
    def f(*args, **kwargs):
        return invoke(op_name, *args, **kwargs)
    f.__name__ = pyname or op_name
    return f


# -- creation -----------------------------------------------------------------

def array(object, dtype=None, ctx=None, device=None):
    return _nd_array(object, ctx=ctx or device, dtype=dtype)


def zeros(shape, dtype=float32, ctx=None, device=None, order="C"):
    return _nd.zeros(shape, ctx=ctx or device, dtype=dtype)


def ones(shape, dtype=float32, ctx=None, device=None, order="C"):
    return _nd.ones(shape, ctx=ctx or device, dtype=dtype)


def full(shape, fill_value, dtype=None, ctx=None, device=None):
    return _nd.full(shape, fill_value, ctx=ctx or device, dtype=dtype)


def empty(shape, dtype=float32, ctx=None, device=None):
    return _nd.empty(shape, ctx=ctx or device, dtype=dtype)


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    return _nd.arange(start, stop, step, dtype=dtype, ctx=ctx or device)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    return _wrap(jnp.linspace(start, stop, num, endpoint=endpoint,
                              retstep=retstep, dtype=dtype, axis=axis),
                 ctx=ctx or device)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None, device=None):
    return _wrap(jnp.logspace(start, stop, num, endpoint=endpoint,
                              base=base, dtype=dtype), ctx=ctx or device)


def eye(N, M=None, k=0, dtype=float32, ctx=None, device=None):
    return _wrap(jnp.eye(N, M, k, dtype=dtype), ctx=ctx or device)


identity = lambda n, dtype=float32, **kw: eye(n, dtype=dtype)
identity.__name__ = "identity"


def _src_ctx(a):
    return a.context if isinstance(a, NDArray) else None


def zeros_like(a, dtype=None):
    return invoke("zeros_like_op", a) if dtype is None else \
        _wrap(jnp.zeros_like(_unwrap(a), dtype=dtype), ctx=_src_ctx(a))


def ones_like(a, dtype=None):
    return invoke("ones_like_op", a) if dtype is None else \
        _wrap(jnp.ones_like(_unwrap(a), dtype=dtype), ctx=_src_ctx(a))


def full_like(a, fill_value, dtype=None):
    return _wrap(jnp.full_like(_unwrap(a), fill_value, dtype=dtype),
                 ctx=_src_ctx(a))


def copy(a):
    return a.copy()


def ascontiguousarray(a, dtype=None):
    return array(a, dtype=dtype)


asarray = array


# -- elementwise math: registry-backed (taped + jit-cached) --------------------

_REGISTRY_FUNCS = {
    # numpy name: op name
    "add": "broadcast_add", "subtract": "broadcast_sub",
    "multiply": "broadcast_mul", "divide": "broadcast_div",
    "true_divide": "broadcast_div", "mod": "broadcast_mod",
    "remainder": "broadcast_mod", "power": "broadcast_power",
    "maximum": "broadcast_maximum", "minimum": "broadcast_minimum",
    "hypot": "broadcast_hypot",
    "equal": "broadcast_equal", "not_equal": "broadcast_not_equal",
    "greater": "broadcast_greater", "less": "broadcast_lesser",
    "greater_equal": "broadcast_greater_equal",
    "less_equal": "broadcast_lesser_equal",
    "logical_and": "broadcast_logical_and",
    "logical_or": "broadcast_logical_or",
    "logical_xor": "broadcast_logical_xor",
    "logical_not": "logical_not",
    "negative": "negative", "reciprocal": "reciprocal",
    "exp": "exp", "expm1": "expm1", "log": "log", "log2": "log2",
    "log10": "log10", "log1p": "log1p", "sqrt": "sqrt", "cbrt": "cbrt",
    "square": "square", "abs": "abs", "absolute": "abs", "fabs": "abs",
    "sign": "sign", "rint": "rint", "fix": "fix", "floor": "floor",
    "ceil": "ceil", "trunc": "trunc", "round": "round",
    "sin": "sin", "cos": "cos", "tan": "tan", "arcsin": "arcsin",
    "arccos": "arccos", "arctan": "arctan", "arctan2": "arctan2",
    "sinh": "sinh", "cosh": "cosh", "tanh": "tanh", "arcsinh": "arcsinh",
    "arccosh": "arccosh", "arctanh": "arctanh",
    "degrees": "degrees", "radians": "radians",
    "copysign": "copysign", "ldexp": "ldexp", "logaddexp": "logaddexp",
    "isnan": "isnan", "isinf": "isinf", "isfinite": "isfinite",
    "sinc": "sinc", "i0": "i0", "nan_to_num": "nan_to_num",
    "heaviside": "heaviside", "interp": "interp",
    "bitwise_and": "bitwise_and", "bitwise_or": "bitwise_or",
    "bitwise_xor": "bitwise_xor", "bitwise_not": "bitwise_not",
    "invert": "bitwise_not",
    "left_shift": "bitwise_left_shift", "right_shift": "bitwise_right_shift",
    "lcm": None, "gcd": None,  # handled by jnp fallback below
    # reductions / scans
    "sum": "sum", "prod": "prod", "mean": "mean", "std": "std", "var": "var",
    "min": "min", "max": "max", "argmin": "argmin", "argmax": "argmax",
    "cumsum": "cumsum", "cumprod": "cumprod", "nansum": "nansum",
    "nanprod": "nanprod", "ptp": "ptp", "median": "median",
    "percentile": None, "quantile": None, "average": "average",
    "all": None, "any": None,
    # shape / indexing
    "reshape": None, "transpose": None, "swapaxes": None,
    "expand_dims": None, "squeeze": None,
    "broadcast_to": None, "repeat": None, "tile": None,
    "flip": None, "roll": None, "rot90": None, "split": None,
    "take": "take", "where": "where", "clip": None, "pad": None,
    "diag": None, "diagonal": "diagonal", "tril": None, "triu": None,
    "sort": "sort", "argsort": "argsort", "searchsorted": "searchsorted",
    "histogram": None, "bincount": None, "digitize": "digitize",
    "unravel_index": "unravel_index", "ravel_multi_index": "ravel_multi_index",
    "atleast_1d": "atleast_1d", "atleast_2d": "atleast_2d",
    "atleast_3d": "atleast_3d",
    # linear algebra
    "dot": "dot", "einsum": None, "kron": "kron", "cross": "cross",
    "trace": "trace_op", "outer": None, "inner": None, "matmul": None,
    "tensordot": None, "vdot": None,
}

_this = sys.modules[__name__]
for _pyname, _opname in _REGISTRY_FUNCS.items():
    if _opname is not None:
        setattr(_this, _pyname, _op_fn(_opname, _pyname))

# jnp-backed tail (no registry op / different semantics)
for _pyname in ["matmul", "tensordot", "inner", "outer", "vdot", "lcm",
                "gcd", "all", "any", "meshgrid", "indices", "tril_indices",
                "triu_indices", "unique", "ediff1d", "diff", "gradient",
                "trapz", "nanmean", "nanstd", "nanvar", "nanmin", "nanmax",
                "count_nonzero", "array_equal", "allclose", "isclose",
                "float_power", "nextafter", "positive", "real", "imag",
                "conj", "exp2", "signbit", "frexp", "deg2rad", "rad2deg",
                "moveaxis", "ravel", "vstack", "hstack", "dstack",
                "column_stack", "flipud", "fliplr", "append", "resize",
                "insert", "delete", "polyval", "vander", "tri",
                "fill_diagonal", "may_share_memory", "shares_memory"]:
    if not hasattr(_this, _pyname) and hasattr(jnp, _pyname):
        setattr(_this, _pyname, _jnp_fn(getattr(jnp, _pyname)))


# numpy positional signatures that differ from the registry kwarg form
def reshape(a, newshape, order="C"):
    return invoke("reshape", a, shape=tuple(newshape) if
                  not isinstance(newshape, int) else (newshape,))


def transpose(a, axes=None):
    return invoke("transpose", a, axes=tuple(axes) if axes is not None
                  else None)


def expand_dims(a, axis):
    return invoke("expand_dims", a, axis=axis)


def squeeze(a, axis=None):
    return invoke("squeeze", a, axis=axis)


def broadcast_to(a, shape):
    return invoke("broadcast_to", a, shape=tuple(shape))


def repeat(a, repeats, axis=None):
    return invoke("repeat", a, repeats=repeats, axis=axis)


def tile(a, reps):
    return invoke("tile", a, reps=tuple(reps) if
                  not isinstance(reps, int) else (reps,))


def flip(a, axis=None):
    if axis is None:
        return _wrap(jnp.flip(_unwrap(a)))
    return invoke("flip", a, axis=axis)


def roll(a, shift, axis=None):
    return invoke("roll", a, shift=shift, axis=axis)


def rot90(a, k=1, axes=(0, 1)):
    return invoke("rot90", a, k=k, axes=tuple(axes))


def clip(a, a_min, a_max, out=None):
    return invoke("clip", a, a_min=a_min, a_max=a_max)


def pad(a, pad_width, mode="constant", constant_values=0.0, **kw):
    # normalize numpy's forms — int, (b, a), ((b0,a0), (b1,a1), ...) — to
    # the registry op's flat (b0, a0, b1, a1, ...) layout
    nd_ = a.ndim
    if isinstance(pad_width, int):
        pairs = [(pad_width, pad_width)] * nd_
    else:
        pw = list(pad_width)
        if pw and not isinstance(pw[0], (list, tuple)):
            if len(pw) == 2:
                pairs = [tuple(pw)] * nd_
            else:
                pairs = [(int(w), int(w)) for w in pw]
        else:
            pairs = [tuple(p) for p in pw]
            if len(pairs) == 1:
                pairs = pairs * nd_
    flat = tuple(int(x) for p in pairs for x in p)
    return invoke("pad", a, pad_width=flat, mode=mode,
                  constant_value=constant_values)


def diag(v, k=0):
    return invoke("diag", v, k=k)


def tril(m, k=0):
    return invoke("tril", m, k=k)


def triu(m, k=0):
    return invoke("triu", m, k=k)


def percentile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return invoke("percentile", a, q=q, axis=axis, keepdims=keepdims,
                  interpolation=interpolation)


def quantile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return invoke("quantile", a, q=q, axis=axis, keepdims=keepdims,
                  interpolation=interpolation)


def histogram(a, bins=10, range=None, weights=None, density=None):
    if range is None:
        a_np = a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)
        range = (float(a_np.min()), float(a_np.max()))
    return invoke("histogram", a, bin_cnt=bins, range=tuple(range))


def bincount(x, weights=None, minlength=0):
    if minlength <= 0:
        x_np = x.asnumpy() if isinstance(x, NDArray) else _onp.asarray(x)
        minlength = int(x_np.max()) + 1 if x_np.size else 1
    if weights is not None:
        return invoke("bincount", x, weights, minlength=minlength)
    return invoke("bincount", x, minlength=minlength)


def einsum(subscripts, *operands, **kwargs):
    return invoke("einsum", *operands, subscripts=subscripts)


def split(ary, indices_or_sections, axis=0):
    if isinstance(indices_or_sections, int):
        return invoke("split_v2", ary, sections=indices_or_sections,
                      axis=axis)
    return invoke("split_v2", ary, indices=tuple(indices_or_sections),
                  axis=axis)


def concatenate(seq, axis=0, out=None):
    if axis is None:   # numpy semantics: flatten everything first
        seq = [invoke("flatten", s).reshape((-1,)) if isinstance(s, NDArray)
               else _wrap(jnp.ravel(jnp.asarray(s))) for s in seq]
        axis = 0
    return invoke("concat", *seq, dim=axis)


def stack(arrays, axis=0, out=None):
    return _nd.stack_arrays(tuple(arrays), axis=axis)


def shape(a):
    return a.shape


def ndim(a):
    return a.ndim


def size(a, axis=None):
    return a.size if axis is None else a.shape[axis]


def may_promote(*args):  # internal helper kept for API explorers
    return _onp.result_type(*[getattr(a, "dtype", type(a)) for a in args])


# -- submodules: np.linalg / np.random ----------------------------------------

linalg = ModuleType(__name__ + ".linalg")
linalg.norm = _op_fn("norm", "norm")
linalg.inv = _op_fn("linalg_inverse", "inv")
linalg.det = _op_fn("linalg_det", "det")
linalg.slogdet = _op_fn("linalg_slogdet", "slogdet")
linalg.cholesky = _op_fn("linalg_potrf", "cholesky")
linalg.eigh = _op_fn("linalg_syevd", "eigh")
linalg.svd = _jnp_fn(jnp.linalg.svd)
linalg.qr = _jnp_fn(jnp.linalg.qr)
linalg.solve = _jnp_fn(jnp.linalg.solve)
linalg.lstsq = _jnp_fn(jnp.linalg.lstsq)
linalg.matrix_rank = _jnp_fn(jnp.linalg.matrix_rank)
linalg.pinv = _jnp_fn(jnp.linalg.pinv)
linalg.eigvalsh = _jnp_fn(jnp.linalg.eigvalsh)
sys.modules[linalg.__name__] = linalg

random = ModuleType(__name__ + ".random")
random.uniform = lambda low=0.0, high=1.0, size=None, dtype=None, ctx=None, \
    device=None: invoke("_random_uniform", low=low, high=high,
                        shape=size if size is not None else (),
                        dtype=dtype or "float32")
random.normal = lambda loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, \
    device=None: invoke("_random_normal", loc=loc, scale=scale,
                        shape=size if size is not None else (),
                        dtype=dtype or "float32")
random.randint = lambda low, high=None, size=None, dtype=None, ctx=None: \
    invoke("_random_randint", low=low if high is not None else 0,
           high=high if high is not None else low,
           shape=size if size is not None else (),
           dtype=dtype or "int32")
random.rand = lambda *shape: random.uniform(size=shape or ())
random.randn = lambda *shape: random.normal(size=shape or ())
random.gamma = lambda shape_p=1.0, scale=1.0, size=None, **kw: \
    invoke("_random_gamma", alpha=shape_p, beta=scale,
           shape=size if size is not None else ())
random.exponential = lambda scale=1.0, size=None, **kw: \
    invoke("_random_exponential", lam=1.0 / scale,
           shape=size if size is not None else ())
def _shuffle_inplace(a):
    a._set_jax(invoke("shuffle", a)._jax)


random.shuffle = _shuffle_inplace
random.choice = lambda a, size=None, replace=True, p=None, **kw: _wrap(
    jax.random.choice(_np_random_key(), _unwrap(a) if
                      isinstance(a, NDArray) else jnp.arange(a),
                      shape=tuple(size) if isinstance(size, (list, tuple))
                      else (() if size is None else (size,)),
                      replace=replace, p=_unwrap(p) if p is not None else None))
random.seed = None  # bound below to mx.random.seed
sys.modules[random.__name__] = random


def _np_random_key():
    from ..ops import random as _rnd
    return _rnd.next_key()


def _bind_seed():
    from .. import random as _mxrandom
    random.seed = _mxrandom.seed


_bind_seed()


# -- numpy-surface tail (auto-lifted jnp wrappers) ---------------------------
# Each name not already defined above and present in jnp gets the standard
# wrapper: unwrap NDArrays -> jnp -> wrap back.  This is how the reference
# fills its `_npi` long tail with one C++ macro per op; here the substrate
# already speaks numpy.

_TAIL_NAMES = [
    # nan-aware
    "nanmean", "nanstd", "nanvar", "nanmax", "nanmin", "nanargmax",
    "nanargmin", "nanmedian", "nanquantile", "nanpercentile", "nancumsum",
    "nancumprod",
    # set ops (dynamic output: eager jnp, fine off-trace)
    "unique", "intersect1d", "union1d", "setdiff1d", "setxor1d", "isin",
    "in1d",
    # stacking / splitting
    "vstack", "hstack", "dstack", "column_stack", "row_stack",
    "array_split", "hsplit", "vsplit", "dsplit", "broadcast_arrays",
    # construction
    "meshgrid", "tri", "vander", "indices", "fromfunction",
    # statistics / calculus
    "cov", "corrcoef", "gradient", "ediff1d", "interp", "convolve",
    "correlate", "histogram2d", "histogramdd",
    # elementwise tail
    "floor_divide", "true_divide", "remainder", "float_power", "signbit",
    "exp2", "logaddexp2", "angle", "real", "imag", "conj", "conjugate",
    "around", "fabs", "positive", "frexp", "modf",
    # indexing / predicates
    "argwhere", "flatnonzero", "nonzero", "count_nonzero", "compress",
    "take_along_axis", "extract", "select", "piecewise",
    "apply_along_axis", "apply_over_axes",
    # shapes
    "fliplr", "flipud", "resize", "trim_zeros",
    # reductions / misc
    "amax", "amin", "alltrue", "any", "all", "iscomplex", "isreal",
    "isclose", "array_equal", "array_equiv", "allclose",
    "packbits", "unpackbits", "tril_indices", "triu_indices",
    "diag_indices", "tensordot", "inner", "outer", "vdot", "matmul",
    "divmod", "copy", "result_type", "promote_types", "can_cast",
]

_g = globals()
for _name in _TAIL_NAMES:
    if _name in _g:
        continue
    _src = getattr(jnp, _name, None)
    if _src is None:
        continue
    _g[_name] = _jnp_fn(_src) if callable(_src) else _src


def trapz(y, x=None, dx=1.0, axis=-1):
    fn = getattr(jnp, "trapezoid", None) or getattr(jnp, "trapz")
    return _wrap(fn(_unwrap(y), _unwrap(x) if x is not None else None,
                    dx=dx, axis=axis))


# -- linalg tail --------------------------------------------------------------
linalg.cond = _jnp_fn(jnp.linalg.cond)
linalg.matrix_power = _jnp_fn(jnp.linalg.matrix_power)
linalg.multi_dot = lambda arrays, **kw: _wrap(
    jnp.linalg.multi_dot([_unwrap(a) for a in arrays], **kw))
linalg.eigvals = _jnp_fn(jnp.linalg.eigvals)
linalg.eig = _jnp_fn(jnp.linalg.eig)
linalg.tensorsolve = _jnp_fn(jnp.linalg.tensorsolve)
linalg.tensorinv = _jnp_fn(jnp.linalg.tensorinv)


# -- random tail --------------------------------------------------------------

def _rand_size(size):
    if size is None:
        return ()
    return tuple(size) if isinstance(size, (list, tuple)) else (size,)


def _rk():
    return _np_random_key()


random.beta = lambda a, b, size=None, **kw: _wrap(
    jax.random.beta(_rk(), a, b, _rand_size(size)))
random.laplace = lambda loc=0.0, scale=1.0, size=None, **kw: _wrap(
    jax.random.laplace(_rk(), _rand_size(size)) * scale + loc)
random.gumbel = lambda loc=0.0, scale=1.0, size=None, **kw: invoke(
    "_random_gumbel", loc=loc, scale=scale, shape=_rand_size(size))
random.logistic = lambda loc=0.0, scale=1.0, size=None, **kw: invoke(
    "_random_logistic", loc=loc, scale=scale, shape=_rand_size(size))
random.pareto = lambda a, size=None, **kw: invoke(
    "_random_pareto", a=a, shape=_rand_size(size))
random.rayleigh = lambda scale=1.0, size=None, **kw: invoke(
    "_random_rayleigh", scale=scale, shape=_rand_size(size))
random.weibull = lambda a, size=None, **kw: invoke(
    "_random_weibull", a=a, shape=_rand_size(size))
random.poisson = lambda lam=1.0, size=None, **kw: invoke(
    "_random_poisson", lam=lam, shape=_rand_size(size))
random.lognormal = lambda mean=0.0, sigma=1.0, size=None, **kw: _wrap(
    jnp.exp(jax.random.normal(_rk(), _rand_size(size)) * sigma + mean))
random.chisquare = lambda df, size=None, **kw: _wrap(
    jax.random.gamma(_rk(), df / 2.0, _rand_size(size)) * 2.0)
random.standard_normal = lambda size=None: random.normal(size=size)
random.standard_exponential = lambda size=None: random.exponential(
    size=size)
random.multivariate_normal = lambda mean, cov, size=None, **kw: _wrap(
    jax.random.multivariate_normal(_rk(), _unwrap(mean), _unwrap(cov),
                                   _rand_size(size) or None))
random.multinomial = lambda n=1, pvals=None, size=None, **kw: _wrap(
    _onp.random.RandomState(
        int(jax.random.randint(_rk(), (), 0, 2**31 - 1))
    ).multinomial(n, _onp.asarray(_unwrap(pvals)), _rand_size(size) or None))
random.permutation = lambda x, **kw: _wrap(
    jax.random.permutation(_rk(), _unwrap(x) if isinstance(x, NDArray)
                           else x))
random.binomial = lambda n, p, size=None, **kw: _wrap(
    jax.random.binomial(_rk(), n, _unwrap(p),
                        shape=_rand_size(size) or None).astype("int32"))
