"""mx.np — the NumPy-compatible array API (2.x era).

Reference: ``python/mxnet/numpy/multiarray.py`` (mx.np.ndarray + the numpy
function surface) and ``python/mxnet/numpy/linalg.py``/``random.py``.

Design decision (TPU-first): the reference maintains TWO array types —
legacy ``mx.nd.NDArray`` and ``mx.np.ndarray`` — because its C++ storage
distinguishes legacy ops from numpy-semantics ops.  This rebuild has one
substrate (jax.Array) whose semantics ARE numpy's, so ``mx.np`` exposes
the numpy function surface over the SAME array type as ``mx.nd``
(``mx.np.ndarray is mx.nd.NDArray``).  Code written against either API
interoperates; ``npx.set_np()`` is a compatibility flag, not a mode
switch.

Functions whose MXNet op exists route through the op registry (per-op jit
cache, autograd tape); the numpy-only tail wraps jnp directly — still
traced/differentiated when recording, because recording happens at the
``invoke`` layer for registry ops and these wrappers stay out of autograd
(matching the reference, where mx.np creation/query ops are not
differentiable either).
"""
from __future__ import annotations

import sys
from types import ModuleType
from typing import Any

import numpy as _onp
import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray, invoke, from_jax, array as _nd_array
from ..ndarray import ndarray as _nd
from ..device import current_context

ndarray = NDArray          # one array type (see module docstring)

pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
euler_gamma = _onp.euler_gamma

float32 = _onp.float32
float64 = _onp.float64
float16 = _onp.float16
int8 = _onp.int8
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_


# -- helpers ------------------------------------------------------------------

def _unwrap(x):
    if isinstance(x, NDArray):
        return x._jax
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(out, ctx=None):
    if isinstance(out, (list, tuple)):
        return type(out)(_wrap(o, ctx) for o in out)
    if hasattr(out, "dtype") and hasattr(out, "shape"):
        return from_jax(jnp.asarray(out), ctx=ctx or current_context())
    return out


def _jnp_fn(jfn):
    def f(*args, **kwargs):
        return _wrap(jfn(*[_unwrap(a) for a in args],
                         **{k: _unwrap(v) for k, v in kwargs.items()}))
    f.__name__ = jfn.__name__
    f.__doc__ = "mx.np.%s — numpy-compatible wrapper over jnp.%s" % (
        jfn.__name__, jfn.__name__)
    return f


def _op_fn(op_name, pyname=None):
    def f(*args, **kwargs):
        return invoke(op_name, *args, **kwargs)
    f.__name__ = pyname or op_name
    return f


# -- creation -----------------------------------------------------------------

def array(object, dtype=None, ctx=None, device=None):
    return _nd_array(object, ctx=ctx or device, dtype=dtype)


def zeros(shape, dtype=float32, ctx=None, device=None, order="C"):
    return _nd.zeros(shape, ctx=ctx or device, dtype=dtype)


def ones(shape, dtype=float32, ctx=None, device=None, order="C"):
    return _nd.ones(shape, ctx=ctx or device, dtype=dtype)


def full(shape, fill_value, dtype=None, ctx=None, device=None):
    return _nd.full(shape, fill_value, ctx=ctx or device, dtype=dtype)


def empty(shape, dtype=float32, ctx=None, device=None):
    return _nd.empty(shape, ctx=ctx or device, dtype=dtype)


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    return _nd.arange(start, stop, step, dtype=dtype, ctx=ctx or device)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    return _wrap(jnp.linspace(start, stop, num, endpoint=endpoint,
                              retstep=retstep, dtype=dtype, axis=axis),
                 ctx=ctx or device)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None, device=None):
    return _wrap(jnp.logspace(start, stop, num, endpoint=endpoint,
                              base=base, dtype=dtype), ctx=ctx or device)


def eye(N, M=None, k=0, dtype=float32, ctx=None, device=None):
    return _wrap(jnp.eye(N, M, k, dtype=dtype), ctx=ctx or device)


identity = lambda n, dtype=float32, **kw: eye(n, dtype=dtype)
identity.__name__ = "identity"


def _src_ctx(a):
    return a.context if isinstance(a, NDArray) else None


def zeros_like(a, dtype=None):
    return invoke("zeros_like_op", a) if dtype is None else \
        _wrap(jnp.zeros_like(_unwrap(a), dtype=dtype), ctx=_src_ctx(a))


def ones_like(a, dtype=None):
    return invoke("ones_like_op", a) if dtype is None else \
        _wrap(jnp.ones_like(_unwrap(a), dtype=dtype), ctx=_src_ctx(a))


def full_like(a, fill_value, dtype=None):
    return _wrap(jnp.full_like(_unwrap(a), fill_value, dtype=dtype),
                 ctx=_src_ctx(a))


def copy(a):
    return a.copy()


def ascontiguousarray(a, dtype=None):
    return array(a, dtype=dtype)


asarray = array


# -- elementwise math: registry-backed (taped + jit-cached) --------------------

_REGISTRY_FUNCS = {
    # numpy name: op name
    "add": "broadcast_add", "subtract": "broadcast_sub",
    "multiply": "broadcast_mul", "divide": "broadcast_div",
    "true_divide": "broadcast_div", "mod": "broadcast_mod",
    "remainder": "broadcast_mod", "power": "broadcast_power",
    "maximum": "broadcast_maximum", "minimum": "broadcast_minimum",
    "hypot": "broadcast_hypot",
    "equal": "broadcast_equal", "not_equal": "broadcast_not_equal",
    "greater": "broadcast_greater", "less": "broadcast_lesser",
    "greater_equal": "broadcast_greater_equal",
    "less_equal": "broadcast_lesser_equal",
    "logical_and": "broadcast_logical_and",
    "logical_or": "broadcast_logical_or",
    "logical_xor": "broadcast_logical_xor",
    "logical_not": "logical_not",
    "negative": "negative", "reciprocal": "reciprocal",
    "exp": "exp", "expm1": "expm1", "log": "log", "log2": "log2",
    "log10": "log10", "log1p": "log1p", "sqrt": "sqrt", "cbrt": "cbrt",
    "square": "square", "abs": "abs", "absolute": "abs", "fabs": "abs",
    "sign": "sign", "rint": "rint", "fix": "fix", "floor": "floor",
    "ceil": "ceil", "trunc": "trunc", "round": "round",
    "sin": "sin", "cos": "cos", "tan": "tan", "arcsin": "arcsin",
    "arccos": "arccos", "arctan": "arctan", "arctan2": "arctan2",
    "sinh": "sinh", "cosh": "cosh", "tanh": "tanh", "arcsinh": "arcsinh",
    "arccosh": "arccosh", "arctanh": "arctanh",
    "degrees": "degrees", "radians": "radians",
    "copysign": "copysign", "ldexp": "ldexp", "logaddexp": "logaddexp",
    "isnan": "isnan", "isinf": "isinf", "isfinite": "isfinite",
    "sinc": "sinc", "i0": "i0", "nan_to_num": "nan_to_num",
    "heaviside": "heaviside", "interp": "interp",
    "bitwise_and": "bitwise_and", "bitwise_or": "bitwise_or",
    "bitwise_xor": "bitwise_xor", "bitwise_not": "bitwise_not",
    "invert": "bitwise_not",
    "left_shift": "bitwise_left_shift", "right_shift": "bitwise_right_shift",
    "lcm": None, "gcd": None,  # handled by jnp fallback below
    # reductions / scans
    "sum": "sum", "prod": "prod", "mean": "mean", "std": "std", "var": "var",
    "min": "min", "max": "max", "argmin": "argmin", "argmax": "argmax",
    "cumsum": "cumsum", "cumprod": "cumprod", "nansum": "nansum",
    "nanprod": "nanprod", "ptp": "ptp", "median": "median",
    "percentile": None, "quantile": None, "average": "average",
    "all": None, "any": None,
    # shape / indexing
    "reshape": None, "transpose": None, "swapaxes": None,
    "expand_dims": None, "squeeze": None,
    "broadcast_to": None, "repeat": None, "tile": None,
    "flip": None, "roll": None, "rot90": None, "split": None,
    "take": "take", "where": "where", "clip": None, "pad": None,
    "diag": None, "diagonal": "diagonal", "tril": None, "triu": None,
    "sort": "sort", "argsort": "argsort", "searchsorted": "searchsorted",
    "histogram": None, "bincount": None, "digitize": "digitize",
    "unravel_index": "unravel_index", "ravel_multi_index": "ravel_multi_index",
    "atleast_1d": "atleast_1d", "atleast_2d": "atleast_2d",
    "atleast_3d": "atleast_3d",
    # linear algebra
    "dot": "dot", "einsum": None, "kron": "kron", "cross": "cross",
    "trace": "trace_op", "outer": None, "inner": None, "matmul": None,
    "tensordot": None, "vdot": None,
}

_this = sys.modules[__name__]
for _pyname, _opname in _REGISTRY_FUNCS.items():
    if _opname is not None:
        setattr(_this, _pyname, _op_fn(_opname, _pyname))

# jnp-backed tail (no registry op / different semantics)
for _pyname in ["matmul", "tensordot", "inner", "outer", "vdot", "lcm",
                "gcd", "all", "any", "meshgrid", "indices", "tril_indices",
                "triu_indices", "unique", "ediff1d", "diff", "gradient",
                "trapz", "nanmean", "nanstd", "nanvar", "nanmin", "nanmax",
                "count_nonzero", "array_equal", "allclose", "isclose",
                "float_power", "nextafter", "positive", "real", "imag",
                "conj", "exp2", "signbit", "frexp", "deg2rad", "rad2deg",
                "moveaxis", "ravel", "vstack", "hstack", "dstack",
                "column_stack", "flipud", "fliplr", "append", "resize",
                "insert", "delete", "polyval", "vander", "tri",
                "fill_diagonal", "may_share_memory", "shares_memory"]:
    if not hasattr(_this, _pyname) and hasattr(jnp, _pyname):
        setattr(_this, _pyname, _jnp_fn(getattr(jnp, _pyname)))


# numpy positional signatures that differ from the registry kwarg form
def reshape(a, newshape, order="C"):
    return invoke("reshape", a, shape=tuple(newshape) if
                  not isinstance(newshape, int) else (newshape,))


def transpose(a, axes=None):
    return invoke("transpose", a, axes=tuple(axes) if axes is not None
                  else None)


def expand_dims(a, axis):
    return invoke("expand_dims", a, axis=axis)


def squeeze(a, axis=None):
    return invoke("squeeze", a, axis=axis)


def broadcast_to(a, shape):
    return invoke("broadcast_to", a, shape=tuple(shape))


def repeat(a, repeats, axis=None):
    return invoke("repeat", a, repeats=repeats, axis=axis)


def tile(a, reps):
    return invoke("tile", a, reps=tuple(reps) if
                  not isinstance(reps, int) else (reps,))


def flip(a, axis=None):
    if axis is None:
        return _wrap(jnp.flip(_unwrap(a)))
    return invoke("flip", a, axis=axis)


def roll(a, shift, axis=None):
    return invoke("roll", a, shift=shift, axis=axis)


def rot90(a, k=1, axes=(0, 1)):
    return invoke("rot90", a, k=k, axes=tuple(axes))


def clip(a, a_min, a_max, out=None):
    return invoke("clip", a, a_min=a_min, a_max=a_max)


def pad(a, pad_width, mode="constant", constant_values=0.0, **kw):
    # normalize numpy's forms — int, (b, a), ((b0,a0), (b1,a1), ...) — to
    # the registry op's flat (b0, a0, b1, a1, ...) layout
    nd_ = a.ndim
    if isinstance(pad_width, int):
        pairs = [(pad_width, pad_width)] * nd_
    else:
        pw = list(pad_width)
        if pw and not isinstance(pw[0], (list, tuple)):
            if len(pw) == 2:
                pairs = [tuple(pw)] * nd_
            else:
                pairs = [(int(w), int(w)) for w in pw]
        else:
            pairs = [tuple(p) for p in pw]
            if len(pairs) == 1:
                pairs = pairs * nd_
    flat = tuple(int(x) for p in pairs for x in p)
    return invoke("pad", a, pad_width=flat, mode=mode,
                  constant_value=constant_values)


def diag(v, k=0):
    return invoke("diag", v, k=k)


def tril(m, k=0):
    return invoke("tril", m, k=k)


def triu(m, k=0):
    return invoke("triu", m, k=k)


def percentile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return invoke("percentile", a, q=q, axis=axis, keepdims=keepdims,
                  interpolation=interpolation)


def quantile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return invoke("quantile", a, q=q, axis=axis, keepdims=keepdims,
                  interpolation=interpolation)


def histogram(a, bins=10, range=None, weights=None, density=None):
    if range is None:
        a_np = a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)
        range = (float(a_np.min()), float(a_np.max()))
    return invoke("histogram", a, bin_cnt=bins, range=tuple(range))


def bincount(x, weights=None, minlength=0):
    if minlength <= 0:
        x_np = x.asnumpy() if isinstance(x, NDArray) else _onp.asarray(x)
        minlength = int(x_np.max()) + 1 if x_np.size else 1
    if weights is not None:
        return invoke("bincount", x, weights, minlength=minlength)
    return invoke("bincount", x, minlength=minlength)


def einsum(subscripts, *operands, **kwargs):
    return invoke("einsum", *operands, subscripts=subscripts)


def split(ary, indices_or_sections, axis=0):
    if isinstance(indices_or_sections, int):
        return invoke("split_v2", ary, sections=indices_or_sections,
                      axis=axis)
    return invoke("split_v2", ary, indices=tuple(indices_or_sections),
                  axis=axis)


def concatenate(seq, axis=0, out=None):
    if axis is None:   # numpy semantics: flatten everything first
        seq = [invoke("flatten", s).reshape((-1,)) if isinstance(s, NDArray)
               else _wrap(jnp.ravel(jnp.asarray(s))) for s in seq]
        axis = 0
    return invoke("concat", *seq, dim=axis)


def stack(arrays, axis=0, out=None):
    return _nd.stack_arrays(tuple(arrays), axis=axis)


def shape(a):
    return a.shape


def ndim(a):
    return a.ndim


def size(a, axis=None):
    return a.size if axis is None else a.shape[axis]


def may_promote(*args):  # internal helper kept for API explorers
    return _onp.result_type(*[getattr(a, "dtype", type(a)) for a in args])


# -- submodules: np.linalg / np.random ----------------------------------------

linalg = ModuleType(__name__ + ".linalg")
linalg.norm = _op_fn("norm", "norm")
linalg.inv = _op_fn("linalg_inverse", "inv")
linalg.det = _op_fn("linalg_det", "det")
linalg.slogdet = _op_fn("linalg_slogdet", "slogdet")
linalg.cholesky = _op_fn("linalg_potrf", "cholesky")
linalg.eigh = _op_fn("linalg_syevd", "eigh")
linalg.svd = lambda a, full_matrices=False: tuple(
    invoke("_npi_svd", a, full_matrices=full_matrices))
linalg.qr = lambda a: tuple(invoke("_npi_qr", a))
linalg.solve = _op_fn("_npi_solve", "solve")
linalg.lstsq = lambda a, b, rcond=None: tuple(
    invoke("_npi_lstsq", a, b, rcond=rcond))
linalg.matrix_rank = _op_fn("_npi_matrix_rank", "matrix_rank")
linalg.pinv = _op_fn("_npi_pinv", "pinv")
linalg.eigvalsh = _op_fn("_npi_eigvalsh", "eigvalsh")
sys.modules[linalg.__name__] = linalg

# -- np.fft: the full NumPy fft surface over XLA's FFT HLO --------------------
fft = ModuleType(__name__ + ".fft")
for _f1 in ("fft", "ifft", "rfft", "irfft", "hfft", "ihfft"):
    def _mk1(_opn="_npi_" + _f1):
        def f(a, n=None, axis=-1, norm=None):
            return invoke(_opn, a, n=n, axis=axis, norm=norm)
        return f
    setattr(fft, _f1, _mk1())
    getattr(fft, _f1).__name__ = _f1
for _fn_ in ("fft2", "ifft2", "rfft2", "irfft2", "fftn", "ifftn",
             "rfftn", "irfftn"):
    def _mkn(_opn="_npi_" + _fn_):
        def f(a, s=None, axes=None, norm=None):
            return invoke(_opn, a, s=tuple(s) if s is not None else None,
                          axes=tuple(axes) if axes is not None else None,
                          norm=norm)
        return f
    setattr(fft, _fn_, _mkn())
    getattr(fft, _fn_).__name__ = _fn_
fft.fftfreq = lambda n, d=1.0: invoke("_npi_fftfreq", n=n, d=d)
fft.rfftfreq = lambda n, d=1.0: invoke("_npi_rfftfreq", n=n, d=d)
fft.fftshift = lambda x, axes=None: invoke(
    "_npi_fftshift", x, axes=tuple(axes) if isinstance(axes, (list, tuple))
    else axes)
fft.ifftshift = lambda x, axes=None: invoke(
    "_npi_ifftshift", x, axes=tuple(axes)
    if isinstance(axes, (list, tuple)) else axes)
sys.modules[fft.__name__] = fft

random = ModuleType(__name__ + ".random")
random.uniform = lambda low=0.0, high=1.0, size=None, dtype=None, ctx=None, \
    device=None: invoke("_random_uniform", low=low, high=high,
                        shape=size if size is not None else (),
                        dtype=dtype or "float32")
random.normal = lambda loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, \
    device=None: invoke("_random_normal", loc=loc, scale=scale,
                        shape=size if size is not None else (),
                        dtype=dtype or "float32")
random.randint = lambda low, high=None, size=None, dtype=None, ctx=None: \
    invoke("_random_randint", low=low if high is not None else 0,
           high=high if high is not None else low,
           shape=size if size is not None else (),
           dtype=dtype or "int32")
random.rand = lambda *shape: random.uniform(size=shape or ())
random.randn = lambda *shape: random.normal(size=shape or ())
random.gamma = lambda shape_p=1.0, scale=1.0, size=None, **kw: \
    invoke("_random_gamma", alpha=shape_p, beta=scale,
           shape=size if size is not None else ())
random.exponential = lambda scale=1.0, size=None, **kw: \
    invoke("_random_exponential", lam=1.0 / scale,
           shape=size if size is not None else ())
def _shuffle_inplace(a):
    a._set_jax(invoke("shuffle", a)._jax)


random.shuffle = _shuffle_inplace
random.choice = lambda a, size=None, replace=True, p=None, **kw: _wrap(
    jax.random.choice(_np_random_key(), _unwrap(a) if
                      isinstance(a, NDArray) else jnp.arange(a),
                      shape=tuple(size) if isinstance(size, (list, tuple))
                      else (() if size is None else (size,)),
                      replace=replace, p=_unwrap(p) if p is not None else None))
random.seed = None  # bound below to mx.random.seed
sys.modules[random.__name__] = random


def _np_random_key():
    from ..ops import random as _rnd
    return _rnd.next_key()


def _bind_seed():
    from .. import random as _mxrandom
    random.seed = _mxrandom.seed


_bind_seed()


# -- numpy-surface tail (auto-lifted jnp wrappers) ---------------------------
# Each name not already defined above and present in jnp gets the standard
# wrapper: unwrap NDArrays -> jnp -> wrap back.  This is how the reference
# fills its `_npi` long tail with one C++ macro per op; here the substrate
# already speaks numpy.

_TAIL_NAMES = [
    # nan-aware
    "nanmean", "nanstd", "nanvar", "nanmax", "nanmin", "nanargmax",
    "nanargmin", "nanmedian", "nanquantile", "nanpercentile", "nancumsum",
    "nancumprod",
    # set ops (dynamic output: eager jnp, fine off-trace)
    "unique", "intersect1d", "union1d", "setdiff1d", "setxor1d", "isin",
    "in1d",
    # stacking / splitting
    "vstack", "hstack", "dstack", "column_stack", "row_stack",
    "array_split", "hsplit", "vsplit", "dsplit", "broadcast_arrays",
    # construction
    "meshgrid", "tri", "vander", "indices", "fromfunction",
    # statistics / calculus
    "cov", "corrcoef", "gradient", "ediff1d", "interp", "convolve",
    "correlate", "histogram2d", "histogramdd",
    # elementwise tail
    "floor_divide", "true_divide", "remainder", "float_power", "signbit",
    "exp2", "logaddexp2", "angle", "real", "imag", "conj", "conjugate",
    "around", "fabs", "positive", "frexp", "modf",
    # indexing / predicates
    "argwhere", "flatnonzero", "nonzero", "count_nonzero", "compress",
    "take_along_axis", "extract", "select", "piecewise",
    "apply_along_axis", "apply_over_axes",
    # shapes
    "fliplr", "flipud", "resize", "trim_zeros",
    # reductions / misc
    "amax", "amin", "alltrue", "any", "all", "iscomplex", "isreal",
    "isclose", "array_equal", "array_equiv", "allclose",
    "packbits", "unpackbits", "tril_indices", "triu_indices",
    "diag_indices", "tensordot", "inner", "outer", "vdot", "matmul",
    "divmod", "copy", "result_type", "promote_types", "can_cast",
]

_g = globals()
for _name in _TAIL_NAMES:
    if _name in _g:
        continue
    _src = getattr(jnp, _name, None)
    if _src is None:
        continue
    _g[_name] = _jnp_fn(_src) if callable(_src) else _src


def trapz(y, x=None, dx=1.0, axis=-1):
    fn = getattr(jnp, "trapezoid", None) or getattr(jnp, "trapz")
    return _wrap(fn(_unwrap(y), _unwrap(x) if x is not None else None,
                    dx=dx, axis=axis))


# -- linalg tail --------------------------------------------------------------
linalg.cond = _op_fn("_npi_cond", "cond")
linalg.matrix_power = _op_fn("_npi_matrix_power", "matrix_power")
linalg.multi_dot = lambda arrays, **kw: invoke("_npi_multi_dot", *arrays)
linalg.eigvals = _jnp_fn(jnp.linalg.eigvals)   # complex out: jnp path
linalg.eig = _jnp_fn(jnp.linalg.eig)           # complex out: jnp path
linalg.tensorsolve = lambda a, b, axes=None: invoke(
    "_npi_tensorsolve", a, b, axes=tuple(axes) if axes else None)
linalg.tensorinv = lambda a, ind=2: invoke("_npi_tensorinv", a, ind=ind)


# -- random tail --------------------------------------------------------------

def _rand_size(size):
    if size is None:
        return ()
    return tuple(size) if isinstance(size, (list, tuple)) else (size,)


def _rk():
    return _np_random_key()


random.beta = lambda a, b, size=None, **kw: _wrap(
    jax.random.beta(_rk(), a, b, _rand_size(size)))
random.laplace = lambda loc=0.0, scale=1.0, size=None, **kw: _wrap(
    jax.random.laplace(_rk(), _rand_size(size)) * scale + loc)
random.gumbel = lambda loc=0.0, scale=1.0, size=None, **kw: invoke(
    "_random_gumbel", loc=loc, scale=scale, shape=_rand_size(size))
random.logistic = lambda loc=0.0, scale=1.0, size=None, **kw: invoke(
    "_random_logistic", loc=loc, scale=scale, shape=_rand_size(size))
random.pareto = lambda a, size=None, **kw: invoke(
    "_random_pareto", a=a, shape=_rand_size(size))
random.rayleigh = lambda scale=1.0, size=None, **kw: invoke(
    "_random_rayleigh", scale=scale, shape=_rand_size(size))
random.weibull = lambda a, size=None, **kw: invoke(
    "_random_weibull", a=a, shape=_rand_size(size))
random.f = lambda dfnum, dfden, size=None, **kw: invoke(
    "_random_f", dfnum=dfnum, dfden=dfden, shape=_rand_size(size))
random.geometric = lambda p, size=None, **kw: invoke(
    "_random_geometric", p=p, shape=_rand_size(size))
random.power = lambda a, size=None, **kw: invoke(
    "_random_power", a=a, shape=_rand_size(size))
random.negative_binomial = lambda n, p, size=None, **kw: invoke(
    "_random_negative_binomial", k=n, p=p, shape=_rand_size(size))
random.poisson = lambda lam=1.0, size=None, **kw: invoke(
    "_random_poisson", lam=lam, shape=_rand_size(size))
random.lognormal = lambda mean=0.0, sigma=1.0, size=None, **kw: _wrap(
    jnp.exp(jax.random.normal(_rk(), _rand_size(size)) * sigma + mean))
random.chisquare = lambda df, size=None, **kw: _wrap(
    jax.random.gamma(_rk(), df / 2.0, _rand_size(size)) * 2.0)
random.standard_normal = lambda size=None: random.normal(size=size)
random.standard_exponential = lambda size=None: random.exponential(
    size=size)
def _scalar_param(name, v):
    """Distribution parameters ride the jit cache as STATIC attrs, so
    they must be host scalars; numpy's array-parameter broadcasting is
    not supported (matching the rest of this module) — turn the
    deep unhashable-key crash into a clear error."""
    if isinstance(v, NDArray) or isinstance(v, _onp.ndarray):
        if getattr(v, "size", 2) == 1:
            return float(v.asnumpy() if isinstance(v, NDArray) else v)
        raise TypeError(
            "np.random: array-valued parameter %r is not supported "
            "(pass a scalar; broadcasting over parameter arrays is a "
            "documented divergence)" % name)
    return float(v)


random.standard_t = lambda df, size=None, **kw: invoke(
    "_npi_standard_t", df=_scalar_param("df", df), size=_rand_size(size))
random.standard_cauchy = lambda size=None, **kw: invoke(
    "_npi_standard_cauchy", size=_rand_size(size))
random.standard_gamma = lambda shape, size=None, **kw: invoke(
    "_npi_standard_gamma", shape_param=_scalar_param("shape", shape),
    size=_rand_size(size))
random.triangular = lambda left, mode, right, size=None, **kw: invoke(
    "_npi_triangular", left=_scalar_param("left", left),
    mode=_scalar_param("mode", mode),
    right=_scalar_param("right", right), size=_rand_size(size))
random.dirichlet = lambda alpha, size=None, **kw: invoke(
    "_npi_dirichlet", alpha=tuple(float(a) for a in alpha),
    size=_rand_size(size))
random.noncentral_chisquare = lambda df, nonc, size=None, **kw: invoke(
    "_npi_noncentral_chisquare", df=_scalar_param("df", df),
    nonc=_scalar_param("nonc", nonc), size=_rand_size(size))
random.wald = lambda mean, scale, size=None, **kw: invoke(
    "_npi_wald", mean=_scalar_param("mean", mean),
    scale=_scalar_param("scale", scale), size=_rand_size(size))
random.logseries = lambda p, size=None, **kw: invoke(
    "_npi_logseries", p=_scalar_param("p", p), size=_rand_size(size))
random.vonmises = lambda mu, kappa, size=None, **kw: invoke(
    "_npi_vonmises", mu=_scalar_param("mu", mu),
    kappa=_scalar_param("kappa", kappa), size=_rand_size(size))
random.zipf = lambda a, size=None, **kw: invoke(
    "_npi_zipf", a=_scalar_param("a", a), size=_rand_size(size))
random.multivariate_normal = lambda mean, cov, size=None, **kw: _wrap(
    jax.random.multivariate_normal(_rk(), _unwrap(mean), _unwrap(cov),
                                   _rand_size(size) or None))
random.multinomial = lambda n=1, pvals=None, size=None, **kw: _wrap(
    _onp.random.RandomState(
        int(jax.random.randint(_rk(), (), 0, 2**31 - 1))
    ).multinomial(n, _onp.asarray(_unwrap(pvals)), _rand_size(size) or None))
random.permutation = lambda x, **kw: _wrap(
    jax.random.permutation(_rk(), _unwrap(x) if isinstance(x, NDArray)
                           else x))
random.binomial = lambda n, p, size=None, **kw: _wrap(
    jax.random.binomial(_rk(), n, _unwrap(p),
                        shape=_rand_size(size) or None).astype("int32"))


# -- route the surface through the registered _npi_* layer --------------------
# (reference: python/mxnet/numpy/multiarray.py dispatching to _npi ops).
# These overrides supersede the legacy-op routing and the raw-jnp tail above:
# every call goes through `invoke` -> per-op jit cache + autograd tape, with
# TRUE numpy semantics (bool comparisons, numpy promotion) from ops/numpy_ops.

def _npi1(op, **fixed):
    def fn(a, **kw):
        kw.update(fixed)
        return invoke(op, a, **kw)
    fn.__name__ = op.replace("_npi_", "")
    return fn


def _npi2(op):
    """Binary dispatch with the reference's array-scalar split: a python
    number on either side routes to the _npi_*_scalar / _npi_r*_scalar
    kernel (no scalar->array materialization; graphs record the same
    node the reference writes), arrays to the tensor-tensor kernel."""
    stem = op.replace("_npi_", "")
    stem = "mod" if stem == "remainder" else stem
    s_name = "_npi_%s_scalar" % stem
    r_name = "_npi_r%s_scalar" % stem
    _commutes = stem in ("add", "multiply", "maximum", "minimum", "fmax",
                         "fmin", "hypot", "logaddexp", "logaddexp2")
    _have = []                           # memoized (s_ok, r_ok)

    def _num(x):
        """Python number usable as a float attr without precision loss
        (large ints stay on the exact tensor path)."""
        if isinstance(x, (bool, _onp.bool_)) or \
                not isinstance(x, (int, float)):
            return False
        return not isinstance(x, int) or abs(x) <= 2 ** 53

    def fn(a, b, **kw):
        if not _have:
            from ..ops.registry import _REGISTRY as _ops
            _have.append((s_name in _ops, r_name in _ops))
        s_ok, r_ok = _have[0]
        if s_ok and _num(b) and not isinstance(a, (int, float)):
            return invoke(s_name, a, scalar=float(b),
                          is_int=isinstance(b, int), **kw)
        if _num(a) and not isinstance(b, (int, float)):
            if r_ok:
                return invoke(r_name, b, scalar=float(a),
                              is_int=isinstance(a, int), **kw)
            if s_ok and _commutes:
                return invoke(s_name, b, scalar=float(a),
                              is_int=isinstance(a, int), **kw)
        return invoke(op, a, b, **kw)
    fn.__name__ = op.replace("_npi_", "")
    return fn


for _py, _opn in [
        ("add", "_npi_add"), ("subtract", "_npi_subtract"),
        ("multiply", "_npi_multiply"), ("divide", "_npi_true_divide"),
        ("true_divide", "_npi_true_divide"), ("power", "_npi_power"),
        ("float_power", "_npi_float_power"),
        ("floor_divide", "_npi_floor_divide"), ("mod", "_npi_remainder"),
        ("remainder", "_npi_remainder"), ("fmod", "_npi_fmod"),
        ("maximum", "_npi_maximum"), ("minimum", "_npi_minimum"),
        ("fmax", "_npi_fmax"), ("fmin", "_npi_fmin"),
        ("arctan2", "_npi_arctan2"), ("hypot", "_npi_hypot"),
        ("logaddexp", "_npi_logaddexp"), ("logaddexp2", "_npi_logaddexp2"),
        ("copysign", "_npi_copysign"), ("nextafter", "_npi_nextafter"),
        ("ldexp", "_npi_ldexp"), ("heaviside", "_npi_heaviside"),
        ("gcd", "_npi_gcd"), ("lcm", "_npi_lcm"),
        ("bitwise_and", "_npi_bitwise_and"),
        ("bitwise_or", "_npi_bitwise_or"),
        ("bitwise_xor", "_npi_bitwise_xor"),
        ("left_shift", "_npi_left_shift"),
        ("right_shift", "_npi_right_shift"),
        ("equal", "_npi_equal"), ("not_equal", "_npi_not_equal"),
        ("less", "_npi_less"), ("less_equal", "_npi_less_equal"),
        ("greater", "_npi_greater"),
        ("greater_equal", "_npi_greater_equal"),
        ("logical_and", "_npi_logical_and"),
        ("logical_or", "_npi_logical_or"),
        ("logical_xor", "_npi_logical_xor"),
        ("isclose", "_npi_isclose"), ("array_equal", "_npi_array_equal"),
        ("array_equiv", "_npi_array_equiv"), ("allclose", "_npi_allclose"),
        ("matmul", "_npi_matmul"), ("dot", "_npi_dot"),
        ("vdot", "_npi_vdot"), ("inner", "_npi_inner"),
        ("outer", "_npi_outer"), ("digitize", "_npi_digitize"),
        ("convolve", "_npi_convolve"), ("correlate", "_npi_correlate"),
        ("polyval", "_npi_polyval"), ("searchsorted", "_npi_searchsorted"),
        ("isin", "_npi_isin"), ("in1d", "_npi_in1d"),
        ("intersect1d", "_npi_intersect1d"), ("union1d", "_npi_union1d"),
        ("setdiff1d", "_npi_setdiff1d"), ("setxor1d", "_npi_setxor1d")]:
    _g[_py] = _npi2(_opn)

for _py, _opn in [
        ("absolute", "_npi_absolute"), ("abs", "_npi_absolute"),
        ("fabs", "_npi_fabs"), ("negative", "_npi_negative"),
        ("positive", "_npi_positive"), ("exp", "_npi_exp"),
        ("exp2", "_npi_exp2"), ("expm1", "_npi_expm1"), ("log", "_npi_log"),
        ("log2", "_npi_log2"), ("log10", "_npi_log10"),
        ("log1p", "_npi_log1p"), ("sqrt", "_npi_sqrt"),
        ("cbrt", "_npi_cbrt"), ("square", "_npi_square"),
        ("reciprocal", "_npi_reciprocal"), ("sin", "_npi_sin"),
        ("cos", "_npi_cos"), ("tan", "_npi_tan"), ("arcsin", "_npi_arcsin"),
        ("arccos", "_npi_arccos"), ("arctan", "_npi_arctan"),
        ("sinh", "_npi_sinh"), ("cosh", "_npi_cosh"), ("tanh", "_npi_tanh"),
        ("arcsinh", "_npi_arcsinh"), ("arccosh", "_npi_arccosh"),
        ("arctanh", "_npi_arctanh"), ("degrees", "_npi_degrees"),
        ("radians", "_npi_radians"), ("deg2rad", "_npi_deg2rad"),
        ("rad2deg", "_npi_rad2deg"), ("sinc", "_npi_sinc"),
        ("i0", "_npi_i0"), ("sign", "_npi_sign"),
        ("signbit", "_npi_signbit"), ("floor", "_npi_floor"),
        ("ceil", "_npi_ceil"), ("trunc", "_npi_trunc"),
        ("rint", "_npi_rint"), ("fix", "_npi_fix"), ("isnan", "_npi_isnan"),
        ("isinf", "_npi_isinf"), ("isfinite", "_npi_isfinite"),
        ("isneginf", "_npi_isneginf"), ("isposinf", "_npi_isposinf"),
        ("logical_not", "_npi_logical_not"),
        ("bitwise_not", "_npi_bitwise_not"), ("invert", "_npi_invert"),
        ("real", "_npi_real"), ("imag", "_npi_imag"),
        ("conjugate", "_npi_conjugate"), ("conj", "_npi_conjugate"),
        ("nan_to_num", "_npi_nan_to_num"), ("ravel", "_npi_ravel"),
        ("fliplr", "_npi_fliplr"), ("flipud", "_npi_flipud"),
        ("flatnonzero", "_npi_flatnonzero"), ("argwhere", "_npi_argwhere"),
        ("ediff1d", "_npi_ediff1d"), ("corrcoef", "_npi_corrcoef"),
        ("trim_zeros", "_npi_trim_zeros"), ("diagflat", "_npi_diagflat"),
        ("msort", "_npi_msort")]:
    _g[_py] = _npi1(_opn)


def _red_sig(op, has_dtype=True, has_ddof=False):
    if has_ddof:
        def fn(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
            return invoke(op, a, out=out, axis=axis, dtype=dtype, ddof=ddof,
                          keepdims=keepdims)
    elif has_dtype:
        def fn(a, axis=None, dtype=None, out=None, keepdims=False):
            return invoke(op, a, out=out, axis=axis, dtype=dtype,
                          keepdims=keepdims)
    else:
        def fn(a, axis=None, out=None, keepdims=False):
            return invoke(op, a, out=out, axis=axis, keepdims=keepdims)
    fn.__name__ = op.replace("_npi_", "")
    return fn


for _py, _opn, _kind in [
        ("sum", "_npi_sum", "dtype"), ("prod", "_npi_prod", "dtype"),
        ("mean", "_npi_mean", "dtype"), ("nansum", "_npi_nansum", "dtype"),
        ("nanprod", "_npi_nanprod", "dtype"),
        ("nanmean", "_npi_nanmean", "dtype"),
        ("std", "_npi_std", "ddof"), ("var", "_npi_var", "ddof"),
        ("nanstd", "_npi_nanstd", "ddof"), ("nanvar", "_npi_nanvar", "ddof"),
        ("max", "_npi_amax", "plain"), ("amax", "_npi_amax", "plain"),
        ("min", "_npi_amin", "plain"), ("amin", "_npi_amin", "plain"),
        ("nanmax", "_npi_nanmax", "plain"),
        ("nanmin", "_npi_nanmin", "plain"), ("ptp", "_npi_ptp", "plain"),
        ("all", "_npi_all", "plain"), ("any", "_npi_any", "plain"),
        ("median", "_npi_median", "plain"),
        ("nanmedian", "_npi_nanmedian", "plain"),
        ("count_nonzero", "_npi_count_nonzero", "plain")]:
    _g[_py] = _red_sig(_opn, has_dtype=_kind == "dtype",
                       has_ddof=_kind == "ddof")


def _argred_sig(op):
    def fn(a, axis=None, out=None, keepdims=False):
        return invoke(op, a, out=out, axis=axis, keepdims=keepdims)
    fn.__name__ = op.replace("_npi_", "")
    return fn


argmax = _argred_sig("_npi_argmax")
argmin = _argred_sig("_npi_argmin")
nanargmax = _argred_sig("_npi_nanargmax")
nanargmin = _argred_sig("_npi_nanargmin")


def _cum_sig(op):
    def fn(a, axis=None, dtype=None, out=None):
        return invoke(op, a, out=out, axis=axis, dtype=dtype)
    fn.__name__ = op.replace("_npi_", "")
    return fn


cumsum = _cum_sig("_npi_cumsum")
cumprod = _cum_sig("_npi_cumprod")
nancumsum = _cum_sig("_npi_nancumsum")
nancumprod = _cum_sig("_npi_nancumprod")


def percentile(a, q, axis=None, out=None, method="linear", keepdims=False,
               interpolation=None):
    return invoke("_npi_percentile", a, out=out, q=float(q) if _onp.isscalar(q)
                  else tuple(q), axis=axis,
                  method=interpolation or method, keepdims=keepdims)


def quantile(a, q, axis=None, out=None, method="linear", keepdims=False,
             interpolation=None):
    return invoke("_npi_quantile", a, out=out, q=float(q) if _onp.isscalar(q)
                  else tuple(q), axis=axis,
                  method=interpolation or method, keepdims=keepdims)


def nanpercentile(a, q, axis=None, out=None, method="linear",
                  keepdims=False):
    return invoke("_npi_nanpercentile", a, out=out,
                  q=float(q) if _onp.isscalar(q) else tuple(q), axis=axis,
                  method=method, keepdims=keepdims)


def nanquantile(a, q, axis=None, out=None, method="linear", keepdims=False):
    return invoke("_npi_nanquantile", a, out=out,
                  q=float(q) if _onp.isscalar(q) else tuple(q), axis=axis,
                  method=method, keepdims=keepdims)


def average(a, axis=None, weights=None, returned=False):
    if weights is None:
        out = invoke("_npi_average", a, axis=axis)
    else:
        out = invoke("_npi_average", a, weights, axis=axis)
    if returned:
        w = (full_like(a, 1.0) if weights is None else weights)
        return out, sum(w, axis=axis) if axis is not None else sum(w)
    return out


def unique(ar, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    return invoke("_npi_unique", ar, return_index=return_index,
                  return_inverse=return_inverse,
                  return_counts=return_counts, axis=axis)


def nonzero(a):
    return tuple(invoke("_npi_nonzero", a))


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    return invoke("_npi_where", condition, x, y)


def take_along_axis(arr, indices, axis):
    return invoke("_npi_take_along_axis", arr, indices, axis=axis)


def compress(condition, a, axis=None, out=None):
    return invoke("_npi_compress", condition, a, out=out, axis=axis)


def extract(condition, arr):
    return invoke("_npi_extract", condition, arr)


def select(condlist, choicelist, default=0):
    return invoke("_npi_select", *(list(condlist) + list(choicelist)),
                  default=default)


def moveaxis(a, source, destination):
    return invoke("_npi_moveaxis", a,
                  source=tuple(source) if isinstance(source, (list, tuple))
                  else source,
                  destination=tuple(destination)
                  if isinstance(destination, (list, tuple)) else destination)


def rollaxis(a, axis, start=0):
    return invoke("_npi_rollaxis", a, axis=axis, start=start)


def append(arr, values, axis=None):
    return invoke("_npi_append", arr, values, axis=axis)


def delete(arr, obj, axis=None):
    if isinstance(obj, NDArray):
        obj = [int(v) for v in obj.asnumpy().ravel()]
    elif isinstance(obj, _onp.ndarray):
        obj = [int(v) for v in obj.ravel()]
    return invoke("_npi_delete", arr, obj=obj, axis=axis)


def insert(arr, obj, values, axis=None):
    if isinstance(obj, NDArray):
        obj = [int(v) for v in obj.asnumpy().ravel()]
    elif isinstance(obj, _onp.ndarray):
        obj = [int(v) for v in obj.ravel()]
    return invoke("_npi_insert", arr, values, obj=obj, axis=axis)


def interp(x, xp, fp, left=None, right=None):
    return invoke("_npi_interp", x, xp, fp, left=left, right=right)


def gradient(f, *varargs, axis=None):
    return invoke("_npi_gradient", f, *varargs, axis=axis)


def diff(a, n=1, axis=-1):
    return invoke("_npi_diff", a, n=n, axis=axis)


def cov(m, y=None, rowvar=True, bias=False, ddof=None):
    if y is not None:
        m = vstack((m, y))
    return invoke("_npi_cov", m, rowvar=rowvar, bias=bias, ddof=ddof)


def meshgrid(*xi, indexing="xy", sparse=False):
    return tuple(invoke("_npi_meshgrid", *xi, indexing=indexing,
                        sparse=sparse))


def broadcast_arrays(*args):
    return tuple(invoke("_npi_broadcast_arrays", *args))


def vstack(tup, **kw):
    return invoke("_npi_vstack", *tup)


row_stack = vstack


def hstack(tup, **kw):
    return invoke("_npi_hstack", *tup)


def dstack(tup, **kw):
    return invoke("_npi_dstack", *tup)


def column_stack(tup, **kw):
    return invoke("_npi_column_stack", *tup)


def array_split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    return invoke("_npi_array_split", ary,
                  indices_or_sections=ios if isinstance(ios, int)
                  else tuple(ios), axis=axis)


def hsplit(ary, indices_or_sections):
    ios = indices_or_sections
    return invoke("_npi_hsplit", ary,
                  indices_or_sections=ios if isinstance(ios, int)
                  else tuple(ios))


def vsplit(ary, indices_or_sections):
    ios = indices_or_sections
    return invoke("_npi_vsplit", ary,
                  indices_or_sections=ios if isinstance(ios, int)
                  else tuple(ios))


def dsplit(ary, indices_or_sections):
    ios = indices_or_sections
    return invoke("_npi_dsplit", ary,
                  indices_or_sections=ios if isinstance(ios, int)
                  else tuple(ios))


def tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(x) if isinstance(x, (list, tuple)) else int(x)
                     for x in axes)
    return invoke("_npi_tensordot", a, b, axes=axes)


def lexsort(keys, axis=-1):
    return invoke("_npi_lexsort", *keys, axis=axis)


def partition(a, kth, axis=-1):
    return invoke("_npi_partition", a, kth=kth, axis=axis)


def argpartition(a, kth, axis=-1):
    return invoke("_npi_argpartition", a, kth=kth, axis=axis)


def tri(N, M=None, k=0, dtype=None):
    return invoke("_npi_tri", N=N, M=M, k=k, dtype=_onp.dtype(dtype).name
                  if dtype else None)


def vander(x, N=None, increasing=False):
    return invoke("_npi_vander", x, N=N, increasing=increasing)


def tril_indices(n, k=0, m=None):
    return tuple(invoke("_npi_tril_indices", n=n, k=k, m=m))


def triu_indices(n, k=0, m=None):
    return tuple(invoke("_npi_triu_indices", n=n, k=k, m=m))


def diag_indices_from(arr):
    return tuple(invoke("_npi_diag_indices_from", arr))


def indices(dimensions, dtype=None):
    return invoke("_npi_indices", dimensions=tuple(dimensions),
                  dtype=_onp.dtype(dtype).name if dtype else None)


def full_like(a, fill_value, dtype=None):
    return invoke("_npi_full_like", a, fill_value=fill_value,
                  dtype=_onp.dtype(dtype).name if dtype else None)


def empty_like(prototype, dtype=None):
    return invoke("_npi_empty_like", prototype,
                  dtype=_onp.dtype(dtype).name if dtype else None)


def identity(n, dtype=None):
    return invoke("_npi_identity", n=n,
                  dtype=_onp.dtype(dtype).name if dtype else None)


def bartlett(M):
    return invoke("_npi_bartlett", M=M)


def blackman(M):
    return invoke("_npi_blackman_np", M=M)


def hamming(M):
    return invoke("_npi_hamming_np", M=M)


def hanning(M):
    return invoke("_npi_hanning_np", M=M)


def unwrap(p, discont=None, axis=-1, period=6.283185307179586):
    return invoke("_npi_unwrap", p, discont=discont, axis=axis,
                  period=period)


def spacing(x):
    return invoke("_npi_spacing", x)


def polyadd(a1, a2):
    return invoke("_npi_polyadd", a1, a2)


def polysub(a1, a2):
    return invoke("_npi_polysub", a1, a2)


def polymul(a1, a2):
    return invoke("_npi_polymul", a1, a2)


def polydiv(u, v):
    return tuple(invoke("_npi_polydiv", u, v))


def polyder(p, m=1):
    return invoke("_npi_polyder", p, m=m)


def polyint(p, m=1):
    return invoke("_npi_polyint", p, m=m)


def polyfit(x, y, deg):
    return invoke("_npi_polyfit", x, y, deg=deg)


def roots(p):
    return invoke("_npi_roots", p)


def poly(seq_of_zeros):
    return invoke("_npi_poly", seq_of_zeros)


def histogram_bin_edges(a, bins=10, range=None):
    return invoke("_npi_histogram_bin_edges", a, bins=bins,
                  range=tuple(range) if range is not None else None)


def real_if_close(a, tol=100.0):
    return invoke("_npi_real_if_close", a, tol=tol)


def matrix_transpose(x):
    return invoke("_npi_matrix_transpose", x)


def iscomplexobj(x):
    return _onp.issubdtype(_onp.dtype(getattr(x, "dtype", type(x))),
                           _onp.complexfloating)


def isrealobj(x):
    return not iscomplexobj(x)


def shares_memory(a, b, max_work=None):
    """Chunk identity is the only aliasing this NDArray model has: views
    share their root chunk; separate arrays never share."""
    ca = getattr(a, "_chunk", None)
    cb = getattr(b, "_chunk", None)
    return ca is not None and ca is cb


may_share_memory = shares_memory


def einsum_path(*operands, optimize="greedy"):
    ops = [o.asnumpy() if isinstance(o, NDArray) else o for o in operands]
    return _onp.einsum_path(*ops, optimize=optimize)


def common_type(*arrays):
    return _onp.common_type(*[_onp.empty(0, dtype=a.dtype)
                              for a in arrays])


def min_scalar_type(a):
    return _onp.min_scalar_type(a.asnumpy() if isinstance(a, NDArray)
                                else a)


def place(arr, mask, vals):
    """numpy.place: in-place write of `vals` (cycled over the running
    True count) at mask positions."""
    out = invoke("_npi_place_impl", arr, mask,
                 vals if isinstance(vals, NDArray) else array(vals))
    arr._set_jax(out._jax)


def putmask(a, mask, values):
    """numpy.putmask: in-place write, values cycled by flat position."""
    out = invoke("_npi_putmask_impl", a, mask,
                 values if isinstance(values, NDArray) else array(values))
    a._set_jax(out._jax)


def copyto(dst, src, where=True):
    """numpy.copyto onto an NDArray destination."""
    src = src if isinstance(src, NDArray) else array(src)
    if where is True:
        out = broadcast_to(src, dst.shape).astype(dst.dtype)
    else:
        w = where if isinstance(where, NDArray) else array(where)
        # numpy.copyto preserves the destination dtype even when the
        # where-select promotes
        out = invoke("_npi_where", w, src, dst).astype(dst.dtype)
    dst._set_jax(out._jax if isinstance(out, NDArray) else out)


def fromiter(iterable, dtype, count=-1):
    return array(_onp.fromiter(iterable, dtype=dtype, count=count))


def frombuffer(buffer, dtype=float, count=-1, offset=0):
    return array(_onp.frombuffer(buffer, dtype=dtype, count=count,
                                 offset=offset))


def fromstring(string, dtype=float, count=-1, sep=""):
    return array(_onp.fromstring(string, dtype=dtype, count=count,
                                 sep=sep))


class _IndexGrid:
    """np.mgrid/ogrid index tricks (dense/open) over NDArray outputs."""

    def __init__(self, sparse):
        self._sparse = sparse

    def __getitem__(self, key):
        out = (_onp.ogrid if self._sparse else _onp.mgrid)[key]
        if isinstance(out, _onp.ndarray):
            return array(out)
        return [array(o) for o in out]


mgrid = _IndexGrid(sparse=False)
ogrid = _IndexGrid(sparse=True)


def kaiser(M, beta):
    return invoke("_npi_kaiser", M=M, beta=beta)


def trapz(y, x=None, dx=1.0, axis=-1):
    if x is None:
        return invoke("_npi_trapz", y, dx=dx, axis=axis)
    return invoke("_npi_trapz", y, x, axis=axis)


trapezoid = trapz


def histogram(a, bins=10, range=None, weights=None, density=None):
    if range is None:
        a_np = a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)
        range = (float(a_np.min()), float(a_np.max()))
    if weights is None:
        out = invoke("_npi_histogram", a, bins=bins, range=tuple(range),
                     density=bool(density))
    else:
        out = invoke("_npi_histogram", a, weights, bins=bins,
                     range=tuple(range), density=bool(density))
    return tuple(out)


def bincount(x, weights=None, minlength=0):
    if weights is None:
        return invoke("_npi_bincount", x, minlength=minlength)
    return invoke("_npi_bincount", x, weights, minlength=minlength)


def divmod_(a, b):
    return tuple(invoke("_npi_divmod", a, b))


divmod = divmod_


def modf(a):
    return tuple(invoke("_npi_modf", a))


def frexp(a):
    return tuple(invoke("_npi_frexp", a))


def around(a, decimals=0, out=None):
    return invoke("_npi_around", a, out=out, decimals=decimals)


round = around
round_ = around


def clip(a, a_min, a_max, out=None):
    return invoke("_npi_clip", a, out=out, a_min=a_min, a_max=a_max)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None, device=None):
    return invoke("_npi_logspace", start=start, stop=stop, num=num,
                  endpoint=endpoint, base=base,
                  dtype=_onp.dtype(dtype).name if dtype else None,
                  ctx=ctx or device)


def geomspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None,
              device=None):
    return invoke("_npi_geomspace", start=start, stop=stop, num=num,
                  endpoint=endpoint,
                  dtype=_onp.dtype(dtype).name if dtype else None,
                  ctx=ctx or device)


# ---------------------------------------------------------------------------
# NumPy-2.0 / array-API aliases (numpy renamed these in 2.0; exposing both
# spellings keeps mx.np usable as a drop-in with new-style user code)
# ---------------------------------------------------------------------------
acos = arccos
acosh = arccosh
asin = arcsin
asinh = arcsinh
atan = arctan
atan2 = arctan2
atanh = arctanh
concat = concatenate
permute_dims = transpose
pow = power
bitwise_invert = invert
bitwise_left_shift = left_shift
bitwise_right_shift = right_shift


def broadcast_shapes(*shapes):
    return _onp.broadcast_shapes(*shapes)


def finfo(dtype):
    return _onp.finfo(_onp.dtype(getattr(dtype, "dtype", dtype)))


def iinfo(dtype):
    return _onp.iinfo(_onp.dtype(getattr(dtype, "dtype", dtype)))


def astype(x, dtype, copy=True):
    return x.astype(dtype, copy=copy)


def block(arrays):
    """Assemble nested lists of arrays (np.block subset: nested lists,
    no bare-scalar mixing)."""
    ctx = None

    def find_ctx(a):
        nonlocal ctx
        if isinstance(a, list):
            for x in a:
                find_ctx(x)
        elif ctx is None:
            ctx = _src_ctx(a)
    find_ctx(arrays)
    return _wrap(jnp.block(_unwrap(arrays)), ctx=ctx)


def choose(a, choices, out=None, mode="raise"):
    """numpy.choose over the registry ops (stack + take_along_axis), so
    float choices stay on the autograd tape; XLA cannot raise on
    out-of-range, so mode='raise' checks eagerly when possible and
    otherwise clips."""
    idx = asarray(a).astype("int32")
    n = len(choices)
    if mode == "wrap":
        idx = mod(idx, n)
    elif mode == "clip":
        idx = clip(idx, 0, n - 1)
    else:
        try:
            inp = _onp.asarray(_unwrap(asarray(a)))
            if inp.size and (inp.min() < 0 or inp.max() >= n):
                raise ValueError(
                    "choose: index out of range for %d choices" % n)
        except TypeError:
            pass   # traced index: fall through to clipped gather
        idx = clip(idx, 0, n - 1)
    from ..ndarray.ndarray import NDArray as _ND
    chs = [c if isinstance(c, _ND) else asarray(c) for c in choices]
    # numpy semantics: index and choices broadcast together
    common = _onp.broadcast_shapes(tuple(idx.shape),
                                   *[tuple(c.shape) for c in chs])
    idx = broadcast_to(idx, common)
    ch = stack([c if tuple(c.shape) == common else broadcast_to(c, common)
                for c in chs])    # broadcast_to is a registry op: taped
    return take_along_axis(ch, expand_dims(idx, 0), 0)[0]


def put_along_axis(arr, indices, values, axis):
    """Out-of-place variant (functional substrate): returns the updated
    array AND writes through when `arr` is an NDArray."""
    a = _unwrap(arr)
    res = jnp.put_along_axis(a, _unwrap(indices),
                             jnp.asarray(_unwrap(values)).astype(a.dtype),
                             axis, inplace=False)
    if hasattr(arr, "_set_jax"):
        arr._set_jax(res)
        return arr
    return _wrap(res, ctx=_src_ctx(arr))


def _check_2d(arr, what):
    if len(arr.shape) != 2:
        raise ValueError("%s: input array must be 2-d" % what)


def tril_indices_from(arr, k=0):
    _check_2d(arr, "tril_indices_from")
    return tril_indices(arr.shape[0], k=k, m=arr.shape[1])


def triu_indices_from(arr, k=0):
    _check_2d(arr, "triu_indices_from")
    return triu_indices(arr.shape[0], k=k, m=arr.shape[1])


def ix_(*args):
    conv = []
    for a in args:
        ja = _unwrap(asarray(a))
        if ja.dtype == jnp.bool_:
            ja = jnp.nonzero(ja)[0]       # numpy: masks become indices
        conv.append(ja)
    outs = jnp.ix_(*conv)
    ctx = _src_ctx(args[0]) if args else None
    return tuple(_wrap(o, ctx=ctx) for o in outs)


def mask_indices(n, mask_func, k=0):
    """numpy semantics: apply mask_func to an (n, n) ones matrix and
    return the nonzero indices — works with any triu/tril-like callable
    (ours or numpy's)."""
    m = ones((n, n))
    a = mask_func(m, k)
    return nonzero(a)
