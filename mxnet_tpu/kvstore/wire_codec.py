"""Host-side wire codecs (numpy only — no jax, no ops registry).

The dist_async TCP path ships compressed gradients as compact picklable
``QGRAD`` tuples; the parameter server decodes them BEFORE its
updater/accumulator sees the value (the optimizer contract is full-width
gradients).  This module is deliberately free of jax imports so the
server's PUSH hot path never drags in the device kernel stack — the
jitted kernels live in :mod:`mxnet_tpu.ops.quantization`, and
:mod:`.gradient_compression` (which owns residual state) re-exports
these helpers for compatibility.

Formats (see docs/ARCHITECTURE.md "Gradient wire format"):
  int8:  ``(QGRAD, 'int8', shape, dtype, n, q_bytes, scales_f32)``
  2bit:  ``(QGRAD, '2bit', shape, dtype, n, words_u32, threshold)``

The packed 2-bit layout (16 codes per uint32 word, code i at bits
[2i, 2i+1], 00=zero 01=-t 10=+t) is bit-compatible with the device pack
(`ops.quantization.pack_2bit_words`); the parity test pins it.

The serving engine (ISSUE 9) rides the same numpy-only contract: its
PREDICT request/response tensors cross the socket as compact ``NPX``
tuples (:func:`encode_array`/:func:`decode_array`), so neither the
serving client nor a health-probing tool ever needs the device stack to
talk the wire, and a device array can never leak into a pickle.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["WireCodecError",
           "is_wire_payload", "encode_wire", "decode_wire",
           "pack_2bit", "unpack_2bit", "quantize_int8_np",
           "is_array_payload", "encode_array", "decode_array",
           "is_text_payload", "encode_text", "decode_text",
           "is_json_payload", "encode_json", "decode_json"]

_WIRE_TAG = "QGRAD"
_ARR_TAG = "NPX"


class WireCodecError(ValueError):
    """A wire payload failed structural validation while decoding.

    Every ``decode_*`` in this module raises this — and only this — on
    a malformed payload (wrong tag, truncated bytes, inconsistent
    shape/dtype/length, undecodable utf-8/json): the decode either
    returns a fully-built value or raises cleanly BEFORE any caller
    state is touched, so a corrupt frame can never partially apply.
    Subclasses ``ValueError`` so pre-existing ``except ValueError``
    call sites keep working."""


def _codec_fail(what, detail):
    raise WireCodecError("%s: %s" % (what, detail))


def _expect_bytes(what, raw):
    if not isinstance(raw, (bytes, bytearray)):
        _codec_fail(what, "payload bytes field is %s, not bytes"
                    % type(raw).__name__)
    return bytes(raw)


def _expect_shape(what, shape):
    if not (isinstance(shape, tuple) and
            all(isinstance(s, int) and s >= 0 for s in shape)):
        _codec_fail(what, "shape field %r is not a tuple of "
                    "non-negative ints" % (shape,))
    n = 1
    for s in shape:
        n *= s
    return n


def _expect_dtype(what, dtype):
    try:
        return _np.dtype(dtype)
    except (TypeError, ValueError) as e:
        _codec_fail(what, "bad dtype %r (%s)" % (dtype, e))


def is_array_payload(obj) -> bool:
    return isinstance(obj, tuple) and len(obj) == 4 and obj[0] == _ARR_TAG


def encode_array(arr) -> tuple:
    """One tensor as a compact picklable tuple:
    ``(NPX, shape, dtype_str, row_major_bytes)``.

    Accepts anything numpy can view (ndarray, NDArray via __array__,
    jax array via __array__) but always emits plain host bytes — the
    wire stays device-free by construction.
    """
    a = _np.asarray(arr)
    shape = tuple(int(s) for s in a.shape)   # BEFORE ascontiguousarray
    a = _np.ascontiguousarray(a)             # (it promotes 0-d to 1-d)
    return (_ARR_TAG, shape, str(a.dtype), a.tobytes())


def decode_array(obj) -> _np.ndarray:
    """Inverse of :func:`encode_array`; returns a writable ndarray.

    Raises :class:`WireCodecError` on any malformed payload (wrong
    tag, truncated/overlong bytes, bad shape or dtype) — never a bare
    numpy exception, never a partially-built array."""
    if not is_array_payload(obj):
        raise WireCodecError("not an NPX array payload: %r"
                             % (type(obj),))
    _, shape, dtype, raw = obj
    n = _expect_shape("NPX", shape)
    dt = _expect_dtype("NPX", dtype)
    raw = _expect_bytes("NPX", raw)
    if len(raw) != n * dt.itemsize:
        _codec_fail("NPX", "payload is %d bytes but shape %r of %s "
                    "needs %d" % (len(raw), shape, dt, n * dt.itemsize))
    return _np.frombuffer(raw, dtype=dt).reshape(shape).copy()


_TXT_TAG = "TXT"


def is_text_payload(obj) -> bool:
    return isinstance(obj, tuple) and len(obj) == 2 and obj[0] == _TXT_TAG


def encode_text(text: str) -> tuple:
    """A (possibly large) text blob as a compact picklable tuple —
    ``(TXT, utf8_bytes)``.  The serving METRICS verb ships its
    Prometheus snapshot this way so the exposition crosses the wire as
    one bytes payload, not a python str pickle."""
    return (_TXT_TAG, str(text).encode("utf-8"))


def decode_text(obj) -> str:
    """Raises :class:`WireCodecError` on a non-TXT tuple or bytes that
    are not valid utf-8 (a bit-flipped frame must fail typed, not leak
    a UnicodeDecodeError into the handler)."""
    if not is_text_payload(obj):
        raise WireCodecError("not a TXT payload: %r" % (type(obj),))
    raw = _expect_bytes("TXT", obj[1])
    try:
        return raw.decode("utf-8")
    except UnicodeDecodeError as e:
        _codec_fail("TXT", "payload is not valid utf-8 (%s)" % (e,))


_JSN_TAG = "JSN"


def is_json_payload(obj) -> bool:
    return isinstance(obj, tuple) and len(obj) == 2 and obj[0] == _JSN_TAG


def encode_json(obj) -> tuple:
    """A JSON-able structure as a compact picklable tuple —
    ``(JSN, utf8_bytes)``.  The fleet FLEET verb ships its merged
    snapshot this way: the payload is a typed document (not free text),
    crosses the wire as one bytes blob, and the receiving side gets a
    plain dict with no pickle-of-arbitrary-objects surface."""
    import json as _json
    return (_JSN_TAG, _json.dumps(obj, default=str).encode("utf-8"))


def decode_json(obj):
    """Raises :class:`WireCodecError` on a non-JSN tuple, non-utf-8
    bytes, or bytes that do not parse as one JSON document."""
    if not is_json_payload(obj):
        raise WireCodecError("not a JSN payload: %r" % (type(obj),))
    import json as _json
    raw = _expect_bytes("JSN", obj[1])
    try:
        return _json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        _codec_fail("JSN", "payload does not parse as JSON (%s)" % (e,))


def is_wire_payload(obj) -> bool:
    return isinstance(obj, tuple) and len(obj) >= 2 and obj[0] == _WIRE_TAG


def encode_wire(mode: str, shape, dtype, payload) -> tuple:
    """Build the compact picklable wire tuple for one pushed value.

    int8:  ``(QGRAD, 'int8', shape, dtype, n, q_bytes, scales_f32)``
    2bit:  ``(QGRAD, '2bit', shape, dtype, n, words_u32, threshold)``
    """
    mode = str(mode)
    shape = tuple(int(s) for s in shape)
    n = 1
    for s in shape:
        n *= s
    if mode == "int8":
        q, scales = payload
        return (_WIRE_TAG, "int8", shape, str(dtype), n,
                _np.asarray(q, _np.int8).tobytes(),
                _np.asarray(scales, _np.float32))
    if mode == "2bit":
        words, threshold = payload
        return (_WIRE_TAG, "2bit", shape, str(dtype), n,
                _np.asarray(words, _np.uint32), float(threshold))
    raise ValueError("unknown gradient wire mode %r" % (mode,))


def decode_wire(obj) -> _np.ndarray:
    """Dequantize a wire tuple back to a full-width numpy array (server
    side, before the updater / accumulator sees it).

    Raises :class:`WireCodecError` on any malformed tuple — wrong tag,
    short tuple, shape/count mismatch, truncated quantized bytes,
    block/scale inconsistency — so a corrupt PUSH frame fails BEFORE
    the optimizer or accumulator sees a garbage gradient."""
    if not is_wire_payload(obj):
        raise WireCodecError("not a QGRAD wire payload: %r"
                             % (type(obj),))
    if len(obj) != 7:
        _codec_fail("QGRAD", "tuple has %d fields, expected 7"
                    % len(obj))
    _, mode, shape, dtype, n = obj[:5]
    n_shape = _expect_shape("QGRAD", shape)
    dt = _expect_dtype("QGRAD", dtype)
    if not isinstance(n, int) or n != n_shape:
        _codec_fail("QGRAD", "element count %r does not match shape %r "
                    "(%d elements)" % (n, shape, n_shape))
    if mode == "int8":
        raw = _expect_bytes("QGRAD int8", obj[5])
        try:
            scales = _np.asarray(obj[6], _np.float32)
        except (TypeError, ValueError) as e:
            _codec_fail("QGRAD int8", "bad scales field (%s)" % (e,))
        if scales.ndim != 1 or scales.size == 0:
            _codec_fail("QGRAD int8", "scales must be a non-empty 1-d "
                        "float array, got shape %r"
                        % (getattr(scales, "shape", None),))
        q = _np.frombuffer(raw, dtype=_np.int8).astype(_np.float32)
        if q.size < n or q.size % scales.size != 0:
            _codec_fail("QGRAD int8", "%d quantized bytes cannot cover "
                        "%d elements in %d equal blocks"
                        % (q.size, n, scales.size))
        block = q.size // scales.size
        flat = (q.reshape(-1, block) * scales[:, None]).reshape(-1)[:n]
    elif mode == "2bit":
        try:
            words = _np.asarray(obj[5], _np.uint32)
            threshold = float(obj[6])
        except (TypeError, ValueError) as e:
            _codec_fail("QGRAD 2bit", "bad words/threshold field (%s)"
                        % (e,))
        if words.ndim != 1 or words.size * 16 < n:
            _codec_fail("QGRAD 2bit", "%r uint32 words carry %d codes, "
                        "need %d" % (getattr(words, "shape", None),
                                     words.size * 16, n))
        flat = unpack_2bit(words, n, threshold)
    else:
        _codec_fail("QGRAD", "unknown gradient wire mode %r" % (mode,))
    return flat.astype(dt).reshape(shape)


def quantize_int8_np(flat, block: int = 256):
    """Per-block symmetric int8 quantization of a flat float array — the
    numpy mirror of ``ops.quantization.quantize_int8_blocks``, minus
    error feedback (the server-side PULLQ encode is stateless: the pull
    leg's quantization error is NOT fed back anywhere, which is why the
    quantized pull is an opt-in hierarchical-exchange tier, not the
    default PULL).  Returns ``(q_int8, scales_f32)`` with the tail block
    zero-padded; :func:`decode_wire` trims the pad via the element count
    carried in the tuple."""
    flat = _np.asarray(flat, _np.float32).ravel()
    block = max(1, int(block))
    pad = (-flat.size) % block
    if pad:
        flat = _np.concatenate([flat, _np.zeros(pad, _np.float32)])
    blocks = flat.reshape(-1, block)
    scales = (_np.abs(blocks).max(axis=1) / 127.0).astype(_np.float32)
    safe = _np.where(scales > 0, scales, 1.0).astype(_np.float32)
    q = _np.clip(_np.rint(blocks / safe[:, None]),
                 -127, 127).astype(_np.int8)
    return q.reshape(-1), scales


def pack_2bit(levels: _np.ndarray, threshold: float) -> _np.ndarray:
    """Pack ±t/0 levels into the 2-bit wire format: 16 codes per uint32
    word, code i of a word at bits [2i, 2i+1], 00=zero 01=-t 10=+t
    (reference Quantize2BitImpl packs 16 values per float32 word; the
    in-word bit order is pinned by the roundtrip test)."""
    flat = _np.asarray(levels, _np.float32).ravel()
    codes = _np.where(flat > 0, 2, _np.where(flat < 0, 1, 0)).astype(
        _np.uint32)
    pad = (-len(codes)) % 16
    if pad:
        codes = _np.concatenate([codes, _np.zeros(pad, _np.uint32)])
    words = codes.reshape(-1, 16)
    out = _np.zeros(words.shape[0], _np.uint32)
    for i in range(16):
        out |= words[:, i] << (2 * i)
    return out


def unpack_2bit(words: _np.ndarray, n: int, threshold: float,
                dtype=_np.float32) -> _np.ndarray:
    """Inverse of pack_2bit: first `n` codes back to ±threshold/0."""
    words = _np.asarray(words, _np.uint32)
    codes = _np.zeros((len(words), 16), _np.uint32)
    for i in range(16):
        codes[:, i] = (words >> (2 * i)) & 0x3
    codes = codes.ravel()[:n]
    out = _np.zeros(n, dtype)
    out[codes == 2] = threshold
    out[codes == 1] = -threshold
    return out
