"""Fusion buckets: coalesced gradient exchange (ISSUE 3 tentpole b).

Reference role: MXNet's kvstore groups small dense tensors so the wire /
collective layer sees a few large messages instead of one op per key (the
collective-coalescing direction of arXiv:1802.06949; NCCL-era MXNet did the
same via flattened buffer fusion).  Here a deterministic planner assigns
small dense keys to flat per-dtype buckets of ``MX_KVSTORE_BUCKET_KB``
(default 4 MB); a ResNet-scale push/pull then costs a few bucket exchanges
rather than ~160 per-key RPCs or collectives.

Determinism contract: the layout is a pure function of the ordered
``(key, shape, dtype)`` descriptors and the bucket byte cap, so every
worker — and, for the parameter-server store, every client of the same
server — derives the same key→bucket mapping with no coordination.  The
bucket's wire key embeds a CRC of its member descriptors: if any member's
shape/dtype (or the member set) changes, the name changes with it, and a
stale server entry can never be misread as the new layout.  Stores cache
plans per signature (KVStore._bucket_plans), which is the persisted form
of the layout within a process.

Sparse values are never bucketed: a row_sparse gradient's payload is
(data, indices) keyed on nnz — it has no stable flat extent to place at a
fixed bucket offset.  Values larger than the cap stay solo (they already
amortize their dispatch; the PS big-array path additionally shards them).
"""
from __future__ import annotations

import zlib
from typing import List, Sequence, Tuple

from ..base import get_env

__all__ = ["Bucket", "bucket_bytes", "plan_buckets"]


def bucket_bytes() -> int:
    """Configured bucket capacity in bytes; 0 disables bucketing."""
    kb = get_env("MX_KVSTORE_BUCKET_KB", 4096, int)
    return max(0, int(kb)) * 1024


class Bucket:
    """One fusion bucket: an ordered slice layout over member keys."""

    __slots__ = ("name", "positions", "keys", "offsets", "sizes", "shapes",
                 "dtype", "total")

    def __init__(self, index: int, positions: Sequence[int],
                 keys: Sequence, sizes: Sequence[int],
                 shapes: Sequence[Tuple[int, ...]], dtype: str):
        self.positions = list(positions)     # indices into the caller's keys
        self.keys = list(keys)
        self.sizes = list(sizes)
        self.shapes = [tuple(s) for s in shapes]
        self.dtype = dtype
        self.offsets = []
        off = 0
        for n in self.sizes:
            self.offsets.append(off)
            off += n
        self.total = off
        desc = ";".join("%s:%s:%s" % (k, "x".join(map(str, s)), dtype)
                        for k, s in zip(self.keys, self.shapes))
        # index + member CRC: stable across steps/workers, distinct across
        # layout changes
        self.name = "__fusedb%d_%08x" % (index, zlib.crc32(desc.encode()))

    def slices(self):
        """(position, offset, size, shape) per member, in layout order."""
        return zip(self.positions, self.offsets, self.sizes, self.shapes)

    def __repr__(self):
        return "Bucket(%s, n=%d, total=%d, %s)" % (
            self.name, len(self.keys), self.total, self.dtype)


def plan_buckets(keys: Sequence, shapes: Sequence[Tuple[int, ...]],
                 dtypes: Sequence[str], itemsizes: Sequence[int],
                 stypes: Sequence[str], max_bytes: int):
    """Greedy first-fit in key order, one dtype per bucket.

    Returns ``(buckets, solo_positions)``: positions not covered by any
    bucket (sparse, over-cap, lone-member dtypes) take the per-key path.
    Deterministic in its inputs — see the module docstring contract.
    """
    solo: List[int] = []
    open_by_dtype = {}    # dtype -> (positions, nbytes)
    closed: List[List[int]] = []

    def close(dtype):
        poss, _ = open_by_dtype.pop(dtype)
        if len(poss) > 1:
            closed.append(poss)
        else:
            solo.extend(poss)

    for pos, (shape, dtype, isz, stype) in enumerate(
            zip(shapes, dtypes, itemsizes, stypes)):
        size = 1
        for d in shape:
            size *= int(d)
        nbytes = size * int(isz)
        if stype != "default" or max_bytes <= 0 or nbytes > max_bytes:
            solo.append(pos)
            continue
        poss, used = open_by_dtype.get(dtype, ([], 0))
        if poss and used + nbytes > max_bytes:
            close(dtype)
            poss, used = [], 0
        poss.append(pos)
        open_by_dtype[dtype] = (poss, used + nbytes)
    for dtype in list(open_by_dtype):
        close(dtype)

    buckets = []
    for bi, poss in enumerate(sorted(closed, key=lambda p: p[0])):
        sizes = []
        for p in poss:
            n = 1
            for d in shapes[p]:
                n *= int(d)
            sizes.append(n)
        buckets.append(Bucket(bi, poss, [keys[p] for p in poss], sizes,
                              [shapes[p] for p in poss], str(dtypes[poss[0]])))
    return buckets, sorted(solo)
