"""Fusion buckets: coalesced gradient exchange (ISSUE 3 tentpole b).

Reference role: MXNet's kvstore groups small dense tensors so the wire /
collective layer sees a few large messages instead of one op per key (the
collective-coalescing direction of arXiv:1802.06949; NCCL-era MXNet did the
same via flattened buffer fusion).  Here a deterministic planner assigns
small dense keys to flat per-dtype buckets of ``MX_KVSTORE_BUCKET_KB``
(default 4 MB); a ResNet-scale push/pull then costs a few bucket exchanges
rather than ~160 per-key RPCs or collectives.

Determinism contract: the layout is a pure function of the ordered
``(key, shape, dtype)`` descriptors and the bucket byte cap, so every
worker — and, for the parameter-server store, every client of the same
server — derives the same key→bucket mapping with no coordination.  The
bucket's wire key embeds a CRC of its member descriptors (plus an
optional ``salt`` — elastic jobs pass the membership epoch, so a resize
rolls every name): if any member's shape/dtype (or the member set, or
the salt) changes, the name changes with it, and a
stale server entry can never be misread as the new layout.  Stores cache
plans per signature (KVStore._bucket_plans), which is the persisted form
of the layout within a process.

Sparse values are never bucketed: a row_sparse gradient's payload is
(data, indices) keyed on nnz — it has no stable flat extent to place at a
fixed bucket offset.  Values larger than the cap stay solo (they already
amortize their dispatch; the PS big-array path additionally shards them).
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Sequence, Set, Tuple

from ..base import get_env

__all__ = ["Bucket", "bucket_bytes", "plan_buckets", "ReadinessPlanner"]


def bucket_bytes() -> int:
    """Configured bucket capacity in bytes; 0 disables bucketing."""
    kb = get_env("MX_KVSTORE_BUCKET_KB", 4096, int)
    return max(0, int(kb)) * 1024


class Bucket:
    """One fusion bucket: an ordered slice layout over member keys."""

    __slots__ = ("name", "positions", "keys", "offsets", "sizes", "shapes",
                 "dtype", "total")

    def __init__(self, index: int, positions: Sequence[int],
                 keys: Sequence, sizes: Sequence[int],
                 shapes: Sequence[Tuple[int, ...]], dtype: str,
                 salt=None):
        self.positions = list(positions)     # indices into the caller's keys
        self.keys = list(keys)
        self.sizes = list(sizes)
        self.shapes = [tuple(s) for s in shapes]
        self.dtype = dtype
        self.offsets = []
        off = 0
        for n in self.sizes:
            self.offsets.append(off)
            off += n
        self.total = off
        desc = ";".join("%s:%s:%s" % (k, "x".join(map(str, s)), dtype)
                        for k, s in zip(self.keys, self.shapes))
        if salt:
            # elastic membership (ISSUE 16): the membership epoch rides
            # the CRC, so replanning after a resize is coordination-free
            # AND collision-free — every epoch's layout gets fresh wire
            # names and a pre-resize server accumulator can never be
            # misread as the new world's bucket.  salt=0/None keeps the
            # historical names (fixed-membership jobs are unchanged).
            desc += "|salt:%s" % (salt,)
        # index + member CRC: stable across steps/workers, distinct across
        # layout changes
        self.name = "__fusedb%d_%08x" % (index, zlib.crc32(desc.encode()))

    def slices(self):
        """(position, offset, size, shape) per member, in layout order."""
        return zip(self.positions, self.offsets, self.sizes, self.shapes)

    def __repr__(self):
        return "Bucket(%s, n=%d, total=%d, %s)" % (
            self.name, len(self.keys), self.total, self.dtype)


def plan_buckets(keys: Sequence, shapes: Sequence[Tuple[int, ...]],
                 dtypes: Sequence[str], itemsizes: Sequence[int],
                 stypes: Sequence[str], max_bytes: int,
                 reverse: bool = False, salt=None):
    """Greedy first-fit in key order, one dtype per bucket.

    Returns ``(buckets, solo_positions)``: positions not covered by any
    bucket (sparse, over-cap, lone-member dtypes) take the per-key path.
    Deterministic in its inputs — see the module docstring contract.

    ``reverse=True`` packs in REVERSE parameter order: backward produces
    late-layer gradients first, so reverse packing aligns bucket
    boundaries with production order — the first buckets to fill are
    exactly the first whose members all exist, letting the overlap
    scheduler (:class:`ReadinessPlanner`) launch their exchange while
    early layers are still differentiating.
    """
    solo: List[int] = []
    open_by_dtype = {}    # dtype -> (positions, nbytes)
    closed: List[List[int]] = []

    def close(dtype):
        poss, _ = open_by_dtype.pop(dtype)
        if len(poss) > 1:
            closed.append(poss)
        else:
            solo.extend(poss)

    indices = range(len(shapes) - 1, -1, -1) if reverse \
        else range(len(shapes))
    for pos in indices:
        shape, dtype, isz, stype = (shapes[pos], dtypes[pos],
                                    itemsizes[pos], stypes[pos])
        size = 1
        for d in shape:
            size *= int(d)
        nbytes = size * int(isz)
        if stype != "default" or max_bytes <= 0 or nbytes > max_bytes:
            solo.append(pos)
            continue
        poss, used = open_by_dtype.get(dtype, ([], 0))
        if poss and used + nbytes > max_bytes:
            close(dtype)
            poss, used = [], 0
        poss.append(pos)
        open_by_dtype[dtype] = (poss, used + nbytes)
    for dtype in list(open_by_dtype):
        close(dtype)

    buckets = []
    order_key = (lambda p: -p[0]) if reverse else (lambda p: p[0])
    for bi, poss in enumerate(sorted(closed, key=order_key)):
        sizes = []
        for p in poss:
            n = 1
            for d in shapes[p]:
                n *= int(d)
            sizes.append(n)
        buckets.append(Bucket(bi, poss, [keys[p] for p in poss], sizes,
                              [shapes[p] for p in poss],
                              str(dtypes[poss[0]]), salt=salt))
    return buckets, sorted(solo)


class ReadinessPlanner:
    """Overlap scheduling (ISSUE 5): close exchange *units* — fusion
    buckets or solo keys — the moment their last member gradient lands.

    The exchange layer plans units up front (reverse-parameter-order
    buckets, so the first gradients backward produces complete the first
    units), then feeds per-position readiness events in as autograd
    finalizes leaf gradients.  ``note`` returns the unit indices that
    just closed — the caller launches those exchanges immediately,
    overlapping the collective with the rest of backward.  Positions with
    several device copies close only once every copy has landed.

    A second event for an already-complete position (double backward,
    ``grad_req='add'`` re-entry) sets :attr:`stale`: the caller must
    relaunch every unit at drain time, because launched exchanges read
    values that have since changed.
    """

    def __init__(self, buckets: Sequence[Bucket], solo: Sequence[int],
                 copies: int = 1):
        self._units: List = [("bucket", b) for b in buckets] + \
            [("solo", int(p)) for p in solo]
        self._unit_of_pos: Dict[int, int] = {}
        self._remaining: List[int] = []
        for u, (kind, obj) in enumerate(self._units):
            members = obj.positions if kind == "bucket" else [obj]
            self._remaining.append(len(members))
            for p in members:
                self._unit_of_pos[int(p)] = u
        self._copies = max(1, int(copies))
        self._seen: Dict[int, Set[int]] = {}
        self._closed: List[bool] = [False] * len(self._units)
        self.stale = False

    def __len__(self):
        return len(self._units)

    def unit(self, u: int):
        """(kind, obj) — ('bucket', Bucket) or ('solo', position)."""
        return self._units[u]

    def note(self, pos: int, copy: int = 0) -> List[int]:
        """Record that `pos`'s gradient copy `copy` is final; returns the
        unit indices this event closed (usually [] or [u])."""
        u = self._unit_of_pos.get(int(pos))
        if u is None:
            return []
        seen = self._seen.setdefault(int(pos), set())
        if self._closed[u] or copy in seen:
            self.stale = True
            return []
        seen.add(copy)
        if len(seen) < self._copies:
            return []
        self._remaining[u] -= 1
        if self._remaining[u] == 0:
            self._closed[u] = True
            return [u]
        return []

    def pending(self) -> List[int]:
        """Units not yet closed (drain launches these)."""
        return [u for u, c in enumerate(self._closed) if not c]

    def all_units(self) -> List[int]:
        return list(range(len(self._units)))
