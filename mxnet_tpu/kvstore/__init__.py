"""KVStore package (reference: python/mxnet/kvstore/)."""
from .kvstore import KVStore, create
from .kvstore import KVStoreLocal, KVStoreDevice, KVStoreICI

__all__ = ["KVStore", "create", "KVStoreLocal", "KVStoreDevice", "KVStoreICI"]
