"""dist_async parameter server.

Reference: ``src/kvstore/kvstore_dist_server.h`` (`KVStoreDistServer`,
`DataHandleEx` **async** path — the server applies each worker's push the
moment it arrives, no per-key barrier) and
``python/mxnet/kvstore/kvstore_server.py`` (the python run loop a
DMLC_ROLE=server process enters).

The reference transports over ps-lite/ZeroMQ; this rebuild's sync path
rightly replaced PS with collectives (`kvstore='ici'`), but the ASYNC
semantics — stale-tolerant updates, workers progressing independently —
have no collective equivalent, so the PS role comes back for exactly this
store.  Transport is a length-prefixed pickle protocol over TCP (stdlib
socketserver; the ZMQ dependency is an implementation detail of the
reference, not part of its contract).

Wire protocol: request = (cmd, key, payload...); response = (ok, payload).
Commands: INIT (store if absent), PUSH (updater(key, grad, store) when an
optimizer is installed, else accumulate-sum), PULL, PULLQ (quantized
pull — the hierarchical exchange's cross-slice tier), SET_OPT (pickled
optimizer, the reference's set_optimizer controller message), BARRIER
(explicit only — pushes NEVER barrier), PING (heartbeat; refreshes the
sender's liveness), JOIN/LEAVE/MEMBERS (elastic membership, below), STOP.

Elastic membership (ISSUE 16): the barrier quorum is no longer the
constructor's ``num_workers`` but a live membership TABLE seeded from it.
JOIN adds the sender's rank, LEAVE removes it, and every mutation bumps a
monotonic *membership epoch* — workers salt their fusion-bucket layout
with the epoch they observed, so a resize rolls every bucket name and a
stale accumulator from the pre-resize world can never be misread.
Barrier arithmetic, liveness eviction and ``_effective_workers`` all read
the live table; a barrier that opened under one epoch re-checks the
current epoch before releasing (a JOIN/LEAVE racing a barrier can
neither deadlock the waiters nor double-release).  With
``MX_ELASTIC_EVICT_AFTER`` set, a member silent that long is evicted
from the table outright (an involuntary LEAVE) instead of merely being
discounted from one barrier.

Fault tolerance (the ps-lite resender/heartbeat role, rebuilt here):

* Requests may arrive wrapped as ``("SEQ", client_id, seq, inner)`` — the
  retrying client (kvstore.py) tags each RPC so a reconnect-and-replay
  after a dropped reply is applied **exactly once**: the server caches
  each client's last (seq, response) and answers a replayed seq from the
  cache instead of re-executing it (double-applying a PUSH would corrupt
  the optimizer trajectory).
* Liveness: every SEQ/PING carries a client id whose rank prefix feeds a
  last-seen table.  BARRIER releases when all *live* workers have
  arrived — a worker silent for ``MX_KVSTORE_STALE_TIMEOUT`` seconds is
  evicted from barrier accounting, so a wedged peer cannot hold the
  barrier forever; the overall wait is bounded by
  ``MX_KVSTORE_BARRIER_TIMEOUT``.
* Durability: with ``MX_PS_SNAPSHOT=path`` the server persists its store
  (+ installed optimizer and its slot states) to an atomically-replaced
  pickle after mutations and on STOP, and reloads it at startup — a
  server restarted on the same port resumes with no data loss, which is
  what lets the client's transparent reconnect actually succeed.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import sys
import threading
import time as _time
from typing import Dict, Optional

import numpy as _np

from .. import fault as _fault
from ..base import get_env
from .wire_codec import WireCodecError
from .wire_verbs import declare_verbs

__all__ = ["KVStoreServer", "serve_forever", "send_msg", "recv_msg"]


def send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _env_timeout(name: str, default: str = "") -> Optional[float]:
    """Positive float via base.get_env (catalog defaults apply), else
    `default`; None = no bound."""
    raw = get_env(name)
    if raw is None or raw == "":
        raw = default
    try:
        val = float(raw)
    except (TypeError, ValueError):
        return None
    return val if val > 0 else None


def recv_msg(sock: socket.socket, timeout: Optional[float] = None,
             idle_block: bool = False):
    """Receive one length-prefixed message.

    ``timeout`` bounds how long the peer may stall; None reads the
    ``MX_KVSTORE_RECV_TIMEOUT`` env knob (empty/0 = block forever).
    With ``idle_block=True`` the wait for the FIRST byte is unbounded
    (a server handler idling between requests is healthy) but a peer
    that stalls *mid-message* still trips TimeoutError instead of
    hanging the thread forever.
    """
    if timeout is None:
        timeout = _env_timeout("MX_KVSTORE_RECV_TIMEOUT")
    saved = sock.gettimeout()
    try:
        sock.settimeout(None if idle_block else timeout)
        head = b""
        while len(head) < 8:
            try:
                chunk = sock.recv(8 - len(head))
            except socket.timeout:
                raise TimeoutError(
                    "recv_msg: peer sent no %s within %.3gs"
                    % ("data" if not head else "full header", timeout))
            if not chunk:
                raise ConnectionError("peer closed")
            if not head:
                # first byte landed: message started, bound the rest
                sock.settimeout(timeout)
            head += chunk
        (n,) = struct.unpack("<Q", head)
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = sock.recv(min(1 << 20, n - len(buf)))
            except socket.timeout:
                raise TimeoutError(
                    "recv_msg: peer stalled mid-message (%d/%d bytes) "
                    "for %.3gs" % (len(buf), n, timeout))
            if not chunk:
                raise ConnectionError("peer closed mid-message")
            buf += chunk
        return pickle.loads(bytes(buf))
    finally:
        try:
            sock.settimeout(saved)
        except OSError:
            pass


def _rank_of(client_id) -> str:
    """Liveness is tracked per RANK: a restarted worker (new uuid, same
    rank) replaces its predecessor's entry instead of leaking a stale
    ghost that would permanently shrink the barrier quorum."""
    cid = str(client_id)
    return cid.split(":", 1)[0]


# The parameter-server wire surface, DECLARED (ISSUE 11): mxlint's
# wire-verb-exhaustive rule pairs every client-emitted verb with an
# entry here, checks a handler comparison exists in this file, that
# 'replayable' entries sit in the exactly-once replay set (_MUTATING)
# and 'idempotent' ones do not, and that a named codec has an
# encode_<name>/decode_<name> pair in kvstore/wire_codec.py.  Adding a
# client verb without completing this row fails lint — half-wired
# protocols cannot ship.  The replay/mutates fields are the altitude-4
# protocol contract (ISSUE 19): tools/mxlint/protocol.py diffs them
# against the handler bodies and model-checks the declared semantics
# under bounded fault schedules.
WIRE_VERBS = declare_verbs("kvstore", {
    # mutating commands replay from the SEQ cache after a lost reply
    "INIT": {"semantics": "replayable", "replay": "cached",
             "codec": None, "mutates": ("kv",)},
    "PUSH": {"semantics": "replayable", "replay": "cached",
             "codec": "wire", "mutates": ("kv", "optimizer")},
    "SET_OPT": {"semantics": "replayable", "replay": "cached",
                "codec": None, "mutates": ("optimizer",)},
    # re-executing these on a retried envelope is harmless by design
    "PULL": {"semantics": "idempotent", "replay": "bypass",
             "codec": None, "mutates": ()},
    # quantized pull (ISSUE 16): the hierarchical exchange's cross-slice
    # return leg — same read-only contract as PULL, ~4x fewer wire bytes
    "PULLQ": {"semantics": "idempotent", "replay": "bypass",
              "codec": "wire", "mutates": ()},
    # barrier release may also evict provably-departed members (an
    # involuntary LEAVE), hence membership+epoch in its effect set
    "BARRIER": {"semantics": "idempotent", "replay": "cached",
                "codec": None,
                "mutates": ("barrier", "membership", "epoch")},
    "PING": {"semantics": "idempotent", "replay": "bypass",
             "codec": None, "mutates": ()},
    # elastic membership (ISSUE 16): JOIN of a present rank and LEAVE of
    # an absent rank are designed no-ops (no epoch bump), so a retried
    # envelope re-executes harmlessly — idempotent by construction
    "JOIN": {"semantics": "idempotent", "replay": "cached",
             "codec": None, "mutates": ("membership", "epoch")},
    "LEAVE": {"semantics": "idempotent", "replay": "cached",
              "codec": None, "mutates": ("membership", "epoch")},
    "MEMBERS": {"semantics": "idempotent", "replay": "bypass",
                "codec": None, "mutates": ()},
    # read-only telemetry scrape (ISSUE 12): the fleet collector reads
    # a PS's live instrument registry over the same wire the workers
    # use — no sidecar, no extra port.  telemetry.py imports no jax, so
    # the numpy-only server process can afford it on every scrape.
    "METRICS": {"semantics": "idempotent", "replay": "bypass",
                "codec": "text", "mutates": ()},
    # rides the cache (the bypass tuple is read-only verbs), burns no
    # state: serve_forever owns the actual drain+exit
    "STOP": {"semantics": "idempotent", "replay": "cached",
             "codec": None, "mutates": ()},
}, role="server", durable=True, handler="KVStoreServer.handle")


class KVStoreServer:
    """The server-side store + optimizer (reference: KVStoreDistServer)."""

    def __init__(self, num_workers: int = 1,
                 snapshot_path: Optional[str] = None,
                 snapshot_every: Optional[int] = None):
        self._store: Dict = {}
        self._locks: Dict = {}
        self._global_lock = threading.Lock()
        self._updater = None
        self._opt_blob = None
        self._num_workers = num_workers
        # elastic membership (ISSUE 16): the LIVE quorum table, seeded
        # from the constructor's num_workers in the rank naming
        # _rank_of() produces.  Guarded by _barrier_cv (every mutation
        # notifies the cv — a quorum change is exactly what a parked
        # barrier waiter needs to re-check); _membership_epoch bumps
        # monotonically on every table change.
        self._members = set("r%d" % i for i in range(max(1, num_workers)))
        self._membership_epoch = 0
        # the epoch the in-progress barrier generation opened under —
        # _try_release_barrier re-checks it so a membership change racing
        # a barrier rebases the arrival count instead of deadlocking
        # waiters or double-releasing (satellite of ISSUE 16)
        self._barrier_open_epoch = 0
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        # under use_virtual_time(), exactly ONE parked waiter advances the
        # shared virtual clock — N waiters each charging their tick would
        # run every deadline on that clock N times too fast
        self._vclock_pumper: Optional[int] = None
        # liveness: rank -> last activity (monotonic seconds).  Written
        # by every handler thread (touch) and read/re-stamped under the
        # barrier wait; _seen_lock makes the pair atomic.  Lock order:
        # _barrier_cv is taken FIRST when both are held (the barrier
        # path touches liveness, never the reverse).
        self._seen_lock = threading.Lock()
        self._last_seen: Dict[str, float] = {}
        # which clock regime each stamp was taken under: virtual-clock
        # stamps are meaningless against real monotonic (and vice
        # versa), so a server outliving a use_virtual_time() block
        # must never compare across the switch
        self._seen_regime: Dict[str, bool] = {}
        # ranks parked inside the current barrier generation: alive by
        # definition, excluded from stale eviction
        self._barrier_waiting: Dict[str, int] = {}
        # exactly-once replay cache: client_id -> [seq, done Event, resp]
        # (mutating commands only — PULL/PING re-execute harmlessly, and
        # skipping them keeps parameter-sized replies out of the cache)
        self._replay: Dict[str, list] = {}
        self._replay_lock = threading.Lock()
        self._snapshot_path = snapshot_path if snapshot_path is not None \
            else (get_env("MX_PS_SNAPSHOT") or None)
        try:
            self._snapshot_every = int(
                snapshot_every if snapshot_every is not None else
                get_env("MX_PS_SNAPSHOT_EVERY") or 1)
        except ValueError:
            self._snapshot_every = 1
        self._mutations = 0
        self._mutation_lock = threading.Lock()
        self._snapshot_lock = threading.Lock()
        if self._snapshot_path and os.path.exists(self._snapshot_path):
            self._load_snapshot()

    def _lock_of(self, key):
        with self._global_lock:
            return self._locks.setdefault(key, threading.Lock())

    # -- liveness -----------------------------------------------------------
    def touch(self, client_id) -> None:
        if client_id is not None:
            rank = _rank_of(client_id)
            with self._seen_lock:
                self._last_seen[rank] = _fault.now()
                self._seen_regime[rank] = _fault.is_virtual()

    def _effective_workers(self) -> int:
        """Barrier quorum = live membership table minus transiently-stale
        member ranks.  Caller holds _barrier_cv (the table is guarded by
        it).  Member ranks never heard from are NOT stale (they may still
        be starting), and ranks parked INSIDE the barrier are alive by
        definition — a waiting worker's own silence (e.g. heartbeats
        disabled) must never evict it out of the barrier it is holding."""
        base = max(1, len(self._members))
        stale = _env_timeout("MX_KVSTORE_STALE_TIMEOUT")
        if stale is None:
            return base
        regime = _fault.is_virtual()
        horizon = _fault.now() - stale
        evicted = 0
        with self._seen_lock:   # atomic vs touch() in handler threads
            for r, t in list(self._last_seen.items()):
                if self._seen_regime.get(r, regime) != regime:
                    # stamped under the other clock: re-stamp as fresh
                    # now — never evict on an apples-to-oranges compare
                    self._last_seen[r] = _fault.now()
                    self._seen_regime[r] = regime
                elif t < horizon and r in self._members \
                        and r not in self._barrier_waiting:
                    evicted += 1
        return max(1, base - evicted)

    def _evict_departed(self) -> None:
        """Permanent liveness eviction (ISSUE 16): with
        ``MX_ELASTIC_EVICT_AFTER`` armed, a member rank silent that long
        is removed from the membership TABLE itself — an involuntary
        LEAVE on behalf of a worker that died without preemption notice
        — so every later barrier sizes against the shrunken world
        instead of re-discounting the ghost each time.  Caller holds
        _barrier_cv.  Unset/0 keeps today's transient-only discounting."""
        evict_after = _env_timeout("MX_ELASTIC_EVICT_AFTER")
        if evict_after is None:
            return
        regime = _fault.is_virtual()
        horizon = _fault.now() - evict_after
        gone = []
        with self._seen_lock:
            for r in list(self._members):
                t = self._last_seen.get(r)
                if t is None or r in self._barrier_waiting:
                    continue        # never heard from, or provably alive
                if self._seen_regime.get(r, regime) != regime:
                    continue        # cross-clock stamp: not comparable
                if t < horizon:
                    gone.append(r)
                    self._last_seen.pop(r, None)
                    self._seen_regime.pop(r, None)
        if gone:
            for r in gone:
                self._members.discard(r)
            self._membership_epoch += 1
            self._note_membership_change("evict", gone)

    def _note_membership_change(self, what: str, ranks) -> None:
        """Telemetry + log for one membership-table mutation (safe to
        call with _barrier_cv held — counter/gauge updates only)."""
        from .. import telemetry as _telemetry
        _telemetry.registry.counter(
            "kvstore.membership_%ss" % what,
            doc="elastic membership %s events applied to the live "
                "table" % what).inc(len(ranks) if not
                                    isinstance(ranks, str) else 1)
        _telemetry.registry.gauge(
            "kvstore.membership_epoch",
            doc="monotonic membership epoch — bumps on every JOIN/"
                "LEAVE/evict").set(self._membership_epoch)
        _telemetry.registry.gauge(
            "kvstore.members",
            doc="live membership table size").set(len(self._members))
        print("kvstore server: membership %s %s -> epoch %d, %d member(s)"
              % (what, list(ranks), self._membership_epoch,
                 len(self._members)), file=sys.stderr)

    # -- durability ---------------------------------------------------------
    def _load_snapshot(self) -> None:
        with open(self._snapshot_path, "rb") as f:
            blob = pickle.load(f)
        self._store = {k: _np.array(v, copy=True)
                       for k, v in blob["store"].items()}
        if blob.get("opt_blob") is not None:
            self._install_optimizer(blob["opt_blob"])
            states = blob.get("opt_states")
            if states is not None:
                self._updater.inner.set_states(states)
        # exactly-once across restarts: resurrect the replay cache so a
        # PUSH that was applied+snapshotted right before the crash is
        # answered from cache when the reconnecting client replays it
        for cid, (seq, resp) in blob.get("replay", {}).items():
            done = threading.Event()
            done.set()
            self._replay[cid] = [seq, done, resp]
        # a restarted server resumes the RESIZED world, not the seeded
        # one — membership survives with the store it sized
        if blob.get("members"):
            self._members = set(blob["members"])
            self._membership_epoch = int(blob.get("membership_epoch", 0))
            self._barrier_open_epoch = self._membership_epoch

    def snapshot(self) -> None:
        """Atomically persist store + optimizer (write sibling, rename).
        Serialized under _snapshot_lock: concurrent handler threads must
        not race on the temp file (the loser's os.replace would throw)."""
        path = self._snapshot_path
        if not path:
            return
        with self._barrier_cv:      # taken ALONE (before any data lock)
            members = sorted(self._members)
            membership_epoch = self._membership_epoch
        with self._snapshot_lock:
            with self._global_lock:
                locks = list(self._locks.values())
            # quiesce in-flight per-key mutations — BOUNDED: a handler
            # wedged mid-PUSH must cost us this snapshot, not wedge the
            # snapshotting thread forever (the next mutation retries);
            # real-time bound on purpose, the holders are real threads
            acquired = []
            for lk in locks:
                if lk.acquire(timeout=30.0):
                    acquired.append(lk)
                    continue
                for got in acquired:
                    got.release()
                print("kvstore server: snapshot skipped - a per-key "
                      "lock stayed held for 30s", file=sys.stderr)
                return
            try:
                with self._replay_lock:
                    replay = {cid: (ent[0], ent[2])
                              for cid, ent in self._replay.items()
                              if ent[1].is_set()}
                with self._global_lock:
                    # one fence for everything SET_OPT/INIT mutate under
                    # it: the store dict and the installed optimizer
                    items = list(self._store.items())
                    opt_blob = self._opt_blob
                    updater = self._updater
                blob = {"store": {k: _np.array(v, copy=True)
                                  for k, v in items},
                        "opt_blob": opt_blob,
                        "opt_states": (updater.inner.get_states(False)
                                       if updater is not None
                                       else None),
                        "members": members,
                        "membership_epoch": membership_epoch,
                        "replay": replay}
            finally:
                for lk in acquired:
                    lk.release()
            tmp = "%s.tmp.%d" % (path, os.getpid())
            with open(tmp, "wb") as f:
                pickle.dump(blob, f, protocol=4)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def _note_mutation(self) -> None:
        if not self._snapshot_path:
            return
        with self._mutation_lock:   # lost increments would skip the
            self._mutations += 1    # modulo boundary below
            due = self._mutations % max(1, self._snapshot_every) == 0
        if due:
            self.snapshot()

    def _install_optimizer(self, blob) -> None:
        from ..optimizer import get_updater
        optimizer = pickle.loads(blob)
        self._updater = _NumpyUpdater(get_updater(optimizer))
        self._opt_blob = blob

    # -- exactly-once replay ------------------------------------------------
    _MUTATING = ("INIT", "PUSH", "SET_OPT")

    def handle_request(self, msg, client_id=None):
        """Entry point for one wire request: unwraps SEQ envelopes and
        answers replayed sequence numbers from the cache (idempotent
        reconnect-replay), then dispatches to :meth:`handle`.

        PULL/PING bypass the cache — re-executing them is harmless, and
        skipping them keeps parameter-sized replies out of it.  The
        snapshot for a mutating command fires AFTER its cache entry
        resolves, so a persisted store state always travels with the
        cache entry that marks its push as applied (a crash between the
        two can therefore never lead to a double-apply on restart).

        Distributed tracing (ISSUE 8): a SEQ envelope may carry a fifth
        element ``(trace_id, span_id)`` stamped by the client's RPC
        span; the handling here runs under a child span with those IDs,
        so the merged chrome trace (tools/telemetry_dump.py) shows one
        causal chain per RPC — replay-cache hits become instant
        ``replay`` child events.  Envelopes without the element (older
        clients, tools) are handled identically."""
        if isinstance(msg, tuple) and msg and msg[0] == "SEQ":
            from .. import telemetry as _telemetry
            cid, seq, inner = msg[1], msg[2], msg[3]
            tctx = msg[4] if len(msg) > 4 else None
            self.touch(cid)
            cmd = inner[0] if inner else None
            with _telemetry.rpc_span(
                    "kv.server.%s" % cmd,
                    trace_id=tctx[0] if tctx else None,
                    parent_id=tctx[1] if tctx else None) as span:
                return self._handle_seq(cid, seq, inner, cmd, span)
        resp = self.handle(msg, client_id=client_id)
        if msg and msg[0] in self._MUTATING:
            self._note_mutation()
        return resp

    def _handle_seq(self, cid, seq, inner, cmd, span):
        """SEQ-enveloped dispatch under the caller's server span.
        METRICS joins the PULL/PING cache bypass: it is read-only by
        contract, and caching a whole registry exposition per scrape
        would bloat the replay cache for nothing.  PULLQ and MEMBERS
        bypass for the same read-only reason — and PULLQ replies are
        parameter-sized, exactly what the cache must stay free of."""
        if cmd in ("PULL", "PULLQ", "PING", "METRICS", "MEMBERS"):
            return self.handle(inner, client_id=cid)
        with self._replay_lock:
            ent = self._replay.get(cid)
            if ent is not None and seq == ent[0]:
                dup = ent
            elif ent is not None and seq < ent[0]:
                span.event("stale", seq=seq, server_at=ent[0])
                return False, ("stale request seq %s (server already "
                               "at %s)" % (seq, ent[0]))
            else:
                dup = None
                ent = [seq, threading.Event(), None]
                self._replay[cid] = ent
        if dup is not None:
            # the original execution may still be in flight on the
            # dead connection's thread: wait for its result rather
            # than re-executing (PUSH must apply exactly once)
            from .. import telemetry as _telemetry
            span.event("replay", seq=seq)
            _telemetry.registry.counter(
                "kvstore.server_replays",
                doc="SEQ requests answered from the exactly-once "
                    "replay cache").inc()
            timeout = (_env_timeout("MX_KVSTORE_BARRIER_TIMEOUT")
                       or 120) + 30
            if not dup[1].wait(timeout=timeout):
                return False, "replayed request %s still in flight" % seq
            return dup[2]
        try:
            resp = self.handle(inner, client_id=cid)
        except BaseException as e:
            # the entry MUST resolve even on a handler fault — a
            # forever-pending seq would starve every future replay of
            # it (the client would burn its whole retry deadline)
            ent[2] = (False, "server error handling %r: %s"
                      % (inner[0], e))
            ent[1].set()
            raise
        ent[2] = resp
        ent[1].set()
        if cmd in self._MUTATING:
            self._note_mutation()
        return resp

    # -- command handlers ---------------------------------------------------
    def handle(self, msg, client_id=None):
        cmd = msg[0]
        if cmd == "INIT":
            _, key, value = msg
            with self._lock_of(key):
                if key not in self._store:
                    arr = _np.array(value, copy=True)
                    with self._global_lock:   # fence vs snapshot iteration
                        self._store[key] = arr
            return True, None
        if cmd == "PUSH":
            _, key, grad = msg
            # numpy-only codec: the PUSH hot path must not pull in the
            # device kernel stack (jax/ops) gradient_compression carries
            from .wire_codec import decode_wire, is_wire_payload
            if is_wire_payload(grad):
                # compact wire format (payload + scales + dtype tag):
                # dequantize BEFORE the updater/accumulator sees it — the
                # optimizer contract is full-width gradients (the worker
                # already paid the quantization error via error feedback)
                grad = decode_wire(grad)
            with self._global_lock:
                # snapshot the updater OUTSIDE the per-key lock (same
                # order as INIT: per-key -> global never reverses) — a
                # concurrent SET_OPT installs under _global_lock, and an
                # updater is never uninstalled, so the local ref stays
                # valid for the whole apply
                updater = self._updater
            with self._lock_of(key):
                stored = self._store.get(key)
                if stored is None:
                    return False, "key %r not initialized" % (key,)
                if grad.shape != stored.shape and \
                        grad.size == stored.size:
                    grad = grad.reshape(stored.shape)
                if updater is not None:
                    # async contract: apply THIS worker's gradient now
                    updater(key, grad, stored)
                else:
                    # no optimizer: the server is an ACCUMULATOR — pull
                    # returns init + sum of every push (the dist num_
                    # workers-sum contract); differs from local stores,
                    # where push replaces (documented divergence)
                    stored += grad
            return True, None
        if cmd == "PULL":
            _, key = msg
            with self._lock_of(key):
                stored = self._store.get(key)
                if stored is None:
                    return False, "key %r not initialized" % (key,)
                return True, _np.array(stored, copy=True)
        if cmd == "PULLQ":
            # hierarchical exchange, cross-slice return leg (ISSUE 16):
            # the merged value goes back per-block int8-quantized — ~4x
            # fewer wire bytes than the fp32 PULL.  Stateless encode (no
            # residual on the server), so this is the opt-in tier of
            # MX_EXCHANGE_HIERARCHICAL, never the default pull.  Non-
            # float keys fall back to the full-width PULL reply.
            from .wire_codec import encode_wire, quantize_int8_np
            key = msg[1]
            block = 256
            if len(msg) > 2 and msg[2]:
                block = int(msg[2])
            with self._lock_of(key):
                stored = self._store.get(key)
                if stored is None:
                    return False, "key %r not initialized" % (key,)
                if stored.dtype.kind != "f":
                    return True, _np.array(stored, copy=True)
                q, scales = quantize_int8_np(stored.reshape(-1), block)
                return True, encode_wire("int8", stored.shape,
                                         stored.dtype, (q, scales))
        if cmd == "SET_OPT":
            _, blob = msg
            with self._global_lock:
                # check-and-install is ATOMIC: two workers shipping the
                # optimizer concurrently (startup skew) must not both
                # pass the None check and double-install — the loser
                # would wipe accumulated momentum/Adam state.  Keep the
                # FIRST installation (reference gates the controller
                # message on rank 0 for the same reason).
                if self._updater is not None:
                    return True, "already installed"
                self._install_optimizer(blob)
            return True, None
        if cmd == "PING":
            # heartbeat: payload is the sender's client_id (also reached
            # touch() via the envelope when SEQ-wrapped)
            if len(msg) > 1:
                self.touch(msg[1])
            return True, "PONG"
        if cmd == "METRICS":
            # live telemetry scrape (ISSUE 12): the reply is this server
            # process's whole instrument registry — Prometheus text by
            # default, fmt='json' for the fleet collector's merge path.
            # Read-only/idempotent; bypasses the replay cache.
            from .. import telemetry as _telemetry
            from .wire_codec import encode_text
            fmt = msg[1] if len(msg) > 1 else "prometheus"
            reg = _telemetry.registry
            text = reg.to_json(indent=1) if fmt == "json" \
                else reg.to_prometheus()
            return True, encode_text(text)
        if cmd == "JOIN":
            # elastic membership (ISSUE 16): admit the sender's rank to
            # the live quorum.  A JOIN of a rank already present is a
            # no-op (no epoch bump) — that is what makes the verb
            # idempotent under SEQ retry, and what lets every worker of
            # a FIXED job send JOIN at init unconditionally.
            _fault.fire("kvstore.membership")
            who = msg[1] if len(msg) > 1 and msg[1] is not None \
                else client_id
            rank = _rank_of(who) if who is not None else None
            changed = False
            with self._barrier_cv:
                if rank is not None and rank not in self._members:
                    self._members.add(rank)
                    self._membership_epoch += 1
                    changed = True
                    self._note_membership_change("join", [rank])
                    self._barrier_cv.notify_all()
                epoch = self._membership_epoch
                members = sorted(self._members)
            self.touch(who)
            if changed:
                self.snapshot()
            return True, (epoch, members)
        if cmd == "LEAVE":
            # voluntary departure (preemption drain, supervisor shrink):
            # drop the rank from the quorum NOW so no barrier ever waits
            # on it, and clear its liveness stamp so it cannot read as a
            # stale ghost.  LEAVE of an absent rank is a no-op.
            _fault.fire("kvstore.membership")
            who = msg[1] if len(msg) > 1 and msg[1] is not None \
                else client_id
            rank = _rank_of(who) if who is not None else None
            changed = False
            with self._barrier_cv:
                if rank is not None and rank in self._members:
                    self._members.discard(rank)
                    self._membership_epoch += 1
                    changed = True
                    with self._seen_lock:   # cv -> seen: documented order
                        self._last_seen.pop(rank, None)
                        self._seen_regime.pop(rank, None)
                    self._note_membership_change("leave", [rank])
                    # the quorum shrank: parked waiters may now release
                    self._barrier_cv.notify_all()
                epoch = self._membership_epoch
                members = sorted(self._members)
            if changed:
                self.snapshot()
            return True, (epoch, members)
        if cmd == "MEMBERS":
            with self._barrier_cv:
                return True, (self._membership_epoch,
                              sorted(self._members))
        if cmd == "BARRIER":
            return self._handle_barrier(client_id)
        if cmd == "STOP":
            # serve_forever snapshots once after the drain (fresher and
            # cheaper than snapshotting here too); standalone embedders
            # of KVStoreServer call .snapshot() themselves at shutdown
            return True, "stopping"
        return False, "unknown command %r" % (cmd,)

    def _handle_barrier(self, client_id=None):
        """Generation barrier (explicit _barrier() calls only; PUSH never
        blocks — that's the async contract).  Waits re-check the live-
        worker quorum every poll tick so a stale worker's eviction
        releases the survivors instead of stranding them.  The caller's
        rank registers in _barrier_waiting while parked, which shields
        it from its own stale eviction (it is alive, just waiting)."""
        timeout = _env_timeout("MX_KVSTORE_BARRIER_TIMEOUT") or 120.0
        stale = _env_timeout("MX_KVSTORE_STALE_TIMEOUT") or 30.0
        poll = min(0.25, max(0.02, stale / 5.0))
        rank = _rank_of(client_id) if client_id is not None else None
        with self._barrier_cv:
            gen = self._barrier_gen
            if self._barrier_count == 0:
                # first arrival OPENS this barrier generation: stamp the
                # membership epoch it sized against — release re-checks
                # the stamp (ISSUE 16 satellite) so a racing JOIN/LEAVE
                # rebases the count instead of deadlocking/double-firing
                self._barrier_open_epoch = self._membership_epoch
            self._barrier_count += 1
            if rank is not None:
                self._barrier_waiting[rank] = \
                    self._barrier_waiting.get(rank, 0) + 1
            try:
                if self._try_release_barrier():
                    return True, None
                # Deadline (not now()+timeout): a use_virtual_time()
                # context starting/ending around this park must not make
                # the budget compare across clock regimes
                deadline = _fault.Deadline(timeout)
                while self._barrier_gen == gen:
                    remaining = deadline.remaining()
                    if remaining <= 0:
                        self._barrier_count = max(0,
                                                  self._barrier_count - 1)
                        return False, ("barrier timed out after %.3gs "
                                       "waiting for %d workers (%d arrived)"
                                       % (timeout,
                                          self._effective_workers(),
                                          self._barrier_count + 1))
                    tick = min(poll, remaining)
                    if _fault.is_virtual():
                        # a real cv.wait cannot advance a virtual clock:
                        # yield briefly for arriving workers, then charge
                        # the whole tick so the deadline math progresses
                        # and a chaos test's barrier timeout fires in
                        # milliseconds of real time.  Only the elected
                        # pumper charges (cv lock is held here): every
                        # waiter charging would advance deadlines N×.
                        me = threading.get_ident()
                        if self._vclock_pumper is None:
                            self._vclock_pumper = me
                        self._barrier_cv.wait(timeout=0.001)
                        if self._vclock_pumper == me:
                            _fault.sleep(tick)
                    else:
                        self._barrier_cv.wait(timeout=tick)
                    if self._barrier_gen == gen:
                        if self._try_release_barrier():
                            break
            finally:
                if self._vclock_pumper == threading.get_ident():
                    # hand the clock-pumping duty to whichever waiter
                    # iterates next
                    self._vclock_pumper = None
                if rank is not None:
                    n = self._barrier_waiting.get(rank, 0) - 1
                    if n <= 0:
                        self._barrier_waiting.pop(rank, None)
                    else:
                        self._barrier_waiting[rank] = n
                    self.touch(client_id)     # fresh on the way out
        return True, None

    def _try_release_barrier(self) -> bool:
        """Caller holds _barrier_cv.  Release if every live worker is in.

        Membership re-check (ISSUE 16 satellite): if the membership
        epoch moved since this barrier generation opened, the arrival
        count is REBASED to the parked waiters that are still members —
        a departed rank's ghost arrival can no longer inflate the count
        into a double-release, and a JOIN that grew the quorum mid-wait
        is sized against honestly instead of deadlocking the waiters on
        an arithmetic carried over from the old world.  (Anonymous
        arrivals — client_id=None, rank untracked — are only countable
        pre-rebase; elastic callers always identify themselves.)"""
        self._evict_departed()
        if self._membership_epoch != self._barrier_open_epoch:
            self._barrier_count = sum(
                n for r, n in self._barrier_waiting.items()
                if r in self._members)
            self._barrier_open_epoch = self._membership_epoch
        if self._barrier_count >= self._effective_workers():
            self._barrier_count = 0
            self._barrier_gen += 1
            self._barrier_cv.notify_all()
            return True
        return False


class _NumpyUpdater:
    """Bridge the mx Updater (NDArray in/out) to the numpy server store —
    the server process stays off any accelerator."""

    def __init__(self, updater):
        self.inner = updater

    def __call__(self, key, grad_np, stored_np):
        from ..ndarray.ndarray import array as _arr
        g = _arr(_np.asarray(grad_np))
        w = _arr(stored_np)
        self.inner(key, g, w)
        stored_np[...] = w.asnumpy()


def serve_forever(port=None, num_workers=None, ready_file=None,
                  snapshot_path=None):
    """Run the server loop (reference: KVStoreServer.run; entered by
    DMLC_ROLE=server processes under tools/launch.py).

    STOP drains gracefully: the listener closes, in-flight requests get
    their replies, THEN the process exits — so a worker's final RPC never
    races the shutdown.
    """
    port = int(port if port is not None else get_env("MX_PS_PORT"))
    num_workers = int(num_workers if num_workers is not None else
                      os.environ.get("DMLC_NUM_WORKER", 1))
    server_state = KVStoreServer(num_workers, snapshot_path=snapshot_path)
    stop_event = threading.Event()
    inflight_count = [0]
    inflight_lock = threading.Lock()
    conns = set()                           # live client sockets, severed
    conns_lock = threading.Lock()           # after the STOP drain

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            with conns_lock:
                conns.add(self.request)
            try:
                self._serve()
            finally:
                with conns_lock:
                    conns.discard(self.request)

        def _serve(self):
            while True:
                try:
                    msg = recv_msg(self.request, idle_block=True)
                except (ConnectionError, OSError, TimeoutError):
                    return
                with inflight_lock:
                    inflight_count[0] += 1
                try:
                    _fault.fire("server.handle")
                    ok, payload = server_state.handle_request(msg)
                except SystemExit:          # injected crash: die mid-request
                    os._exit(17)
                except (_fault.FaultError, WireCodecError) as e:
                    # a malformed wire frame is the CLIENT's fault: the
                    # decoder raised before any state was touched, so
                    # answer with a typed refusal on the same connection
                    # instead of severing it with a traceback
                    ok, payload = False, str(e)
                finally:
                    with inflight_lock:
                        inflight_count[0] -= 1
                try:
                    send_msg(self.request, (ok, payload))
                except (ConnectionError, OSError):
                    return
                inner = msg[3] if isinstance(msg, tuple) and msg and \
                    msg[0] == "SEQ" else msg
                if inner and inner[0] == "STOP":
                    stop_event.set()
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server(("0.0.0.0", port), Handler) as srv:
        if ready_file:
            with open(ready_file, "w") as f:
                f.write("%d" % srv.server_address[1])
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        # unbounded BY DESIGN: idling until a worker sends STOP is the
        # server's whole lifetime — there is nothing to time out against
        # (launch.py's supervisor owns killing an abandoned server)
        stop_event.wait()   # mxlint: disable=blocking-wait-unbounded
        srv.shutdown()                      # stop accepting
        drain_deadline = _fault.Deadline(5.0)
        while not drain_deadline.expired():
            with inflight_lock:
                if inflight_count[0] == 0:
                    break
            if _fault.is_virtual():
                # in-flight handlers run in REAL threads: a pure virtual
                # tick would burn the whole drain budget in microseconds
                # without giving them a chance to finish (same treatment
                # as the barrier wait above)
                _time.sleep(0.001)  # mxlint: disable=wall-clock-in-fault-path
            _fault.sleep(0.02)
        server_state.snapshot()
        # sever surviving client connections so peers observe the stop
        # immediately (a subprocess server gets this for free at exit;
        # an in-process one must do it explicitly)
        with conns_lock:
            leftover = list(conns)
        for c in leftover:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


if __name__ == "__main__":
    serve_forever()
