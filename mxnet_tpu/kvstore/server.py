"""dist_async parameter server.

Reference: ``src/kvstore/kvstore_dist_server.h`` (`KVStoreDistServer`,
`DataHandleEx` **async** path — the server applies each worker's push the
moment it arrives, no per-key barrier) and
``python/mxnet/kvstore/kvstore_server.py`` (the python run loop a
DMLC_ROLE=server process enters).

The reference transports over ps-lite/ZeroMQ; this rebuild's sync path
rightly replaced PS with collectives (`kvstore='ici'`), but the ASYNC
semantics — stale-tolerant updates, workers progressing independently —
have no collective equivalent, so the PS role comes back for exactly this
store.  Transport is a length-prefixed pickle protocol over TCP (stdlib
socketserver; the ZMQ dependency is an implementation detail of the
reference, not part of its contract).

Wire protocol: request = (cmd, key, payload...); response = (ok, payload).
Commands: INIT (store if absent), PUSH (updater(key, grad, store) when an
optimizer is installed, else accumulate-sum), PULL, SET_OPT (pickled
optimizer, the reference's set_optimizer controller message), BARRIER
(explicit only — pushes NEVER barrier), STOP.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Dict

import numpy as _np

__all__ = ["KVStoreServer", "serve_forever", "send_msg", "recv_msg"]


def send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def recv_msg(sock: socket.socket):
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (n,) = struct.unpack("<Q", head)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return pickle.loads(bytes(buf))


class KVStoreServer:
    """The server-side store + optimizer (reference: KVStoreDistServer)."""

    def __init__(self, num_workers: int = 1):
        self._store: Dict = {}
        self._locks: Dict = {}
        self._global_lock = threading.Lock()
        self._updater = None
        self._num_workers = num_workers
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()

    def _lock_of(self, key):
        with self._global_lock:
            return self._locks.setdefault(key, threading.Lock())

    # -- command handlers ---------------------------------------------------
    def handle(self, msg):
        cmd = msg[0]
        if cmd == "INIT":
            _, key, value = msg
            with self._lock_of(key):
                if key not in self._store:
                    self._store[key] = _np.array(value, copy=True)
            return True, None
        if cmd == "PUSH":
            _, key, grad = msg
            with self._lock_of(key):
                stored = self._store.get(key)
                if stored is None:
                    return False, "key %r not initialized" % (key,)
                if self._updater is not None:
                    # async contract: apply THIS worker's gradient now
                    self._updater(key, grad, stored)
                else:
                    # no optimizer: the server is an ACCUMULATOR — pull
                    # returns init + sum of every push (the dist num_
                    # workers-sum contract); differs from local stores,
                    # where push replaces (documented divergence)
                    stored += grad
            return True, None
        if cmd == "PULL":
            _, key = msg
            with self._lock_of(key):
                stored = self._store.get(key)
                if stored is None:
                    return False, "key %r not initialized" % (key,)
                return True, _np.array(stored, copy=True)
        if cmd == "SET_OPT":
            _, blob = msg
            if self._updater is not None:
                # every worker ships the optimizer (startup skew): keep the
                # FIRST installation so accumulated momentum/Adam state is
                # never wiped mid-training (reference gates the controller
                # message on rank 0 for the same reason)
                return True, "already installed"
            from ..optimizer import get_updater
            optimizer = pickle.loads(blob)
            self._updater = _NumpyUpdater(get_updater(optimizer))
            return True, None
        if cmd == "BARRIER":
            # generation barrier (explicit _barrier() calls only; PUSH
            # never blocks — that's the async contract)
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count == self._num_workers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    ok = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen > gen, timeout=120)
                    if not ok:
                        self._barrier_count = max(0,
                                                  self._barrier_count - 1)
                        return False, ("barrier timed out waiting for %d "
                                       "workers" % self._num_workers)
            return True, None
        if cmd == "STOP":
            return True, "stopping"
        return False, "unknown command %r" % (cmd,)


class _NumpyUpdater:
    """Bridge the mx Updater (NDArray in/out) to the numpy server store —
    the server process stays off any accelerator."""

    def __init__(self, updater):
        self._updater = updater

    def __call__(self, key, grad_np, stored_np):
        from ..ndarray.ndarray import array as _arr
        g = _arr(_np.asarray(grad_np))
        w = _arr(stored_np)
        self._updater(key, g, w)
        stored_np[...] = w.asnumpy()


def serve_forever(port=None, num_workers=None, ready_file=None):
    """Run the server loop (reference: KVStoreServer.run; entered by
    DMLC_ROLE=server processes under tools/launch.py)."""
    port = int(port if port is not None else
               os.environ.get("MX_PS_PORT", 9600))
    num_workers = int(num_workers if num_workers is not None else
                      os.environ.get("DMLC_NUM_WORKER", 1))
    server_state = KVStoreServer(num_workers)
    stop_event = threading.Event()

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            while True:
                try:
                    msg = recv_msg(self.request)
                except (ConnectionError, OSError):
                    return
                ok, payload = server_state.handle(msg)
                send_msg(self.request, (ok, payload))
                if msg[0] == "STOP":
                    stop_event.set()
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server(("0.0.0.0", port), Handler) as srv:
        if ready_file:
            with open(ready_file, "w") as f:
                f.write("%d" % srv.server_address[1])
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        stop_event.wait()
        srv.shutdown()


if __name__ == "__main__":
    serve_forever()
