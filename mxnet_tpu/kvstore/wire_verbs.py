# coding: utf-8
"""Shared schema for the ``WIRE_VERBS`` manifests (ISSUE 19).

Four modules declare a wire surface — kvstore/server.py, serve/server.py,
serve/router.py and fleet.py — and until this helper each hand-rolled its
own dict shape.  :func:`declare_verbs` validates one schema for all of
them at import time and returns the plain dict the runtime always used,
so callers of ``WIRE_VERBS[verb]["semantics"]`` are unchanged.

The manifest is also a MACHINE-READABLE contract: mxlint's wire-verb
rule (altitude 2) and the wire-protocol verifier (altitude 4,
tools/mxlint/protocol.py) both parse the ``declare_verbs`` call site
with ast — the verbs dict therefore MUST stay a literal at the call
site (no comprehensions, no ``**`` merges); this module enforces the
field vocabulary so the extractor can trust what it reads.

Per-verb fields
---------------
semantics : 'replayable' | 'idempotent'
    The client-visible delivery contract: replayable verbs burn exactly
    one effect per (client_id, seq) no matter how often the envelope is
    retried; idempotent verbs may re-execute harmlessly.
replay : 'cached' | 'bypass' | 'forward' | 'local'
    How the verb crosses the SEQ exactly-once layer: 'cached' resolves
    through the replay cache, 'bypass' dispatches around it (read-only
    or designed-no-op verbs), 'forward' (router only) ships the client
    envelope verbatim upstream, 'local' (router only) is answered from
    router-local state with no replay bookkeeping.
codec : str | None
    Wire codec pair name — ``encode_<codec>/decode_<codec>`` must exist
    in kvstore/wire_codec.py (checked by the altitude-2 rule).
mutates : tuple of category names, default ()
    Which durable server/router state categories the handler is allowed
    to touch; the protocol verifier diffs this against what the handler
    body actually mutates.  Vocabulary: %s.
stream : str, optional
    Name of the server->client frame verb a streaming response uses
    (e.g. GENERATE streams STREAM frames); the frame verb must be a
    declared idempotent row of the same manifest, and the emitting
    client must offset-dedupe re-delivered frames.
handler : str, optional
    Dotted name of the handling function, for documentation; defaults
    to the protocol-level ``handler=`` argument.
"""

__all__ = ["declare_verbs", "SEMANTICS", "REPLAY_CLASSES",
           "STATE_CATEGORIES", "ROLES"]

SEMANTICS = ("replayable", "idempotent")
REPLAY_CLASSES = ("cached", "bypass", "forward", "local")
ROLES = ("server", "router", "collector")
# durable/observable state categories a handler may declare it mutates
# (infrastructure churn — liveness stamps, telemetry, lock tables,
# routing pins, snapshot counters — is deliberately NOT declarable:
# the verifier treats it as benign)
STATE_CATEGORIES = ("kv", "optimizer", "membership", "epoch", "barrier",
                    "engine", "model", "lifecycle")

_ROW_KEYS = ("semantics", "replay", "codec", "mutates", "stream", "handler")

try:
    _STR = (str, unicode)           # noqa: F821  (py2 tooling compat)
except NameError:
    _STR = (str,)

if __doc__:                         # interpolate the vocabulary once
    __doc__ = __doc__ % (", ".join(STATE_CATEGORIES),)


def _fail(protocol, verb, why):
    raise ValueError("WIRE_VERBS[%r] of protocol %r: %s"
                     % (verb, protocol, why))


def declare_verbs(protocol, verbs, role="server", durable=False,
                  handler=None):
    """Validate one wire-surface manifest and return the verbs dict.

    ``role`` says which side of the wire this manifest describes (only
    routers may use the 'forward'/'local' replay classes).  ``durable``
    marks a server that persists its store AND replay cache in a crash
    snapshot — the model checker only explores crash-restart schedules
    against durable protocols.
    """
    if not isinstance(protocol, _STR) or not protocol:
        raise ValueError("declare_verbs: protocol must be a non-empty "
                         "string, got %r" % (protocol,))
    if role not in ROLES:
        raise ValueError("declare_verbs(%r): role %r not in %r"
                         % (protocol, role, ROLES))
    if not isinstance(durable, bool):
        raise ValueError("declare_verbs(%r): durable must be a bool"
                         % (protocol,))
    if handler is not None and not isinstance(handler, _STR):
        raise ValueError("declare_verbs(%r): handler must be a string"
                         % (protocol,))
    if not isinstance(verbs, dict) or not verbs:
        raise ValueError("declare_verbs(%r): verbs must be a non-empty "
                         "dict" % (protocol,))
    out = {}
    for verb, row in verbs.items():
        if not isinstance(verb, _STR) or not verb.isupper():
            _fail(protocol, verb, "verb names are UPPERCASE strings")
        if not isinstance(row, dict):
            _fail(protocol, verb, "row must be a dict")
        unknown = sorted(set(row) - set(_ROW_KEYS))
        if unknown:
            _fail(protocol, verb, "unknown fields %r (schema: %r)"
                  % (unknown, _ROW_KEYS))
        for required in ("semantics", "replay"):
            if required not in row:
                _fail(protocol, verb, "missing required field %r"
                      % (required,))
        if "codec" not in row:
            _fail(protocol, verb, "missing required field 'codec' "
                  "(use None for tuple-native payloads)")
        if row["semantics"] not in SEMANTICS:
            _fail(protocol, verb, "semantics %r not in %r"
                  % (row["semantics"], SEMANTICS))
        replay = row["replay"]
        if replay not in REPLAY_CLASSES:
            _fail(protocol, verb, "replay %r not in %r"
                  % (replay, REPLAY_CLASSES))
        if replay in ("forward", "local") and role != "router":
            _fail(protocol, verb, "replay class %r is router-only "
                  "(role is %r)" % (replay, role))
        if row["semantics"] == "replayable" and \
                replay not in ("cached", "forward"):
            _fail(protocol, verb, "a replayable verb must resolve "
                  "through a replay cache somewhere: replay must be "
                  "'cached' (this server) or 'forward' (the replica's "
                  "cache), not %r" % (replay,))
        codec = row["codec"]
        if codec is not None and not isinstance(codec, _STR):
            _fail(protocol, verb, "codec must be a string or None")
        mutates = row.get("mutates", ())
        if not isinstance(mutates, (tuple, list)):
            _fail(protocol, verb, "mutates must be a tuple of "
                  "category names")
        bad = sorted(set(mutates) - set(STATE_CATEGORIES))
        if bad:
            _fail(protocol, verb, "unknown state categories %r "
                  "(vocabulary: %r)" % (bad, STATE_CATEGORIES))
        row_handler = row.get("handler", handler)
        if row_handler is not None and not isinstance(row_handler, _STR):
            _fail(protocol, verb, "handler must be a string")
        stream = row.get("stream")
        if stream is not None and not isinstance(stream, _STR):
            _fail(protocol, verb, "stream must name a frame verb")
        out[verb] = dict(row, mutates=tuple(mutates))
        if row_handler is not None:
            out[verb]["handler"] = row_handler
    # second pass: stream frame verbs must be declared idempotent rows
    for verb, row in out.items():
        frame = row.get("stream")
        if frame is None:
            continue
        if frame not in out:
            _fail(protocol, verb, "stream frame verb %r is not a row "
                  "of this manifest" % (frame,))
        if out[frame]["semantics"] != "idempotent":
            _fail(protocol, verb, "stream frame verb %r must be "
                  "idempotent (frames re-deliver on failover)" % (frame,))
    return out
