"""KVStore: data-parallel gradient aggregation.

Reference: include/mxnet/kvstore.h (KVStore::Create), src/kvstore/
kvstore_local.h (KVStoreLocal — CPU reduce), comm.h (CommDevice — on-device
tree reduce), kvstore_nccl.h (KVStoreNCCL), kvstore_dist.h (parameter
server), python/mxnet/kvstore/kvstore.py.

TPU-native (SURVEY.md §5.8): the NCCL/ps-lite transports are replaced by
XLA collectives.
  * ``local`` / ``device`` — single-process multi-device reduce+broadcast
    (the reference's CommCPU/CommDevice); here one jitted sum over the
    device copies, placed back per device.
  * ``ici``   — the north-star store: allreduce = `psum` over a
    `jax.sharding.Mesh` data-parallel axis; rides ICI within a slice and
    DCN across slices (XLA inserts the hierarchy).  Multi-host ranks come
    from `jax.distributed` (mxnet_tpu.parallel.init_process_group).
  * ``dist_sync``/``nccl``/``horovod`` — aliases onto the collective
    path (sync DP on dedicated TPU pods is strictly better via
    collectives; SURVEY.md §2.1 KVStore: dist row).
  * ``dist_async`` — the one PS capability with NO collective
    equivalent: a real parameter server (kvstore/server.py over TCP)
    applies every worker's push immediately, no barriers — reference
    kvstore_dist_server.h DataHandleEx async semantics.  Launch with
    ``tools/launch.py -n W -s S`` — keys hash-shard across the S
    servers (MX_PS_ROOTS).
"""
from __future__ import annotations

import pickle
import time as _real_time
from typing import Callable, Dict, List, Optional

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..device import Context, cpu
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd

__all__ = ["KVStore", "create", "KVStoreLocal", "KVStoreDevice",
           "KVStoreICI", "KVStoreDistAsync"]


def _key(k):
    # int keys stay ints: the Trainer numbers params 0..n and the Updater's
    # optimizer looks them up in int-keyed param_dict/lr_mult tables
    return k if isinstance(k, int) else str(k)


def _sum_arrays_body(arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = out + a
    return out


def _make_sum_arrays():
    # light mode: this runs per KEY per push on the eager exchange —
    # jax.jit's C++ dispatch stays; a trivial add-reduction's
    # memory_analysis is not worth a per-dispatch Python signature walk
    from ..programs import register_program
    return register_program("kvstore.sum", _sum_arrays_body,
                            mode="light", specializing=True)


_sum_arrays = _make_sum_arrays()


class KVStore:
    """Base interface (reference: python/mxnet/kvstore/kvstore.py)."""

    def __init__(self):
        self._store: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        # persisted key→bucket layouts, keyed by the ordered (key, shape,
        # dtype, stype) signature of a batched push/pull (see bucketing.py)
        self._bucket_cache: Dict = {}

    # -- identity ----------------------------------------------------------
    @property
    def type(self) -> str:
        raise NotImplementedError

    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    # -- data path ---------------------------------------------------------
    def init(self, key, value):
        """Register initial value(s) (reference: KVStore.init)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            vv = v[0] if isinstance(v, (list, tuple)) else v
            self._store[k] = vv.copy()

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        vlists = [v if isinstance(v, (list, tuple)) else [v] for v in values]
        merged = self._reduce_many(keys, vlists)
        stored_list = []
        for k in keys:
            stored = self._store.get(k)
            if stored is None:
                raise MXNetError("key %s has not been initialized" % k)
            stored_list.append(stored)
        if self._updater is not None:
            # ONE batched updater call: with an aggregate-enabled optimizer
            # the server-side update is a fused pytree dispatch, not a
            # per-key loop
            self._updater(list(keys), merged, stored_list)
        else:
            for stored, m in zip(stored_list, merged):
                stored._set_jax(m.as_in_context(stored.context)._jax)

    def _reduce_many(self, keys, vlists) -> List[NDArray]:
        """Merge each key's device copies (and, in subclasses, exchange
        across workers — where fusion buckets coalesce the wire ops)."""
        out = []
        for k, v in zip(keys, vlists):
            m = self._reduce(v, key=k)
            if isinstance(m, NDArray):
                self._note_wire_value(m)
            out.append(m)
        return out

    # -- wire accounting (tools/bandwidth.py, bench.py --exchange) ---------
    def _wire_nbytes(self, n_elems: int, itemsize: int,
                     floating: bool = True) -> int:
        """Bytes an n-element gradient payload occupies in its exchange
        representation (compressed wire format, bf16 cast, or full
        width).  On the collective path this is the payload entering the
        allreduce; on the PS path the bytes actually sent."""
        gc = getattr(self, "_gc", None)
        if gc is not None and floating:
            return gc.wire_nbytes(int(n_elems))
        if getattr(self, "_compress_bf16", False) and floating and \
                itemsize == 4:
            return 2 * int(n_elems)
        return int(n_elems) * int(itemsize)

    def _note_wire_value(self, m) -> None:
        if not isinstance(m, NDArray):
            return      # sparse payloads are nnz-keyed; not accounted here
        floating = jnp.issubdtype(m._jax.dtype, jnp.floating)
        from ..engine import engine as _engine
        _engine.count_wire_bytes(
            self._wire_nbytes(m.size, m._jax.dtype.itemsize, floating))

    # -- whole-step-compiled exchange (ISSUE 7; sharded variant ISSUE 14) --
    def build_exchange_body(self, keys, arrays, layout=None):
        """Pure-traceable single-worker exchange body for the compiled
        train step (mxnet_tpu.step.CompiledStep): what ONE worker's
        batched push+pull observes of this store's wire, expressed as a
        jax-pure function so the whole gradient exchange inlines into
        the step's single XLA program.

        ``arrays`` are per-key templates (NDArray; shape/dtype only).
        Returns a :class:`TraceableExchange` — or None when the
        transport cannot be traced (host-blocking RPCs on the PS store,
        cross-process collectives that need the SPMD mesh lane) and the
        caller must fall back to the eager pipeline.

        The base store's semantics (local/device): per-key error-feedback
        quantization when gradient compression is installed (exactly
        :meth:`_reduce`'s wire model), bf16 cast-roundtrip under the
        bf16 mode, identity otherwise.

        ``layout`` (a :class:`~mxnet_tpu.parallel.SpecLayout`) selects
        the reduce-scatter/all-gather variant: each quantized payload is
        sharding-constrained onto the layout's fsdp shards before the
        error-feedback kernel runs, so under GSPMD the gradient sum
        reaches each chip as a reduce-scatter, quantization happens
        shard-local, and the residual state stays sharded per chip
        (``residual_shardings`` tells the step how to place/donate it).
        Sharding never changes the math — the replicated body and the
        sharded body compute identical values.
        """
        if self._updater is not None or self._optimizer is not None:
            return None     # server-side optimizer: push is not a pure exchange
        keys = [_key(k) for k in keys]
        gc = getattr(self, "_gc", None)
        bf16 = getattr(self, "_compress_bf16", False)
        plan = []           # per position: (mode, payload sharding or None)
        specs = []          # (wire_key, residual shape, residual dtype)
        shardings = []      # residual placement, aligned with specs
        wire_bytes = 0
        for k, a in zip(keys, arrays):
            floating = jnp.issubdtype(jnp.dtype(str(a.dtype)), jnp.floating)
            if gc is not None and floating:
                if gc.type == "int8":
                    # the fsdp rs-grain int8 path lives on the ICI
                    # store's bucketed body; the base per-key body keeps
                    # the replicated kernel (residual replicated)
                    sh = None if layout is None else layout.replicated()
                    plan.append(("int8", sh))
                    specs.append((k, (int(a.size),), jnp.float32))
                else:
                    # 2bit is elementwise: the residual simply lives on
                    # the gradient's sheet shards; no mid-body
                    # constraints needed (the step already constrains
                    # the gradients themselves)
                    sh = None if layout is None else \
                        layout.sharding(layout.sheet_spec(tuple(a.shape)))
                    plan.append(("2bit", sh))
                    specs.append((k, tuple(a.shape),
                                  jnp.dtype(str(a.dtype))))
                shardings.append(sh)
                wire_bytes += gc.wire_nbytes(int(a.size))
                continue
            if bf16 and floating and _np.dtype(str(a.dtype)).itemsize == 4:
                plan.append(("bf16", None))
                wire_bytes += 2 * int(a.size)
            else:
                plan.append(("none", None))
                wire_bytes += int(a.size) * _np.dtype(str(a.dtype)).itemsize
        block = gc.block if gc is not None and gc.type == "int8" else 0
        threshold = gc.threshold if gc is not None else 0.0

        def body(grads, residuals):
            from ..ops import quantization as _qops
            res_it = iter(residuals)
            new_grads, new_res = [], []
            for (mode, _sh), g in zip(plan, grads):
                if mode == "int8":
                    deq, nr = _qops._roundtrip_int8_kernel(
                        g.reshape(-1), next(res_it), block)
                    new_grads.append(deq.reshape(g.shape).astype(g.dtype))
                    new_res.append(nr)
                elif mode == "2bit":
                    q, nr = _qops._quantize_2bit_kernel(
                        g, next(res_it), jnp.asarray(threshold, g.dtype))
                    new_grads.append(q)
                    new_res.append(nr)
                elif mode == "bf16":
                    new_grads.append(
                        g.astype(jnp.bfloat16).astype(g.dtype))
                else:
                    new_grads.append(g)
            return new_grads, new_res

        return TraceableExchange(specs, body, wire_bytes,
                                 residual_shardings=shardings)

    # -- overlap-scheduled exchange (ISSUE 5) ------------------------------
    def begin_exchange(self, keys, vlists):
        """Open an overlap-scheduled batched exchange: the caller feeds
        per-key readiness events (gradients finalizing during backward)
        and each fusion bucket's exchange launches the moment its last
        member lands; ``drain()`` launches stragglers and commits every
        result (store slot + pull targets).  Returns None on stores that
        cannot overlap (host-blocking RPC transports)."""
        keys = [_key(k) for k in keys]
        vlists = [v if isinstance(v, (list, tuple)) else [v]
                  for v in vlists]
        return _ExchangeSession(self, keys, vlists)

    def _exchange_unit(self, kind, obj, keys, vlists):
        """Launch one exchange unit (async dispatch; no host sync).  Base
        stores have no cross-worker wire: a unit is the per-key local
        merge."""
        if kind == "solo":
            m = self._reduce(vlists[obj], key=keys[obj])
            self._note_wire_value(m)
            return m
        out = []
        for p in obj.positions:
            m = self._reduce(vlists[p], key=keys[p])
            self._note_wire_value(m)
            out.append(m)
        return out

    def _commit_unit(self, kind, obj, result, keys, vlists):
        """Write a launched unit's result into the store slot and every
        pull target — the push+pull contract, deferred to drain time so
        gradients observed between backward and step() keep their
        un-exchanged values."""
        if kind == "solo":
            self._commit_key(keys[obj], result, vlists[obj])
            return
        for p, m in zip(obj.positions, result):
            self._commit_key(keys[p], m, vlists[p])

    def _commit_key(self, k, merged, targets):
        stored = self._store.get(k)
        if stored is None:
            raise MXNetError("key %s has not been initialized" % k)
        stored._set_jax(merged.as_in_context(stored.context)._jax)
        for t in targets:
            stored.copyto(t)

    def _bucket_plans(self, keys, arrays, reverse=False):
        """Cached stable key→bucket layout for a batched exchange.

        `arrays` supplies shapes/dtypes (NDArray or numpy).  Returns
        (buckets, solo_positions); callers gate on bucketing being
        applicable (multi-key, no attached optimizer).  The cache key
        includes the bucket capacity and packing order: changing
        ``MX_KVSTORE_BUCKET_KB`` mid-process (tests, tuning sweeps) must
        re-plan, not serve a stale layout — and ``MX_KVSTORE_BUCKET_KB=0``
        cleanly disables bucketing (everything solo, per-key path)."""
        from .bucketing import bucket_bytes, plan_buckets
        cap = bucket_bytes()
        # elastic membership (ISSUE 16): stores carrying a bucket salt
        # (the membership epoch of their incarnation) roll every bucket
        # CRC on resize — replanning stays coordination-free AND a stale
        # pre-resize server accumulator can never alias a new bucket
        salt = getattr(self, "_bucket_salt", None) or None
        sig = tuple((k, tuple(a.shape), str(a.dtype),
                     getattr(a, "stype", "default"))
                    for k, a in zip(keys, arrays))
        cache_key = (sig, cap, bool(reverse), salt)
        cached = self._bucket_cache.get(cache_key)
        if cached is None:
            cached = plan_buckets(
                keys, [s[1] for s in sig], [s[2] for s in sig],
                [_np.dtype(a.dtype).itemsize for a in arrays],
                [s[3] for s in sig], cap, reverse=reverse, salt=salt)
            self._bucket_cache[cache_key] = cached
        return cached

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            stored = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                stored.copyto(t)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull — THE data-parallel allreduce (reference:
        MXKVStorePushPullEx; SURVEY.md §3.5)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only `row_ids` rows of the stored value (reference:
        KVStore.row_sparse_pull).  One jitted gather per target; the result
        lands in `out` (RowSparseNDArray: contents swapped in; dense
        NDArray: full pull fallback) or is returned."""
        if row_ids is None:
            return self.pull(key, out, priority)
        from ..ndarray.sparse import RowSparseNDArray, _as_idx
        keys, outs = self._normalize(key, out)
        # row_ids forms: one ids array (NDArray/numpy/list of ints) shared by
        # every key, or a list of such matching the key list
        is_per_key = isinstance(row_ids, (list, tuple)) and len(row_ids) and \
            not isinstance(row_ids[0], (int, _np.integer))
        ids_per_key = list(row_ids) if is_per_key else [row_ids] * len(keys)
        results = []
        for k, o, ids in zip(keys, outs, ids_per_key):
            stored = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            per_target = ids if isinstance(ids, (list, tuple)) and len(ids) \
                and not isinstance(ids[0], (int, _np.integer)) else \
                [ids] * len(targets)
            for t, tid in zip(targets, per_target):
                tid = _as_idx(tid, stored.context)
                rows = nd.invoke("take", stored, tid, axis=0)
                if isinstance(t, RowSparseNDArray):
                    t._assign(rows, tid)
                elif isinstance(t, NDArray):
                    stored.copyto(t)  # dense target: full pull
                else:
                    results.append(RowSparseNDArray(rows, tid, stored.shape))
        return results or None

    # -- optimizer ---------------------------------------------------------
    def set_optimizer(self, optimizer):
        from ..optimizer import get_updater
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """Reference: kvstore.py set_gradient_compression →
        src/kvstore/gradient_compression.cc.

        ``{'type': '2bit', 'threshold': t}`` — the reference's exact
        scheme: per-key residual error feedback, each pushed gradient
        quantized to {-t, 0, +t} per worker before the reduce
        (gradient_compression.py).  ``{'type': 'int8', 'block': b}`` —
        per-block symmetric int8 with error feedback; the collective
        payload carries int8 codes + one f32 scale per `b` elements
        (MX_GRAD_COMPRESS_BLOCK default) and is merged scale-aware
        (dequant-sum-requant) inside the allreduce.  ``{'type': 'bf16'}``
        — TPU-extra: cast payloads to bfloat16 before the allreduce
        (half the ICI/DCN bytes).  An unknown type raises ValueError
        (matching upstream MXNet) instead of silently not compressing."""
        params = dict(compression_params or {})
        ctype = params.get("type")
        self._gc = None
        self._compress_bf16 = False
        if ctype in ("2bit", "int8"):
            from .gradient_compression import GradientCompression
            self._gc = GradientCompression(
                type=ctype,
                threshold=float(params.get("threshold", 0.5)),
                block=params.get("block"))
            return
        if ctype == "bf16":
            self._compress_bf16 = True
            return
        if ctype is not None:
            raise ValueError(
                "Unsupported gradient compression type %r (supported: "
                "'2bit', 'int8', 'bf16')" % (ctype,))

    def _maybe_compress(self, x):
        """bf16 cast applied to gradient payloads before the reduce."""
        if getattr(self, "_compress_bf16", False) and \
                jnp.issubdtype(x.dtype, jnp.floating) and \
                x.dtype != jnp.bfloat16.dtype:
            return x.astype(jnp.bfloat16), x.dtype
        return x, None

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for fused optimizer"
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for fused optimizer"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _barrier(self):
        pass

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _normalize(key, value):
        if isinstance(key, (list, tuple)):
            return [_key(k) for k in key], list(value)
        return [_key(key)], [value]

    def _reduce(self, values: List[NDArray], key=None) -> NDArray:
        merged = self._reduce_local(values)
        # error-feedback quantization of the per-process merged gradient
        # (reference: worker quantizes AFTER its local multi-GPU reduce,
        # before the wire — kvstore_dist.h PushImpl).  2bit emits ±t/0
        # levels; int8 is a per-block quantize→dequantize roundtrip (one
        # jitted dispatch) — what a single worker observes of the wire.
        gc = getattr(self, "_gc", None)
        if gc is not None and key is not None and \
                jnp.issubdtype(merged._jax.dtype, jnp.floating):
            from ..engine import engine as _engine
            _engine.count_dispatch()
            merged = NDArray(gc.quantize(key, merged._jax),
                             ctx=merged.context)
        return merged

    def _reduce_local(self, values: List[NDArray]) -> NDArray:
        if len(values) == 1:
            return values[0]
        target = values[0].context
        comp = [self._maybe_compress(v._jax) for v in values]
        orig_dtype = comp[0][1]
        vals = [(x if v.context == target else
                 jax.device_put(x, target.jax_device))
                for (x, _), v in zip(comp, values)]
        from ..engine import engine as _engine
        _engine.count_dispatch()
        out = _sum_arrays(vals)
        if orig_dtype is not None:
            out = out.astype(orig_dtype)
        return NDArray(out, ctx=target)


class TraceableExchange:
    """One store's gradient exchange as a pure function (ISSUE 7).

    ``residual_specs`` names the error-feedback residual state the body
    threads through — ``[(wire_key, shape, dtype)]`` in the exact order
    the body consumes/produces them; the compiled step reads each via
    ``GradientCompression.peek_residual`` (donated jit input) and writes
    the returned state back with ``put_residual`` after the dispatch, so
    eager and compiled steps share one residual store (checkpoint /
    mode-switch continuity).  ``wire_bytes`` is the static per-step wire
    accounting (``engine.count_wire_bytes``) the eager path would have
    recorded for the same exchange.
    """

    def __init__(self, residual_specs, body, wire_bytes: int = 0,
                 residual_shardings=None):
        self.residual_specs = list(residual_specs)
        self._body = body
        self.wire_bytes = int(wire_bytes)
        # sharded lane (ISSUE 14): each residual's NamedSharding, aligned
        # with residual_specs (None entries replicate) — the EF state
        # stays sharded per chip across dispatches
        self.residual_shardings = list(
            residual_shardings if residual_shardings is not None
            else [None] * len(self.residual_specs))

    def __call__(self, grads, residuals):
        """(new_grads, new_residuals) — pure, safe under an outer jit."""
        return self._body(grads, residuals)


class _ExchangeSession:
    """One overlap-scheduled batched gradient exchange (ISSUE 5).

    Created by :meth:`KVStore.begin_exchange` BEFORE backward runs; the
    trainer's grad-ready hooks call :meth:`notify_key` as autograd
    finalizes each leaf gradient, and the moment a fusion bucket's last
    member (across every device copy) lands, that bucket's exchange
    launches — an async XLA dispatch that overlaps with the rest of
    backward.  Buckets are planned in REVERSE parameter order
    (bucketing.plan_buckets(reverse=True)): backward produces late-layer
    gradients first, so the first buckets close (and their collectives
    fly) while early layers are still differentiating.

    Results are committed (store slot + pull targets) only at
    :meth:`drain` — called by Trainer._allreduce_grads before the
    optimizer applies — so code inspecting gradients between backward and
    step() still sees the un-exchanged values.  A notify for an
    already-launched unit (double backward, grad_req='add') marks the
    session stale and drain relaunches everything from the arrays'
    current values — overlap degrades to the serialized exchange, never
    to wrong gradients.
    """

    def __init__(self, store: "KVStore", keys, vlists):
        from .bucketing import ReadinessPlanner
        self._store = store
        self._keys = keys
        self._vlists = vlists
        buckets: List = []
        solo = range(len(keys))
        if len(keys) > 1 and store._optimizer is None and \
                all(isinstance(v[0], NDArray) for v in vlists):
            buckets, solo = store._bucket_plans(
                keys, [v[0] for v in vlists], reverse=True)
        copies = max(len(v) for v in vlists) if vlists else 1
        self._planner = ReadinessPlanner(buckets, list(solo), copies=copies)
        self._pos_of_key = {k: i for i, k in enumerate(keys)}
        self._results: Dict[int, object] = {}
        self._snaps: Dict[int, List] = {}
        self._launched: set = set()

    def notify_key(self, key, copy: int = 0) -> None:
        """Gradient for `key` (device copy `copy`) is final; launch any
        unit this closes."""
        pos = self._pos_of_key.get(_key(key))
        if pos is None:
            return
        for u in self._planner.note(pos, copy):
            self._launch(u)

    def _unit_inputs(self, u: int) -> List:
        """Snapshot of a unit's input buffers (the jax array OBJECTS, not
        bare ids — holding the refs rules out id reuse after gc).
        NDArray writes replace the underlying array object (`_set_jax`),
        so an identity mismatch at drain time means some input was
        rewritten after the unit launched (e.g. manual grad scaling
        between backward and step()) and the launched exchange read a
        stale value."""
        kind, obj = self._planner.unit(u)
        poss = obj.positions if kind == "bucket" else [obj]
        return [v._jax for p in poss for v in self._vlists[p]]

    def _wire_keys(self, u: int) -> List:
        """Wire keys a unit's exchange may quantize under: the bucket's
        CRC name (int8 bucket path) plus/or its member keys (per-key
        quantize paths)."""
        kind, obj = self._planner.unit(u)
        if kind == "solo":
            return [self._keys[obj]]
        return [obj.name] + [self._keys[p] for p in obj.positions]

    def _launch(self, u: int) -> None:
        kind, obj = self._planner.unit(u)
        gc = getattr(self._store, "_gc", None)
        if gc is not None:
            # error feedback makes a launch stateful: checkpoint the
            # residuals it will consume so a RElaunch (stale session /
            # rewritten input) first un-does the discarded payload's EF
            # step instead of double-stepping the residual
            wk = self._wire_keys(u)
            if u in self._launched:
                gc.rollback(wk)
            else:
                gc.checkpoint(wk)
        self._launched.add(u)
        self._snaps[u] = self._unit_inputs(u)
        self._results[u] = self._store._exchange_unit(
            kind, obj, self._keys, self._vlists)

    def _inputs_unchanged(self, u: int) -> bool:
        snap, cur = self._snaps[u], self._unit_inputs(u)
        return len(snap) == len(cur) and \
            all(a is b for a, b in zip(snap, cur))

    def abort(self) -> None:
        """Discard the session without committing anything: roll back the
        error-feedback residuals every launched unit consumed and drop
        the checkpoints.  Used when the exchange key set changed under an
        armed session (e.g. a param unfrozen between steps) — the caller
        falls back to a fresh serialized exchange."""
        gc = getattr(self._store, "_gc", None)
        if gc is not None:
            for u in self._launched:
                wk = self._wire_keys(u)
                gc.rollback(wk)
                gc.commit(wk)
        self._launched.clear()
        self._results.clear()
        self._snaps.clear()

    def drain(self) -> None:
        """Launch every remaining unit, then commit all results."""
        if self._planner.stale:
            # values changed under launched exchanges: redo everything
            self._results.clear()
            for u in self._planner.all_units():
                self._launch(u)
        else:
            for u in self._planner.pending():
                self._launch(u)
            for u in sorted(self._results):
                # input rewritten since launch: the exchange read a stale
                # value — relaunch from the current buffers (overlap
                # degrades to serialized, never to wrong gradients)
                if not self._inputs_unchanged(u):
                    self._launch(u)
        for u in sorted(self._results):
            kind, obj = self._planner.unit(u)
            self._store._commit_unit(kind, obj, self._results[u],
                                     self._keys, self._vlists)
        gc = getattr(self._store, "_gc", None)
        if gc is not None:
            for u in self._launched:
                gc.commit(self._wire_keys(u))
        self._launched.clear()
        self._results.clear()
        self._snaps.clear()


class KVStoreLocal(KVStore):
    """Single-process store, reduce on first device (reference:
    KVStoreLocal + CommCPU)."""

    @property
    def type(self):
        return "local"


class KVStoreDevice(KVStoreLocal):
    """Reduce on device (reference: CommDevice tree reduce; tree/ring
    topology choice belongs to XLA now)."""

    @property
    def type(self):
        return "device"


class KVStoreICI(KVStoreLocal):
    """Collective store over the TPU mesh (reference role: KVStoreNCCL +
    KVStoreDist's dist_sync contract; SURVEY.md §5.8 `kvstore='ici'`).

    Single-host: device-copies are reduced with one jitted sum (XLA emits
    ICI transfers).  Multi-host (`jax.process_count() > 1` after
    mxnet_tpu.parallel.init_process_group): every push additionally
    allreduces across processes — a jitted sum over a global 1-axis mesh,
    lowered by XLA to collectives over ICI within a slice and DCN across
    slices.  The dist_sync contract matches the reference
    (src/kvstore/kvstore_dist.h KVStoreDist::PushPullImpl): a pull after N
    workers push returns the N-worker SUM.
    """

    def __init__(self):
        super().__init__()
        self._rank = 0
        self._size = 1
        try:
            import jax.distributed  # noqa: F401
            self._rank = jax.process_index()
            self._size = jax.process_count()
        except Exception:
            pass
        self._mesh = None
        self._home_dev = None
        self._xsum_cache: Dict = {}

    @property
    def type(self):
        return "ici"

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def init(self, key, value):
        """Multi-process init carries the reference's dist contract: the
        stored value is RANK 0's (kvstore_dist.h: only one worker's init
        reaches the server), so a subsequent broadcast/pull hands every
        worker identical weights regardless of local RNG state."""
        super().init(key, value)
        if self._size > 1:
            keys, _ = self._normalize(key, value)
            for k in keys:
                stored = self._store[k]
                payload = stored._jax if self._rank == 0 else \
                    jnp.zeros_like(stored._jax)
                agreed = self._cross_process_sum(payload)
                stored._set_jax(jax.device_put(agreed.addressable_data(0),
                                               stored.context.jax_device))

    # -- cross-process allreduce -------------------------------------------
    def _ensure_mesh(self):
        """1-axis mesh with ONE device per process: the locally merged
        value is already a single array, so a per-process representative
        device is all the collective needs (a Mesh may legally span a
        subset of devices; every process contributes its device 0)."""
        if self._mesh is None:
            import numpy as np
            from jax.sharding import Mesh
            firsts = {}
            for d in sorted(jax.devices(), key=lambda d: d.id):
                firsts.setdefault(d.process_index, d)
            devs = [firsts[p] for p in sorted(firsts)]
            self._home_dev = firsts[self._rank]
            self._mesh = Mesh(np.array(devs), ("dp",))
        return self._mesh

    def _cross_process_sum(self, x):
        """Cross-process allreduce: stack each process's payload as one
        shard of a (num_workers, ...) global array, jitted sum over the
        mesh axis, result replicated — XLA lowers this to a collective
        over ICI/DCN.  Exact for integer dtypes (no padding, no scaling)."""
        mesh = self._ensure_mesh()
        from jax.sharding import NamedSharding, PartitionSpec as P
        key = (x.shape, str(x.dtype))
        fn = self._xsum_cache.get(key)
        if fn is None:
            # light census: explicit shardings make AOT lowering here
            # depend on the global mesh layout; plain jit dispatch
            # keeps the collective path untouched while the registry
            # still counts its (re)traces and compile time
            from ..programs import register_program
            fn = register_program(
                "kvstore.cross_sum", lambda y: jnp.sum(y, axis=0),
                mode="light",
                in_shardings=NamedSharding(mesh, P("dp")),
                out_shardings=NamedSharding(mesh, P()))
            self._xsum_cache[key] = fn
        shard = jax.device_put(x[None], self._home_dev)
        stacked = jax.make_array_from_single_device_arrays(
            (self._size,) + tuple(x.shape),
            NamedSharding(mesh, P("dp")), [shard])
        from ..engine import engine as _engine
        _engine.count_dispatch()
        return fn(stacked)

    def _cross_reduce_one(self, merged: NDArray) -> NDArray:
        """Cross-process allreduce of ONE locally merged value."""
        payload, orig_dtype = self._maybe_compress(merged._jax)
        from ..engine import engine as _engine
        _engine.count_wire_bytes(payload.size * payload.dtype.itemsize)
        out = self._cross_process_sum(payload)
        if orig_dtype is not None:
            out = out.astype(orig_dtype)
        # out is replicated over the global mesh; its local shard IS the
        # full value — re-home it on the store's device
        out = jax.device_put(out.addressable_data(0),
                             merged.context.jax_device)
        return NDArray(out, ctx=merged.context)

    def _wire_nbytes(self, n_elems: int, itemsize: int,
                     floating: bool = True) -> int:
        gc = getattr(self, "_gc", None)
        if gc is not None and gc.type == "2bit" and floating:
            # the collective ships 2bit LEVELS full-width (±t/0 must sum
            # exactly inside the allreduce) — only the PS TCP wire ships
            # the packed n/4-byte format, so report honest bytes here
            return int(n_elems) * int(itemsize)
        return super()._wire_nbytes(n_elems, itemsize, floating)

    # -- quantized collective (ISSUE 5: EQuARX-style int8 allreduce) -------
    def _int8_active(self, x=None) -> bool:
        gc = getattr(self, "_gc", None)
        return gc is not None and gc.type == "int8" and \
            (x is None or jnp.issubdtype(x.dtype, jnp.floating))

    def _cross_sum_quantized(self, q, scales):
        """AllReduce of the COMPACT payload: every process contributes its
        (int8 codes, per-block scales); inside the jitted collective each
        worker's shard is dequantized at its own scales, summed, and the
        sum requantized at a fresh merged scale — so both directions of
        the exchange stay int8-narrow on the wire (EQuARX's
        dequant-sum-requant).  Returns the replicated (q_sum, scales_sum)
        local shards."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ops import quantization as _qops
        mesh = self._ensure_mesh()
        key = ("q8sum", q.shape, scales.shape)
        fn = self._xsum_cache.get(key)
        if fn is None:
            from ..programs import register_program
            fn = register_program(
                "kvstore.q8_cross_sum",
                _qops._dequant_sum_requant_kernel, mode="light",
                in_shardings=(NamedSharding(mesh, P("dp")),
                              NamedSharding(mesh, P("dp"))),
                out_shardings=(NamedSharding(mesh, P()),
                               NamedSharding(mesh, P())))
            self._xsum_cache[key] = fn
        def _stack(x):
            shard = jax.device_put(x[None], self._home_dev)
            return jax.make_array_from_single_device_arrays(
                (self._size,) + tuple(x.shape),
                NamedSharding(mesh, P("dp")), [shard])
        from ..engine import engine as _engine
        _engine.count_dispatch()
        qo, so = fn(_stack(q), _stack(scales))
        return qo.addressable_data(0), so.addressable_data(0)

    def _exchange_flat(self, wire_key, flat: NDArray) -> NDArray:
        """Full int8-compressed exchange of one FLAT float payload:
        quantize (error feedback, residual keyed by `wire_key`) →
        compact allreduce → dequantize once."""
        from ..engine import engine as _engine
        gc = self._gc
        x = flat._jax
        _engine.count_wire_bytes(gc.wire_nbytes(x.size))
        if self._size <= 1:
            _engine.count_dispatch()
            out = gc.quantize(wire_key, x)     # fused roundtrip
        else:
            _engine.count_dispatch()
            q, scales = gc.compress_device(wire_key, x)
            qo, so = self._cross_sum_quantized(q, scales)
            _engine.count_dispatch()
            out = gc.decompress_device((qo, so), x.size).astype(x.dtype)
        out = jax.device_put(out, flat.context.jax_device)
        return NDArray(out, ctx=flat.context)

    def _reduce(self, values: List[NDArray], key=None) -> NDArray:
        if key is not None and isinstance(values[0], NDArray) and \
                self._int8_active(values[0]._jax):
            merged = self._reduce_local(values)
            flat = NDArray(merged._jax.reshape(-1), ctx=merged.context)
            out = self._exchange_flat(key, flat)
            return NDArray(out._jax.reshape(merged.shape),
                           ctx=merged.context)
        merged = super()._reduce(values, key=key)
        if self._size > 1:
            merged = self._cross_reduce_one(merged)
        return merged

    def _exchange_bucket(self, b, members: List[NDArray]) -> NDArray:
        """One fusion bucket's exchange: concat the locally merged member
        payloads, cross the wire (int8-quantized under the bucket's name,
        plain collective, or local passthrough), return the flat result.
        Shared by the serialized batched exchange (:meth:`_reduce_many`)
        and the overlap session (:meth:`_exchange_unit`)."""
        flat = jnp.concatenate([m._jax.reshape(-1) for m in members])
        from ..engine import engine as _engine
        _engine.count_dispatch()   # the concat launch
        ctx = members[0].context
        if self._int8_active(flat):
            return self._exchange_flat(b.name, NDArray(flat, ctx=ctx))
        if self._size > 1:
            return self._cross_reduce_one(NDArray(flat, ctx=ctx))
        out = NDArray(flat, ctx=ctx)   # local / non-float: no wire to cross
        self._note_wire_value(out)
        return out

    def _reduce_many(self, keys, vlists) -> List[NDArray]:
        """Batched exchange: local per-key reduce (+ optional error-
        feedback quantize), then the cross-process allreduce coalesced
        into fusion buckets — O(#buckets) collectives per step instead of
        O(#keys).  With int8 compression each bucket's payload is
        quantized per-bucket (residual keyed by the bucket name) and
        allreduced compact."""
        int8 = self._int8_active()
        if int8:
            # local merge only: quantization happens per exchange payload
            # (bucket or solo), not per key
            merged = [self._reduce_local(v) for v in vlists]
        else:
            merged = [KVStore._reduce(self, v, key=k)
                      for k, v in zip(keys, vlists)]
            if self._size <= 1:
                for m in merged:
                    self._note_wire_value(m)
                return merged
        buckets = []
        solo = range(len(keys))
        if len(keys) > 1 and self._optimizer is None:
            eligible = all(isinstance(m, NDArray) for m in merged)
            if eligible:
                buckets, solo = self._bucket_plans(keys, merged)
        for b in buckets:
            out = self._exchange_bucket(b, [merged[p] for p in b.positions])
            for p, off, size, shape in b.slices():
                piece = out._jax[off:off + size].reshape(shape)
                merged[p] = NDArray(piece, ctx=merged[p].context)
        for p in solo:
            if int8 and isinstance(merged[p], NDArray) and \
                    jnp.issubdtype(merged[p]._jax.dtype, jnp.floating):
                # _reduce's int8 path: flatten → _exchange_flat → reshape
                merged[p] = self._reduce([merged[p]], key=keys[p])
            elif self._size > 1 and isinstance(merged[p], NDArray):
                merged[p] = self._cross_reduce_one(merged[p])
            else:
                self._note_wire_value(merged[p])
        return merged

    def _exchange_unit(self, kind, obj, keys, vlists):
        """Overlap-session unit launch: the bucket path concatenates,
        exchanges (quantized when int8 compression is on), and returns
        the split pieces; solo keys ride the per-key exchange."""
        if kind == "solo":
            m = self._reduce(vlists[obj], key=keys[obj])
            if self._size <= 1 and not (isinstance(m, NDArray) and
                                        self._int8_active(m._jax)):
                self._note_wire_value(m)
            return m
        merged = [self._reduce_local(vlists[p]) if self._int8_active()
                  else KVStore._reduce(self, vlists[p], key=keys[p])
                  for p in obj.positions]
        out = self._exchange_bucket(obj, merged)
        pieces = []
        for (_p, off, size, shape), m in zip(obj.slices(), merged):
            pieces.append(NDArray(out._jax[off:off + size].reshape(shape),
                                  ctx=m.context))
        return pieces

    def build_exchange_body(self, keys, arrays, layout=None):
        """ICI's traceable body mirrors :meth:`_reduce_many`'s
        single-process semantics: int8 compression quantizes per FUSION
        BUCKET (concat → error-feedback roundtrip keyed by the bucket's
        CRC name → split), solo/2bit/bf16 keys ride the per-key base
        body.  Multi-process exchange needs the SPMD mesh lane
        (parallel.TrainStep) — the compiled Gluon step falls back to the
        eager pipeline there.

        With ``layout`` (ISSUE 14) this is the **reduce-scatter /
        all-gather** variant next to the existing allreduce: each flat
        bucket payload is sharding-constrained over the layout's fsdp
        axis before the error-feedback roundtrip, so GSPMD delivers the
        gradient sum to each chip as a reduce-scatter of the int8
        (codes, scales) grain, quantization and the residual update run
        shard-local, and the dequantized pieces all-gather back into
        each consumer's layout only where the optimizer apply needs
        them.  Residuals stay sharded per chip (``residual_shardings``).
        """
        if self._size > 1:
            return None
        gc = getattr(self, "_gc", None)
        if gc is None or gc.type != "int8" or \
                self._updater is not None or self._optimizer is not None:
            return super().build_exchange_body(keys, arrays, layout=layout)
        keys = [_key(k) for k in keys]
        buckets: List = []
        solo = range(len(keys))
        if len(keys) > 1:
            eligible = all(isinstance(a, NDArray) for a in arrays)
            if eligible:
                buckets, solo = self._bucket_plans(keys, arrays)
        solo = list(solo)
        block = gc.block
        from ..ops.quantization import rs_block_bytes
        # the reduce-scatter grain (fsdp>1): every flat payload pads to
        # whole blocks per shard so shard-local quantization IS logical
        # blockwise quantization; residuals live at the PADDED length,
        # fsdp-sharded (a lane switch rolls them — the shape mismatch
        # hands back fresh zeros, same as a bucket-layout change)
        fsdp = 0 if layout is None else int(layout.fsdp)
        use_rs = fsdp > 1

        def _payload(n):
            """(residual length, residual sharding) of one n-elem flat
            int8 payload under the active layout."""
            if not use_rs:
                return int(n), (None if layout is None
                                else layout.replicated())
            npad = rs_block_bytes(int(n), block, fsdp)
            from jax.sharding import PartitionSpec as _P
            return npad, layout.sharding(_P(layout.fsdp_axis))

        specs = []
        shardings = []
        wire_bytes = 0
        solo_modes = []
        bucket_pads = []
        for b in buckets:
            npad, sh = _payload(b.total)
            specs.append((b.name, (npad,), jnp.float32))
            shardings.append(sh)
            bucket_pads.append(npad)
            wire_bytes += gc.wire_nbytes(int(b.total))
        solo_pads = []
        for p in solo:
            a = arrays[p]
            floating = jnp.issubdtype(jnp.dtype(str(a.dtype)), jnp.floating)
            if floating:
                npad, sh = _payload(a.size)
                specs.append((keys[p], (npad,), jnp.float32))
                shardings.append(sh)
                solo_pads.append(npad)
                wire_bytes += gc.wire_nbytes(int(a.size))
                solo_modes.append("int8")
            else:
                wire_bytes += int(a.size) * _np.dtype(str(a.dtype)).itemsize
                solo_modes.append("none")
                solo_pads.append(0)

        def _quantize_flat(flat, res, npad):
            from jax import lax as _lax
            from ..ops import quantization as _qops
            if not use_rs:
                return _qops._roundtrip_int8_kernel(flat, res, block)
            n = flat.shape[0]
            if npad > n:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((npad - n,), flat.dtype)])
            # XLA:CPU SPMD miscompiles a `concatenate` whose consumer is
            # sharded: the operands get partitioned over the OTHER mesh
            # axes and the pieces psum'd, so values arrive multiplied by
            # the data-axis size.  Pinning the concat result replicated
            # before the manual shard_map kernel sidesteps it — the
            # kernel itself then reshards to the fsdp grain (the
            # reduce-scatter) from a known-good replicated value.
            flat = _lax.with_sharding_constraint(flat, layout.replicated())
            deq, nr = _qops.rs_roundtrip_int8(flat, res, block,
                                              layout.mesh,
                                              layout.fsdp_axis)
            return deq[:n], nr

        def body(grads, residuals):
            res_it = iter(residuals)
            new_grads = list(grads)
            new_res = []
            for b, npad in zip(buckets, bucket_pads):
                flat = jnp.concatenate(
                    [grads[p].reshape(-1) for p in b.positions])
                deq, nr = _quantize_flat(flat, next(res_it), npad)
                new_res.append(nr)
                for p, off, size, shape in b.slices():
                    new_grads[p] = deq[off:off + size].reshape(shape).astype(
                        grads[p].dtype)
            for p, mode, npad in zip(solo, solo_modes, solo_pads):
                if mode == "int8":
                    g = grads[p].reshape(-1)
                    deq, nr = _quantize_flat(g, next(res_it), npad)
                    new_grads[p] = deq.reshape(
                        grads[p].shape).astype(grads[p].dtype)
                    new_res.append(nr)
            return new_grads, new_res

        return TraceableExchange(specs, body, wire_bytes,
                                 residual_shardings=shardings)

    def _barrier(self):
        if self._size > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mx_kvstore_barrier")


def _ps_addr():
    """Parameter-server address from the launcher env, or None."""
    import os
    from ..base import get_env
    addr = get_env("MX_PS_ROOT") or \
        os.environ.get("DMLC_PS_ROOT_URI")
    if not addr:
        return None
    if ":" not in addr:
        addr = "%s:%s" % (addr, os.environ.get("DMLC_PS_ROOT_PORT", "9600"))
    return addr


def _ps_addrs():
    """ALL server addresses (MX_PS_ROOTS, comma-separated) — keys shard
    across them by hash (reference: kvstore_dist.h key->server
    assignment + MXNET_KVSTORE_BIGARRAY_BOUND sharding role)."""
    from ..base import get_env
    roots = get_env("MX_PS_ROOTS")
    if roots:
        return [a.strip() for a in roots.split(",") if a.strip()]
    one = _ps_addr()
    return [one] if one else []


class KVStoreDistAsync(KVStore):
    """Async parameter-server store (reference: KVStoreDist with
    dist_async — src/kvstore/kvstore_dist_server.h DataHandleEx async
    path): each worker's push is applied by the server THE MOMENT it
    arrives (server-side optimizer), pulls return whatever is current,
    and workers never wait for each other.  Server addresses from
    MX_PS_ROOTS (tools/launch.py -s N; keys hash-shard across servers)
    or MX_PS_ROOT (single server).

    Fault tolerance (ps-lite resender role, rebuilt over
    mxnet_tpu.fault): every RPC is SEQ-tagged and retried under a
    :class:`~mxnet_tpu.fault.RetryPolicy` — a dropped connection or a
    server restart triggers transparent reconnect and an idempotent
    replay of the in-flight request (the server's replay cache
    guarantees exactly-once application), with a loud terminal
    MXNetError only after ``MX_KVSTORE_RETRY_DEADLINE`` seconds.  A
    background heartbeat thread PINGs each server every
    ``MX_KVSTORE_HEARTBEAT`` seconds on its own connections so a
    compute-bound worker is never evicted as stale."""

    def __init__(self):
        super().__init__()
        import os
        import threading
        import uuid
        from . import server as _srv
        from .. import fault as _fault
        self._srv_mod = _srv
        self._fault = _fault
        addrs = _ps_addrs()
        if not addrs:
            raise MXNetError(
                "kvstore 'dist_async' needs a parameter server: launch "
                "with tools/launch.py -n <workers> -s <servers> "
                "(MX_PS_ROOTS/MX_PS_ROOT unset)")
        self._addrs = list(addrs)
        from ..base import get_env
        self._rank = int(get_env("MX_PROCESS_ID") or
                         os.environ.get("DMLC_WORKER_ID", 0))
        self._size = int(get_env("MX_NUM_PROCESSES") or
                         os.environ.get("DMLC_NUM_WORKER", 1))
        # liveness is per RANK server-side; the uuid distinguishes a
        # restarted worker's replay cache from its predecessor's
        self._client_id = "r%d:%s" % (self._rank, uuid.uuid4().hex[:12])
        import socket
        self._socks = []
        # connect-retry budget rides the injectable clock (fault.now/
        # fault.sleep) and the documented retry knob, so chaos tests
        # fast-forward it under use_virtual_time() instead of burning a
        # real minute per dead server
        connect_deadline = get_env("MX_KVSTORE_RETRY_DEADLINE", dtype=float)
        for addr in self._addrs:
            host, port = addr.rsplit(":", 1)
            deadline = _fault.Deadline(connect_deadline or 60.0)
            while True:  # the launcher starts servers concurrently:
                try:     # retry until each binds (ps-lite scheduler role)
                    self._socks.append(socket.create_connection(
                        (host, int(port)), timeout=120))
                    break
                except (ConnectionRefusedError, OSError):
                    if deadline.expired():
                        raise
                    if _fault.is_virtual():
                        # the server binds in REAL time: a pure virtual
                        # tick would burn the whole budget in microseconds
                        # before it ever gets a chance — yield briefly,
                        # then charge the tick so a truly dead server
                        # still fails fast in virtual seconds
                        _real_time.sleep(0.005)  # mxlint: disable=wall-clock-in-fault-path
                    _fault.sleep(0.2)
        self._lock = threading.Lock()
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._bucket_inited: set = set()
        # elastic membership (ISSUE 16): under MX_ELASTIC the worker
        # announces itself with JOIN at init (a no-op for ranks the
        # server already seeded) and the launcher hands every worker of
        # one incarnation the SAME membership epoch via MX_ELASTIC_EPOCH
        # — the bucket salt must be agreed BEFORE the first plan, not
        # observed racily while a join storm is still in flight.
        self._elastic = bool(get_env("MX_ELASTIC", 0, int))
        self._membership_epoch = get_env("MX_ELASTIC_EPOCH", 0, int) or 0
        self._bucket_salt = self._membership_epoch or None
        # hierarchical exchange (ISSUE 16): the cross-slice return leg
        # pulls int8 (PULLQ) instead of fp32 — opt-in, gradient/
        # accumulate mode only (a server-side optimizer needs exact
        # full-width weights back)
        self._hier = bool(get_env("MX_EXCHANGE_HIERARCHICAL", 0, int))
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._start_heartbeat()
        if self._elastic:
            self.join()

    # -- resilience plumbing ------------------------------------------------
    def _retry_policy(self):
        from ..fault import RetryPolicy
        return RetryPolicy.from_env()

    def _next_seq(self):
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def _recv_timeout(self, cmd="PULL"):
        """Per-request reply deadline.  BARRIER legitimately blocks up to
        the server's barrier timeout, so its reply window must exceed it
        (a shorter window would replay the barrier and double-count this
        worker)."""
        from ..base import get_env
        if cmd == "BARRIER":
            t = get_env("MX_KVSTORE_BARRIER_TIMEOUT", 120.0, float)
            return (t if t and t > 0 else 120.0) + 30.0
        t = get_env("MX_KVSTORE_RECV_TIMEOUT", 0.0, float)
        return t if t and t > 0 else 30.0

    def _kill_sock(self, idx):
        sock = self._socks[idx]
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._socks[idx] = None

    def _ensure_sock(self, idx):
        """Reconnect a dead connection (server restart recovery path)."""
        import socket
        sock = self._socks[idx]
        if sock is not None:
            return sock
        host, port = self._addrs[idx].rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=5)
        # the 5s bound is for CONNECT only — leave sends as generous as
        # the original __init__ connections (a big sendall over a slow
        # link must not be capped at the connect timeout)
        sock.settimeout(120)
        self._socks[idx] = sock
        return sock

    def _start_heartbeat(self):
        import socket as _socket
        import threading
        from ..base import get_env
        interval = get_env("MX_KVSTORE_HEARTBEAT", dtype=float)
        if not interval or interval <= 0:
            return

        def run():
            # dedicated connections: a heartbeat must not contend with a
            # long-blocking data RPC (e.g. a worker waiting in BARRIER)
            socks = [None] * len(self._addrs)
            while not self._hb_stop.wait(interval):
                for i, addr in enumerate(self._addrs):
                    try:
                        if socks[i] is None:
                            host, port = addr.rsplit(":", 1)
                            socks[i] = _socket.create_connection(
                                (host, int(port)), timeout=2)
                        self._srv_mod.send_msg(
                            socks[i], ("PING", self._client_id))
                        self._srv_mod.recv_msg(socks[i], timeout=2)
                    except (ConnectionError, OSError, TimeoutError):
                        if socks[i] is not None:
                            try:
                                socks[i].close()
                            except OSError:
                                pass
                        socks[i] = None    # reconnect next beat
            for s in socks:
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass

        self._hb_thread = threading.Thread(target=run, daemon=True,
                                           name="mx-kvstore-heartbeat")
        self._hb_thread.start()

    @property
    def type(self):
        return "dist_async"

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def _server_of(self, key) -> int:
        """key -> server index (stable hash; reference key->server
        assignment)."""
        import zlib
        return zlib.crc32(str(key).encode()) % len(self._socks)

    # -- big-array sharding (reference: MXNET_KVSTORE_BIGARRAY_BOUND in
    # kvstore_dist.h — tensors over the bound split EVENLY across ALL
    # servers instead of hashing whole to one) -----------------------------
    @property
    def _bigarray_bound(self):
        from ..base import get_env
        return get_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1_000_000, int)

    def _shard_plan(self, size):
        """[(server, start, stop)] flat slices, or None for whole-key
        routing.  Deterministic in (size, n_servers, bound) so every
        worker computes the same plan with no coordination."""
        n_srv = len(self._socks)
        if n_srv <= 1 or size < self._bigarray_bound:
            return None
        bounds = [size * i // n_srv for i in range(n_srv + 1)]
        return [(i, bounds[i], bounds[i + 1]) for i in range(n_srv)
                if bounds[i + 1] > bounds[i]]

    @staticmethod
    def _part_key(key, i):
        return "%s::part%d" % (key, i)

    def _send_np(self, cmd, k, arr_np):
        """INIT/PUSH routing: whole key by hash, or sliced across all
        servers when over the big-array bound."""
        plan = self._shard_plan(arr_np.size)
        if plan is None:
            self._rpc(cmd, k, arr_np)
            return
        flat = arr_np.ravel()
        for i, s, e in plan:
            self._rpc_on(i, cmd, self._part_key(k, i), flat[s:e])

    @staticmethod
    def _count_pull_bytes(n) -> None:
        """Pull-leg wire accounting — a counter of its own so the push-
        leg ``engine.wire_bytes`` the existing benches pin is untouched;
        tools/bandwidth.py --hierarchical reads both to compare the flat
        and two-tier exchanges end to end."""
        from .. import telemetry as _telemetry
        _telemetry.registry.counter(
            "kvstore.pull_wire_bytes",
            doc="bytes received on the pull leg of the dist_async "
                "exchange (PULLQ compact tuples or full-width "
                "arrays)").inc(int(n))

    def _pull_hier(self, k):
        """Hierarchical cross-slice return leg (ISSUE 16): PULLQ ships
        the merged value per-block int8 — ~4x fewer wire bytes than the
        fp32 PULL.  The pull leg's quantization error is bounded by the
        per-block absmax scale and is NOT error-fed-back (the server
        encode is stateless), which is why this tier is opt-in
        (MX_EXCHANGE_HIERARCHICAL) for the gradient/accumulate exchange
        rather than the default pull."""
        from . import wire_codec as _wc
        gc = self._wire_gc()
        block = gc.block if gc is not None and \
            getattr(gc, "type", None) == "int8" else 256
        payload = self._rpc("PULLQ", k, int(block))
        if _wc.is_wire_payload(payload):
            scales = _np.asarray(payload[6])
            self._count_pull_bytes(len(payload[5]) + scales.nbytes)
            return _wc.decode_wire(payload)
        arr = _np.asarray(payload)          # non-float key: full width
        self._count_pull_bytes(arr.nbytes)
        return arr

    # -- as-ready hierarchical bucket exchange (ISSUE 16) -------------------
    def _hier_pool_get(self):
        """Lazy bounded thread pool for the as-ready bucket pulls; pool
        threads keep their own sockets (a dedicated connection per
        (thread, server), heartbeat-style) so concurrent bucket RPCs
        never contend on the main _lock-serialized connections."""
        if getattr(self, "_hier_pool", None) is None:
            import concurrent.futures as _fut
            import threading as _threading
            from ..base import get_env
            n = max(1, get_env("MX_EXCHANGE_PARALLEL", 4, int) or 4)
            self._hier_pool = _fut.ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="mx-kv-exchange")
            self._hier_tls = _threading.local()
        return self._hier_pool

    def _rpc_dedicated(self, idx, msg):
        """One SEQ-enveloped RPC on this pool thread's OWN connection to
        server ``idx``, retried under the same RetryPolicy as the main
        path.  The envelope's client id carries a per-thread suffix —
        the rank prefix (liveness) is preserved, but each thread gets
        its own replay slot, so concurrent in-flight sequence numbers
        can never clobber one another's exactly-once entry."""
        import socket as _socket
        import threading as _threading
        tls = self._hier_tls
        if not hasattr(tls, "socks"):
            tls.socks = {}
        cid = "%s#x%d" % (self._client_id, _threading.get_ident())
        seq = self._next_seq()
        wrapped = ("SEQ", cid, seq, msg)
        timeout = self._recv_timeout(msg[0])
        policy = self._retry_policy()
        for _attempt in policy:
            sock = tls.socks.get(idx)
            try:
                if sock is None:
                    host, port = self._addrs[idx].rsplit(":", 1)
                    sock = _socket.create_connection(
                        (host, int(port)), timeout=5)
                    sock.settimeout(120)
                    tls.socks[idx] = sock
                self._srv_mod.send_msg(sock, wrapped)
                ok, payload = self._srv_mod.recv_msg(sock, timeout=timeout)
            except (ConnectionError, OSError, TimeoutError) as e:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                tls.socks[idx] = None
                policy.note(e)
                continue
            if not ok:
                raise MXNetError("dist_async server %d: %s"
                                 % (idx, payload))
            return payload
        raise MXNetError(
            "dist_async server %d (%s) unreachable: %r retried for %.3gs "
            "(MX_KVSTORE_RETRY_DEADLINE exceeded); last error: %s"
            % (idx, self._addrs[idx], msg[0], policy.deadline,
               policy.last_error))

    def _hier_bucket_pull(self, name):
        """One bucket's cross-slice return leg on a pool thread: PULLQ
        (int8, ~4x fewer wire bytes), decoded host-side."""
        from . import wire_codec as _wc
        gc = self._wire_gc()
        block = gc.block if gc is not None and \
            getattr(gc, "type", None) == "int8" else 256
        payload = self._rpc_dedicated(self._server_of(name),
                                      ("PULLQ", name, int(block)))
        if _wc.is_wire_payload(payload):
            scales = _np.asarray(payload[6])
            self._count_pull_bytes(len(payload[5]) + scales.nbytes)
            return _wc.decode_wire(payload)
        arr = _np.asarray(payload)
        self._count_pull_bytes(arr.nbytes)
        return arr

    def _pull_np(self, k, shape, size):
        import numpy as _onp
        plan = self._shard_plan(size)
        if plan is None:
            if self._hier:
                return self._pull_hier(k)
            arr = self._rpc("PULL", k)
            self._count_pull_bytes(_np.asarray(arr).nbytes)
            return arr
        # pipeline: issue every part request on its own socket FIRST,
        # then collect replies — wall-clock ~max(parts), not sum(parts)
        # (the concurrency is the point of big-array sharding).  PULL is
        # idempotent, so a failed round simply re-issues every part with
        # fresh seqs under the retry policy.
        from .. import telemetry as _telemetry
        policy = self._retry_policy()
        timeout = self._recv_timeout("PULL")
        with _telemetry.rpc_span("kv.client.PULL_SHARDED") as span:
            tctx = span.wire_context()
            for _attempt in policy:
                try:
                    with self._lock:
                        for i, _s, _e in plan:
                            sock = self._ensure_sock(i)
                            self._fault.fire(
                                "kvstore.send",
                                on_close=lambda i=i: self._kill_sock(i))
                            inner = ("PULL", self._part_key(k, i))
                            env = ("SEQ", self._client_id,
                                   self._next_seq(), inner)
                            self._srv_mod.send_msg(
                                sock, env if tctx is None
                                else env + (tctx,))
                        parts = []
                        bad = None
                        for i, _s, _e in plan:
                            # drain EVERY pending reply even after a
                            # failure: an unread response left buffered
                            # would be misread as the next RPC's answer
                            # (desync)
                            ok, payload = self._srv_mod.recv_msg(
                                self._socks[i], timeout=timeout)
                            if not ok and bad is None:
                                bad = (i, payload)
                            parts.append(payload)
                        if bad is not None:
                            raise MXNetError(
                                "dist_async server %d: %s" % bad)
                    return _onp.concatenate(
                        [_onp.asarray(p).ravel()
                         for p in parts]).reshape(shape)
                except (ConnectionError, OSError, TimeoutError) as e:
                    for i, _s, _e in plan:
                        self._kill_sock(i)
                    policy.note(e)
                    self._note_retry(span, -1, -1, e)
        raise MXNetError(
            "dist_async sharded pull of %r failed for %.3gs "
            "(MX_KVSTORE_RETRY_DEADLINE); last error: %s"
            % (k, policy.deadline, policy.last_error))

    def _rpc_on(self, idx, *msg):
        """One RPC with transparent recovery: on a dropped/ timed-out
        connection, reconnect and REPLAY the same (client_id, seq)
        envelope — the server's replay cache makes the retry idempotent
        (a PUSH applied before the reply was lost is answered from cache,
        never re-applied).  Gives up loudly after the retry deadline.

        Distributed tracing (ISSUE 8): the RPC runs under a client span
        whose (trace_id, span_id) ride the SEQ envelope, so the server's
        handler span becomes this span's child — one causally linked
        trace across the socket; each retry is an instant child event."""
        from .. import telemetry as _telemetry
        seq = self._next_seq()
        timeout = self._recv_timeout(msg[0])
        policy = self._retry_policy()
        if msg[0] == "STOP":
            # shutdown is best-effort: don't spend the full recovery
            # deadline on a server that is already gone
            policy.deadline = min(policy.deadline, 5.0)
        with _telemetry.rpc_span("kv.client.%s" % msg[0]) as span:
            tctx = span.wire_context()
            wrapped = ("SEQ", self._client_id, seq, msg) if tctx is None \
                else ("SEQ", self._client_id, seq, msg, tctx)
            for _attempt in policy:
                with self._lock:
                    try:
                        sock = self._ensure_sock(idx)
                        self._fault.fire(
                            "kvstore.send",
                            on_close=lambda: self._kill_sock(idx))
                        self._srv_mod.send_msg(sock, wrapped)
                        self._fault.fire(
                            "kvstore.recv",
                            on_close=lambda: self._kill_sock(idx))
                        ok, payload = self._srv_mod.recv_msg(
                            sock, timeout=timeout)
                    except (ConnectionError, OSError, TimeoutError) as e:
                        self._kill_sock(idx)
                        policy.note(e)
                        self._note_retry(span, idx, seq, e)
                        continue
                if not ok:
                    raise MXNetError("dist_async server %d: %s"
                                     % (idx, payload))
                return payload
        raise MXNetError(
            "dist_async server %d (%s) unreachable: %r retried for %.3gs "
            "(MX_KVSTORE_RETRY_DEADLINE exceeded); last error: %s"
            % (idx, self._addrs[idx], msg[0], policy.deadline,
               policy.last_error))

    @staticmethod
    def _note_retry(span, idx, seq, err) -> None:
        """Account one reconnect-and-replay: registry counter (rides the
        flight-recorder step records) + an instant child event on the
        RPC span (rides the merged chrome trace)."""
        from .. import telemetry as _telemetry
        _telemetry.registry.counter(
            "kvstore.client_retries",
            doc="dist_async RPC reconnect-and-replay attempts").inc()
        span.event("retry", server=idx, seq=seq, error=str(err))

    def _rpc(self, *msg):
        """Route by key for data commands; controller commands go wider
        (SET_OPT to every server, BARRIER to server 0)."""
        cmd = msg[0]
        if cmd in ("INIT", "PUSH", "PULL", "PULLQ"):
            return self._rpc_on(self._server_of(msg[1]), *msg)
        if cmd in ("SET_OPT", "STOP", "JOIN", "LEAVE"):
            # controller fan-out: every server installs the optimizer /
            # shuts down / applies the membership change (the barrier
            # quorum lives on server 0, but each shard server sizes its
            # own liveness table too; a STOP or LEAVE reaching only
            # server 0 would leak the rest)
            out = None
            for i in range(len(self._socks)):
                try:
                    out = self._rpc_on(i, *msg)
                except MXNetError:
                    if cmd not in ("STOP", "LEAVE"):
                        # STOP/LEAVE are best-effort per server: on the
                        # way OUT, a server that is already gone is fine
                        raise
            return out
        return self._rpc_on(0, *msg)        # BARRIER, MEMBERS

    # -- elastic membership (ISSUE 16) --------------------------------------
    def join(self):
        """Announce this worker's rank to every server's live membership
        table.  Idempotent: a rank the server already counts is a no-op
        (no epoch bump), so fixed-size jobs can send it unconditionally.
        Returns ``(epoch, members)`` as the last server reported."""
        payload = self._rpc("JOIN", self._client_id)
        epoch, members = payload
        self._membership_epoch = max(self._membership_epoch, int(epoch))
        return int(epoch), list(members)

    def leave(self):
        """Voluntarily retire this worker's rank from the quorum (the
        preemption-drain path: the supervisor's SIGTERM gives notice, the
        fit loop checkpoints at the epoch boundary, then leaves).  Best-
        effort per server — on the way out a dead server is fine."""
        payload = self._rpc("LEAVE", self._client_id)
        if payload is not None:
            self._membership_epoch = max(self._membership_epoch,
                                         int(payload[0]))
        return payload

    def members(self):
        """``(epoch, [ranks])`` of server 0's live membership table (the
        barrier quorum lives there, same as BARRIER routing)."""
        epoch, members = self._rpc("MEMBERS")
        return int(epoch), list(members)

    @property
    def membership_epoch(self) -> int:
        """The membership epoch this store incarnation is salted under
        (MX_ELASTIC_EPOCH at init, raised by observed JOIN replies)."""
        return self._membership_epoch

    def metrics(self, fmt: str = "json"):
        """Per-server telemetry scrape over the METRICS wire verb
        (ISSUE 12): returns one decoded exposition per server —
        ``fmt='json'`` a registry-snapshot dict, ``'prometheus'`` the
        text exposition.  Read-only and idempotent; this is the same
        surface the fleet collector (mxnet_tpu/fleet.py) scrapes."""
        import json as _json
        from .wire_codec import decode_text
        out = []
        for i in range(len(self._socks)):
            payload = self._rpc_on(i, "METRICS", fmt)
            text = decode_text(payload)
            out.append(_json.loads(text) if fmt == "json" else text)
        return out

    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            vv = v[0] if isinstance(v, (list, tuple)) else v
            self._send_np("INIT", k, vv.asnumpy())
            self._store[k] = vv.copy()       # local mirror for shape/dtype

    def _buckets_active(self, keys):
        """Bucketing is a pure-gradient-exchange optimization: with a
        server-side optimizer installed the server must see each key
        individually (per-key lr/wd/state), so buckets are off."""
        return len(keys) > 1 and self._optimizer is None and \
            self._updater is None

    def begin_exchange(self, keys, vlists):
        """No overlap on the PS store: its RPCs are host-blocking socket
        roundtrips — launching them mid-backward would serialize backward
        behind the wire instead of hiding it.  The Trainer falls back to
        the batched push/pull."""
        return None

    def build_exchange_body(self, keys, arrays, layout=None):
        """Untraceable: the exchange crosses a TCP socket mid-step (the
        server applies pushes the moment they arrive), so there is no
        pure function of the local gradients to inline — the compiled
        step lane (MX_STEP_COMPILE) falls back to the eager pipeline on
        this transport."""
        return None

    def _wire_gc(self):
        """The compact-wire compressor, when one is installed (2bit/int8;
        bf16 is a collective-path cast with no numpy dtype, so the PS
        wire ships it full-width)."""
        return getattr(self, "_gc", None)

    def _push_payload(self, wire_key, nd_value):
        """One PUSH: compressed wire tuple (payload + scales + dtype tag,
        dequantized server-side) or the full-width numpy array.

        Keys over the big-array bound are NOT compressed: INIT slices
        them across every server (``key::partN`` pieces), so a compact
        whole-key PUSH would target a server that only holds parts and
        fail 'not initialized' — they take the sharded full-width path
        instead (the bound already marks them as bandwidth-amortized)."""
        from ..engine import engine as _engine
        gc = self._wire_gc()
        if gc is not None and isinstance(nd_value, NDArray) and \
                jnp.issubdtype(nd_value._jax.dtype, jnp.floating) and \
                self._shard_plan(int(nd_value.size)) is None:
            wire = gc.encode(wire_key, nd_value._jax)
            _engine.count_wire_bytes(gc.wire_nbytes(nd_value.size))
            self._rpc("PUSH", wire_key, wire)
            return
        arr = nd_value.asnumpy()
        _engine.count_wire_bytes(arr.nbytes)
        self._send_np("PUSH", wire_key, arr)

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        vlists = [v if isinstance(v, (list, tuple)) else [v] for v in values]
        # local device merge only — wire compression (error feedback,
        # residual per wire key) happens at _push_payload, so the payload
        # is quantized exactly once
        merged = [self._reduce_local(v) if self._wire_gc() is not None
                  else self._reduce(v, key=k)
                  for k, v in zip(keys, vlists)]
        buckets = []
        solo = range(len(keys))
        if self._buckets_active(keys):
            # plan from the NDArrays, not densified numpy: the signature
            # must keep stype so the paired pull (planned from same-stype
            # targets) derives the identical layout
            buckets, solo = self._bucket_plans(keys, merged)
        for b in buckets:
            # concatenate ON DEVICE, then ONE host transfer per bucket —
            # a per-key asnumpy loop would reintroduce O(#keys) syncs
            flat = jnp.concatenate(
                [merged[p]._jax.reshape(-1) for p in b.positions])
            if b.name not in self._bucket_inited:
                # zero-init so the server's accumulator contract (pull =
                # init + sum of pushes) returns exactly the pushed sums
                self._send_np("INIT", b.name,
                              _np.zeros((b.total,),
                                        _np.dtype(str(flat.dtype))))
                self._bucket_inited.add(b.name)
            # one wire op per bucket; the SEQ-tagged retry layer now
            # replays buckets, not keys
            self._push_payload(b.name, NDArray(flat))
        for p in solo:
            self._push_payload(keys[p], merged[p])

    @staticmethod
    def _commit_bucket(b, flat, target_lists):
        """Scatter one pulled bucket to its member targets, homing each
        piece on the TARGET's device — a default-ctx array labeled with
        t's context would feed mixed-device operands into later jits."""
        flat = _np.asarray(flat).ravel()
        for p, off, size, shape in b.slices():
            piece = flat[off:off + size].reshape(shape)
            for t in target_lists[p]:
                t._set_jax(nd.array(piece, ctx=t.context)
                           .astype(t.dtype)._jax)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        target_lists = [o if isinstance(o, (list, tuple)) else [o]
                        for o in outs]
        firsts = [ts[0] for ts in target_lists]
        buckets = []
        solo = range(len(keys))
        if self._buckets_active(keys):
            # same signature as the paired push (grads pull into same-stype,
            # same-shaped buffers), so the derived layout agrees — even for
            # a worker that never pushed itself (bucket names are a pure
            # function of the signature)
            buckets, solo = self._bucket_plans(keys, firsts)
        solo = list(solo)
        if self._hier and len(buckets) > 1:
            # as-ready cross-slice tier (ISSUE 16): every bucket's PULLQ
            # flies concurrently on its own connection and COMMITS the
            # moment its reply lands — a straggling server shard (or
            # slice behind it) delays only its own buckets, never the
            # whole pull.  Commits happen on THIS thread (the
            # as_completed loop), so target mutation stays single-
            # threaded.
            import concurrent.futures as _fut
            ex = self._hier_pool_get()
            futs = {ex.submit(self._hier_bucket_pull, b.name): b
                    for b in buckets}
            for f in _fut.as_completed(futs):
                b = futs[f]
                try:
                    flat = f.result()
                except MXNetError:
                    solo.extend(b.positions)
                    continue
                self._commit_bucket(b, flat, target_lists)
        else:
            for b in buckets:
                try:
                    flat = self._pull_np(b.name, (b.total,), b.total)
                except MXNetError:
                    # bucket absent server-side (nothing pushed this
                    # layout yet — e.g. pulling broadcast weights):
                    # per-key fallback for exactly this bucket's
                    # members, never silent staleness
                    solo.extend(b.positions)
                    continue
                self._commit_bucket(b, flat, target_lists)
        for p in sorted(solo):
            arr = self._pull_np(keys[p], firsts[p].shape,
                                int(firsts[p].size))
            for t in target_lists[p]:
                t._set_jax(nd.array(arr, ctx=t.context)
                           .astype(t.dtype)._jax)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull CURRENT server rows (the base implementation reads the
        local init-time mirror, which a server-side optimizer has long
        moved past)."""
        keys, outs = self._normalize(key, out)
        for k in keys:
            mirror = self._store.get(k)
            if mirror is not None:           # init populated shape/dtype
                arr = self._pull_np(k, mirror.shape, int(mirror.size))
            else:
                # key init'd by another worker only: whole-key pull (a
                # big SHARDED key still needs a local init for its shape)
                arr = self._rpc("PULL", k)
            self._store[k] = nd.array(arr)     # refresh mirror, then gather
        return super().row_sparse_pull(key, out=out, priority=priority,
                                       row_ids=row_ids)

    def set_optimizer(self, optimizer):
        """Ship the optimizer to the server (reference: the pickled
        set_optimizer controller message).  The server keeps the FIRST
        installation (state preservation); the trailing barrier guarantees
        no worker pushes before the optimizer is installed."""
        self._rpc("SET_OPT", pickle.dumps(optimizer))
        self._optimizer = optimizer
        # updates happen server-side: no local updater
        self._updater = None
        if self._size > 1:
            self._barrier()

    def _barrier(self):
        self._rpc("BARRIER", None)

    def stop_server(self):
        try:
            self._rpc("STOP", None)
        except MXNetError:
            pass
        self.close()

    def close(self):
        """Stop the heartbeat thread and drop every connection.  (A
        voluntary departure calls :meth:`leave` FIRST — close alone
        keeps the rank in the quorum, which is what a worker that will
        be respawned under the same rank wants.)"""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
            self._hb_thread = None
        if getattr(self, "_hier_pool", None) is not None:
            self._hier_pool.shutdown(wait=False)
            self._hier_pool = None
        with self._lock:
            for i in range(len(self._socks)):
                self._kill_sock(i)


_STORES = {
    "local": KVStoreLocal,
    "device": KVStoreDevice,
    "ici": KVStoreICI,
    # collective path covers these transports on TPU:
    "nccl": KVStoreICI,
    "dist": KVStoreICI,
    "dist_sync": KVStoreICI,
    "dist_device_sync": KVStoreICI,
    "dist_async": KVStoreDistAsync,
    "horovod": KVStoreICI,
}


def create(name: str = "local") -> KVStore:
    """Reference: kvstore.create / KVStore::Create."""
    import os
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    key = name.lower()
    if key == "dist_async" and _ps_addr() is None:
        # no PS in the deployment: degrade to the sync collective store
        # with a loud note, like the reference refuses to start without
        # a tracker (here multi-process jobs still work, just synchronously)
        import warnings
        warnings.warn("kvstore 'dist_async' requested without a parameter "
                      "server (launch with tools/launch.py -s <servers>); "
                      "using the synchronous collective store instead")
        return KVStoreICI()
    if key not in _STORES:
        raise MXNetError("unknown KVStore type %r (have %s)"
                         % (name, sorted(_STORES)))
    return _STORES[key]()


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the gradient-exchange bodies' declared
# donation/HBM invariants.  The exchange bodies normally inline into
# the compiled step's single program; contracting them STANDALONE keeps
# the proof per-transport — the int8/2bit error-feedback residuals are
# the donated state, and the verifier shows each survives as an output
# alias under every compression mode before any TPU sees the job.
# Builders run only inside `python -m tools.mxlint --contracts`.
# ---------------------------------------------------------------------------

def _exchange_contract_cases():
    from ..programs import ContractCase, register_program
    from ..device import cpu
    cases = []
    shapes = [(96, 4), (256,)]
    for mode in ("int8", "2bit", "none"):
        kv = KVStoreLocal()
        if mode != "none":
            kv.set_gradient_compression({"type": mode})
        templates = [NDArray(jnp.zeros(s, jnp.float32), ctx=cpu())
                     for s in shapes]
        body = kv.build_exchange_body(list(range(len(shapes))), templates)
        pname = "kvstore.exchange_%s" % mode
        prog = register_program(pname, body, donate_argnums=(1,))
        grads = [jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]
        residuals = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(dt))
                     for _wk, s, dt in body.residual_specs]
        cases.append(ContractCase(pname, (grads, residuals),
                                  label=mode, target=prog))
    return cases


def _sum_contract_cases():
    from ..programs import ContractCase
    arrs = tuple(jax.ShapeDtypeStruct((128, 8), jnp.float32)
                 for _ in range(4))
    return [ContractCase("kvstore.sum", (arrs,), label="sum4",
                         target=_sum_arrays)]


def _declare_kvstore_contracts():
    from ..programs import declare_contract
    declare_contract(
        "kvstore.exchange", _exchange_contract_cases,
        donate_argnums=(1,),
        temp_budget_bytes=1 << 20,
        description="single-worker traceable exchange bodies (int8 / "
                    "2bit / uncompressed): error-feedback residuals "
                    "donate in-place; gradients rebind to the returned "
                    "merged values")
    declare_contract(
        "kvstore.sum", _sum_contract_cases,
        donate_argnums=(),
        temp_budget_bytes=1 << 20,
        description="per-key eager reduction body (light census mode): "
                    "no donations — the summands are live parameter "
                    "gradients owned by their devices")


_declare_kvstore_contracts()
