"""2-bit gradient compression with residual accumulation (error feedback).

Reference: ``src/kvstore/gradient_compression.cc`` (`GradientCompression`,
`Quantize2BitImpl`, `Dequantize2BitImpl`) and
``src/kvstore/gradient_compression-inl.h``.

Contract (the reference's exact algorithm):
  * per worker and per key a float *residual* accumulates what compression
    dropped: ``residual += grad``;
  * each element is quantized to one of three levels —
    ``+threshold`` when ``residual >= threshold``, ``-threshold`` when
    ``residual <= -threshold``, else 0 — and the emitted level is
    subtracted back from the residual (error feedback keeps |residual| <
    threshold + |grad_step|, so no gradient mass is ever lost, only
    delayed);
  * the receiver sums workers' *dequantized* values.

TPU-native realization: quantize/error-feedback is one jitted elementwise
kernel (XLA fuses the compare/select/subtract).  On the collective path
the "wire" is the allreduce itself, which sums the dequantized ±t/0
levels directly — a 2-bit payload would have to be decoded before psum
anyway, so nothing is gained by shipping codes between chips.  The packed
2-bit wire format (16 codes per 32-bit word) is still implemented and
tested for format parity with reference byte streams: ``pack_2bit`` /
``unpack_2bit``.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as _np
import jax
import jax.numpy as jnp

__all__ = ["GradientCompression", "quantize_2bit", "pack_2bit",
           "unpack_2bit"]


@jax.jit
def _quantize_2bit_jit(grad, residual, threshold):
    acc = residual + grad
    q = jnp.where(acc >= threshold, threshold, 0.0) + \
        jnp.where(acc <= -threshold, -threshold, 0.0)
    q = q.astype(grad.dtype)
    return q, (acc - q).astype(grad.dtype)


def quantize_2bit(grad, residual, threshold: float):
    """One error-feedback quantization step; returns (dequantized levels,
    new residual).  Levels are in {-threshold, 0, +threshold}."""
    return _quantize_2bit_jit(grad, residual,
                              jnp.asarray(threshold, grad.dtype))


def pack_2bit(levels: _np.ndarray, threshold: float) -> _np.ndarray:
    """Pack ±t/0 levels into the 2-bit wire format: 16 codes per uint32
    word, code i of a word at bits [2i, 2i+1], 00=zero 01=-t 10=+t
    (reference Quantize2BitImpl packs 16 values per float32 word; the
    in-word bit order is pinned by the roundtrip test)."""
    flat = _np.asarray(levels, _np.float32).ravel()
    codes = _np.where(flat > 0, 2, _np.where(flat < 0, 1, 0)).astype(
        _np.uint32)
    pad = (-len(codes)) % 16
    if pad:
        codes = _np.concatenate([codes, _np.zeros(pad, _np.uint32)])
    words = codes.reshape(-1, 16)
    out = _np.zeros(words.shape[0], _np.uint32)
    for i in range(16):
        out |= words[:, i] << (2 * i)
    return out


def unpack_2bit(words: _np.ndarray, n: int, threshold: float,
                dtype=_np.float32) -> _np.ndarray:
    """Inverse of pack_2bit: first `n` codes back to ±threshold/0."""
    words = _np.asarray(words, _np.uint32)
    codes = _np.zeros((len(words), 16), _np.uint32)
    for i in range(16):
        codes[:, i] = (words >> (2 * i)) & 0x3
    codes = codes.ravel()[:n]
    out = _np.zeros(n, dtype)
    out[codes == 2] = threshold
    out[codes == 1] = -threshold
    return out


class GradientCompression:
    """Per-store compression state: residual per key (reference keeps one
    residual buffer per key per worker)."""

    def __init__(self, threshold: float = 0.5):
        if threshold <= 0:
            raise ValueError("2bit compression threshold must be > 0, got "
                             "%r" % threshold)
        self.type = "2bit"
        self.threshold = float(threshold)
        self._residuals: Dict = {}

    def quantize(self, key, x) -> Tuple:
        """Quantize jax array `x` for `key`, updating the residual."""
        res = self._residuals.get(key)
        if res is None or res.shape != x.shape:
            res = jnp.zeros_like(x)
        q, new_res = quantize_2bit(x, res, self.threshold)
        self._residuals[key] = new_res
        return q

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}
