"""Gradient compression for the exchange wire (2-bit + int8, error
feedback).

Reference: ``src/kvstore/gradient_compression.cc`` (`GradientCompression`,
`Quantize2BitImpl`, `Dequantize2BitImpl`) and
``src/kvstore/gradient_compression-inl.h``; the int8 mode follows EQuARX
(arXiv:2506.17615) — per-block symmetric int8 with scale-merged
dequant-sum-requant inside the collective.

Contract (the reference's exact algorithm, both modes):
  * per worker and per wire key a float *residual* accumulates what
    compression dropped: ``residual += grad``;
  * the emitted payload is subtracted back from the residual (error
    feedback: compression error is carried into the next step, so no
    gradient mass is ever lost, only delayed);
  * the receiver sums workers' *dequantized* values.

The kernels live in :mod:`mxnet_tpu.ops.quantization` — jitted,
donation-aware (the residual buffer is donated into each quantize step).
This module owns the per-key residual STATE (device-resident, f32); the
host-side ``QGRAD`` wire codec the dist_async TCP path ships lives in
:mod:`.wire_codec` (numpy-only, so the server never imports the device
kernel stack) and is re-exported here.

The packed 2-bit wire format (16 codes per 32-bit word) is implemented
both device-side (ops.quantization.pack_2bit_words) and host-side
(wire_codec.pack_2bit / unpack_2bit, kept for format parity with
reference byte streams); the roundtrip test pins them bit-compatible.
"""
from __future__ import annotations

from typing import Dict

import numpy as _np
import jax.numpy as jnp

from ..ops import quantization as _qops
from .wire_codec import (is_wire_payload, encode_wire, decode_wire,  # noqa: F401
                         pack_2bit, unpack_2bit)

__all__ = ["GradientCompression", "quantize_2bit", "pack_2bit",
           "unpack_2bit", "encode_wire", "decode_wire", "is_wire_payload"]


def quantize_2bit(grad, residual, threshold: float):
    """One error-feedback quantization step; returns (dequantized levels,
    new residual).  Levels are in {-threshold, 0, +threshold}.  NB the
    residual buffer is donated into the jitted kernel — pass a fresh array
    or one you will not read again."""
    return _qops.quantize_2bit_ef(grad, residual, threshold)


def wire_nbytes(mode: str, n: int, block: int = None) -> int:
    """Bytes the payload of an n-element gradient occupies on the wire."""
    if mode == "int8":
        return _qops.int8_wire_bytes(n, block or _qops.grad_compress_block())
    if mode == "2bit":
        return _qops.two_bit_wire_bytes(n)
    if mode == "bf16":
        return 2 * n
    return 4 * n


class GradientCompression:
    """Per-store compression state: one device-resident residual per wire
    key (reference keeps one residual buffer per key per worker).  Wire
    keys are whatever the exchange layer compresses — a parameter key on
    the per-key path, a fusion-bucket name on the bucketed path (bucket
    names embed a member CRC, so a layout change rolls the residual
    instead of misapplying it)."""

    def __init__(self, type: str = "2bit", threshold: float = 0.5,
                 block: int = None):
        if type not in ("2bit", "int8"):
            raise ValueError("unsupported gradient compression type %r "
                             "(GradientCompression handles '2bit'/'int8')"
                             % (type,))
        if threshold <= 0:
            raise ValueError("2bit compression threshold must be > 0, got "
                             "%r" % threshold)
        self.type = type
        self.threshold = float(threshold)
        self.block = int(block) if block else _qops.grad_compress_block()
        self._residuals: Dict = {}
        # buffer-census attribution (ISSUE 10): device-resident error-
        # feedback residuals land in "ef_residuals"
        from .. import programs as _programs
        _programs.track_buffers(
            "ef_residuals", self,
            lambda gc: [a for a in list(gc._residuals.values())
                        + list(gc._pinned.values()) if a is not None])
        # wire keys whose PRE-quantize residual must stay restorable (the
        # overlap session's relaunch path): quantization for a pinned key
        # runs donation-FREE so the checkpointed buffer remains valid on
        # backends where donation really invalidates it (TPU)
        self._pinned: Dict = {}

    # -- residual store -----------------------------------------------------
    def _residual(self, key, shape, dtype=None):
        res = self._residuals.pop(key, None)
        if res is None or res.shape != tuple(shape):
            res = jnp.zeros(shape, dtype or jnp.float32)
        return res

    def _donate(self, key) -> bool:
        return key not in self._pinned

    # -- compiled-step residual threading (ISSUE 7) --------------------------
    def peek_residual(self, key, shape, dtype=None):
        """Current residual for `key` as a concrete array (zeros when
        absent or shape-rolled) WITHOUT popping it — the whole-step
        compiled lane reads every wire key's residual as a donated jit
        input and writes the new state back via :meth:`put_residual`
        after the dispatch."""
        res = self._residuals.get(key)
        if res is None or res.shape != tuple(shape):
            return jnp.zeros(tuple(shape), dtype or jnp.float32)
        return res

    def put_residual(self, key, value) -> None:
        """Install the post-step residual for `key` (the compiled step's
        write-back half of :meth:`peek_residual`)."""
        self._residuals[key] = value

    # -- overlap-session checkpointing (relaunch rollback) -------------------
    def checkpoint(self, keys) -> None:
        """Pin the CURRENT residuals of `keys`: until :meth:`commit`,
        quantize steps for these keys keep the checkpointed buffer alive
        (no donation) so :meth:`rollback` can restore the exact
        pre-launch error-feedback state.  Idempotent per key — a second
        checkpoint before commit keeps the ORIGINAL snapshot (the
        relaunch path re-quantizes from the restored state)."""
        for k in keys:
            if k not in self._pinned:
                self._pinned[k] = self._residuals.get(k)

    def rollback(self, keys) -> None:
        """Restore the checkpointed residuals of `keys` (the launched
        exchange's payload was discarded, so its error-feedback step
        must un-happen before re-quantizing)."""
        for k in keys:
            if k not in self._pinned:
                continue
            snap = self._pinned[k]
            if snap is None:
                self._residuals.pop(k, None)
            else:
                self._residuals[k] = snap

    def commit(self, keys) -> None:
        """Drop the checkpoints of `keys` (results committed; donation
        resumes next step)."""
        for k in keys:
            self._pinned.pop(k, None)

    # -- device-side API (collective path) ----------------------------------
    def quantize(self, key, x):
        """Error-feedback compress→decompress of `x` for wire key `key`:
        what a single worker's exchange observes of the compression.  One
        jitted dispatch; updates the residual."""
        if self.type == "int8":
            flat = x.reshape(-1)
            res = self._residual(key, flat.shape)
            deq, new_res = _qops.roundtrip_int8_blocks(
                flat, res, self.block, donate=self._donate(key))
            self._residuals[key] = new_res
            return deq.reshape(x.shape)
        res = self._residual(key, x.shape, x.dtype)
        q, new_res = _qops.quantize_2bit_ef(x, res, self.threshold,
                                            donate=self._donate(key))
        self._residuals[key] = new_res
        return q

    def compress_device(self, key, flat):
        """Compress a FLAT payload to its compact device representation,
        updating the residual.  int8 → (q, scales); 2bit → (words,) of
        the packed format."""
        if self.type == "int8":
            res = self._residual(key, flat.shape)
            q, scales, new_res = _qops.quantize_int8_blocks(
                flat, res, self.block, donate=self._donate(key))
            self._residuals[key] = new_res
            return q, scales
        res = self._residual(key, flat.shape, flat.dtype)
        levels, new_res = _qops.quantize_2bit_ef(flat, res, self.threshold,
                                                 donate=self._donate(key))
        self._residuals[key] = new_res
        return (_qops.pack_2bit_words(levels),)

    def decompress_device(self, payload, n):
        """Inverse of :meth:`compress_device` (device, jitted)."""
        if self.type == "int8":
            q, scales = payload
            return _qops.dequantize_int8_blocks(q, scales, n)
        return _qops.unpack_2bit_words(payload[0], self.threshold, n)

    # -- host-side wire (dist_async path) -----------------------------------
    def encode(self, key, x):
        """Compress `x` and encode it for the TCP wire (ONE host transfer
        of the compact payload instead of the full-width float array)."""
        flat = x.reshape(-1)
        payload = self.compress_device(key, flat)
        if self.type == "int8":
            q, scales = payload
            return encode_wire("int8", x.shape, x.dtype,
                               (_np.asarray(q), _np.asarray(scales)))
        return encode_wire("2bit", x.shape, x.dtype,
                           (_np.asarray(payload[0]), self.threshold))

    def wire_nbytes(self, n: int) -> int:
        return wire_nbytes(self.type, n, self.block)

    def get_params(self):
        p = {"type": self.type, "threshold": self.threshold}
        if self.type == "int8":
            p["block"] = self.block
        return p
