"""Async double-buffered host→device input pipeline (ISSUE 13).

The step-phase histograms (PR 8) exist to expose exactly one stall this
module removes: ``data_wait`` — the training loop blocking on batch
preparation + the synchronous host→device transfer before every step.
:class:`DevicePrefetcher` runs both on a background thread, one batch
*ahead* of the consumer (double-buffered by default, ``depth``
configurable via ``MX_PREFETCH_DEPTH``), so the device transfer of
batch N+1 overlaps the device compute of batch N, and the loop's
``data_wait`` share collapses to the queue handoff.

Semantics are exactly the synchronous loop's:

* **bit-parity** — ``jax.device_put`` moves bytes; it never rounds,
  casts or reorders, so a prefetched run's loss trajectory is
  bit-identical to the unprefetched one (test-pinned on the
  deterministic MLP).
* **bounded** — the queue holds at most ``depth`` batches; the
  producer blocks (stop-aware, bounded polls) when the consumer falls
  behind, so prefetching can never balloon host/device memory by more
  than ``depth`` batches.
* **clean shutdown** — :meth:`close` (idempotent; also ``with`` exit
  and ``__del__``) stops the producer, drains the queue and joins the
  thread with a bounded wait; a producer blocked on a full queue
  observes the stop event within one poll tick.  A wedged *source*
  iterator cannot wedge ``close()``.
* **error transparency** — a source that raises surfaces the exception
  (chained, naming the source) from the consumer's next ``next()``
  call, not on a background thread's stderr.

The wait the consumer *does* pay is measured: each ``next()`` records
its block time into ``step_phase_seconds{phase=data_wait}`` via
``telemetry.observe_phase`` (the cross-thread form — the wait starts on
the consumer thread against work finishing on the producer thread).
The clock is injectable for deterministic tests.

Hot-path contract (mxlint-rooted): ``__next__`` is queue handoff +
clock reads only — the device transfer, any host-side transform and
the source's own work all live on the producer thread.  No disk I/O,
no device sync on the consumer side.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, Optional

import jax

from ..base import MXNetError, get_env
from ..ndarray.ndarray import NDArray
from .. import telemetry as _telemetry

__all__ = ["DevicePrefetcher", "prefetch_enabled", "prefetch_depth"]

_POLL_S = 0.05          # stop-aware bounded wait tick


def prefetch_enabled() -> bool:
    """MX_PREFETCH (default on): async device input prefetch in the
    harnesses that support it (bench.py --eager)."""
    return bool(get_env("MX_PREFETCH", dtype=bool))


def prefetch_depth() -> int:
    """MX_PREFETCH_DEPTH: batches in flight ahead of the consumer
    (2 = classic double buffering)."""
    try:
        val = get_env("MX_PREFETCH_DEPTH", 2, int)
        n = 2 if val is None else int(val)
    except (TypeError, ValueError):
        n = 2
    return max(1, n)        # 0 clamps: the consumer needs >= 1 slot


class _Stop:
    """Queue sentinel: source exhausted."""


class _Err:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def _to_device(x, device):
    if x is None:
        return None
    if isinstance(x, NDArray):
        return NDArray(jax.device_put(x._jax, device), ctx=x.ctx)
    return jax.device_put(x, device)


class DevicePrefetcher:
    """Iterate `source` one batch ahead, device-putting each leaf.

    ``source`` is any iterable of array pytrees (tuples/lists/dicts of
    numpy arrays, jax arrays or NDArrays).  ``transform`` (optional)
    runs on the PRODUCER thread before the transfer — host-side batch
    assembly belongs there, not in the training loop.  ``device=None``
    uses jax's default placement (``jax.device_put`` with no target).
    """

    def __init__(self, source: Iterable, device=None,
                 depth: Optional[int] = None,
                 transform: Optional[Callable[[Any], Any]] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self._source = source
        self._device = device
        self._depth = depth if depth is not None else prefetch_depth()
        if self._depth < 1:
            raise MXNetError("DevicePrefetcher depth must be >= 1, got %d"
                             % self._depth)
        self._transform = transform
        self._clock = clock
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="DevicePrefetcher",
            daemon=True)
        self._thread.start()

    # -- producer -----------------------------------------------------------
    def _put(self, item) -> bool:
        """Bounded, stop-aware enqueue; False once stopped."""
        with self._cv:
            while len(self._q) >= self._depth:
                if self._stop.is_set():
                    return False
                self._cv.wait(timeout=_POLL_S)
            if self._stop.is_set():
                return False
            self._q.append(item)
            self._cv.notify_all()
        return True

    def _run(self):
        it = iter(self._source)
        while not self._stop.is_set():
            try:
                try:
                    batch = next(it)
                except StopIteration:
                    self._put(_Stop)
                    return
                if self._transform is not None:
                    batch = self._transform(batch)
                batch = jax.tree_util.tree_map(
                    lambda x: _to_device(x, self._device), batch,
                    is_leaf=lambda x: isinstance(x, NDArray))
            except Exception as e:      # surfaced by the consumer's next()
                err = MXNetError(
                    "DevicePrefetcher: source %s raised %s: %s"
                    % (type(self._source).__name__, type(e).__name__, e))
                err.__cause__ = e
                self._put(_Err(err))
                self._put(_Stop)
                return
            if not self._put(batch):
                return

    # -- consumer -----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise MXNetError("DevicePrefetcher is closed")
        t0 = self._clock()
        with self._cv:
            while not self._q:
                if self._stop.is_set() or not self._thread.is_alive():
                    # producer died without a sentinel (interpreter
                    # teardown edge): treat as exhausted
                    if not self._q:
                        raise StopIteration
                    break
                self._cv.wait(timeout=_POLL_S)
            item = self._q.popleft()
            self._cv.notify_all()
        _telemetry.observe_phase("data_wait", self._clock() - t0)
        if item is _Stop:
            raise StopIteration
        if isinstance(item, _Err):
            raise item.exc
        return item

    next = __next__

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        """Stop the producer and release the thread.  Idempotent; never
        blocks unbounded (a source wedged mid-``next`` keeps its daemon
        thread, which exits at its next queue interaction)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        with self._cv:
            self._q.clear()
            self._cv.notify_all()
        self._thread.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass    # interpreter shutdown: locks/threads may be gone
