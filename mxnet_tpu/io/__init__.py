"""mx.io — data iterators.

Reference: ``python/mxnet/io/io.py`` (DataDesc, DataBatch, DataIter,
NDArrayIter, ResizeIter, PrefetchingIter, CSVIter) and
``src/io/iter_image_recordio_2.cc`` (ImageRecordIter — the threaded
.rec→decode→augment→batch pipeline).

TPU-first notes: the iterator protocol is host-side plumbing and stays
Python; the heavy parts are (a) the .rec parser, which is native C++
(``mxnet_tpu.recordio``), and (b) JPEG decode, which PIL does in C with
the GIL released — ``ImageRecordIter`` runs decode+augment on a thread
pool and assembles batches NCHW, then the training loop's device_put
overlaps H2D with compute the way the reference's prefetcher overlaps
PCIe copies.
"""
from __future__ import annotations

import threading
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

import numpy as _np

from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..device import cpu

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "ImageRecordIter", "ImageRecordUInt8Iter", "LibSVMIter",
           "MNISTIter", "DevicePrefetcher"]

from .prefetch import DevicePrefetcher


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Shape/type descriptor (reference: io.DataDesc)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), _np.dtype(dtype),
                               layout)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """One batch (reference: io.DataBatch)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None, bucket_key=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label
        self.bucket_key = bucket_key  # BucketingModule routing

    def __repr__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        return "DataBatch: data shapes %s" % (shapes,)


class DataIter:
    """Iterator protocol (reference: io.DataIter — next/reset/provide_*)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        raise NotImplementedError

    def __next__(self):
        return self.next()

    @property
    def provide_data(self) -> List[DataDesc]:
        raise NotImplementedError

    @property
    def provide_label(self) -> List[DataDesc]:
        raise NotImplementedError

    # reference's default implementations
    def iter_next(self) -> bool:
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            self._next_batch = None
            return False

    def getdata(self):
        return self._next_batch.data[0]

    def getlabel(self):
        return self._next_batch.label[0]

    def getindex(self):
        return self._next_batch.index

    def getpad(self):
        return self._next_batch.pad


def _as_arrays(data, default_name="data"):
    """Normalize data= argument to [(name, numpy)] (reference: _init_data)."""
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [(default_name, data)]
    elif isinstance(data, (list, tuple)):
        data = [(default_name if i == 0 else "%s%d" % (default_name, i), d)
                for i, d in enumerate(data)]
    elif isinstance(data, dict):
        data = sorted(data.items())
    out = []
    for name, arr in data:
        if isinstance(arr, NDArray):
            arr = arr.asnumpy()
        out.append((name, _np.asarray(arr)))
    return out


class NDArrayIter(DataIter):
    """Batch iterator over in-memory arrays (reference: io.NDArrayIter —
    shuffle, pad/discard/roll_over last-batch handling, multi-input dicts).
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _as_arrays(data, data_name)
        self.label = _as_arrays(label, label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise ValueError("bad last_batch_handle %r" % last_batch_handle)
        self.last_batch_handle = last_batch_handle
        self.shuffle = shuffle
        self.cursor = -batch_size
        self._roll = 0  # carried samples for roll_over
        self._order = _np.arange(self.num_data)
        if shuffle:
            _np.random.shuffle(self._order)

    @property
    def provide_data(self):
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:], arr.dtype)
                for name, arr in self.data]

    @property
    def provide_label(self):
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:], arr.dtype)
                for name, arr in self.label]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self._order)
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + \
                (self.cursor - self.num_data) % self.batch_size or \
                -self.batch_size
        else:
            self.cursor = -self.batch_size

    def _take(self, arrays, start, count):
        idx = self._order[start:start + count]
        return [arr[idx] for _name, arr in arrays]

    def next(self) -> DataBatch:
        self.cursor += self.batch_size
        if self.cursor >= self.num_data:
            raise StopIteration
        end = self.cursor + self.batch_size
        if end <= self.num_data:
            datas = self._take(self.data, self.cursor, self.batch_size)
            labels = self._take(self.label, self.cursor, self.batch_size)
            pad = 0
        else:
            pad = end - self.num_data
            if self.last_batch_handle == "discard":
                raise StopIteration
            tail_d = self._take(self.data, self.cursor,
                                self.num_data - self.cursor)
            tail_l = self._take(self.label, self.cursor,
                                self.num_data - self.cursor)
            # pad: wrap around to the head (reference pads with first
            # samples; roll_over keeps them for the next epoch)
            head_d = self._take(self.data, 0, pad)
            head_l = self._take(self.label, 0, pad)
            datas = [_np.concatenate([t, h]) for t, h in zip(tail_d, head_d)]
            labels = [_np.concatenate([t, h]) for t, h in zip(tail_l, head_l)]
        return DataBatch(
            data=[nd.array(d, ctx=cpu(), dtype=d.dtype) for d in datas],
            label=[nd.array(l, ctx=cpu(), dtype=l.dtype) for l in labels],
            pad=pad, provide_data=self.provide_data,
            provide_label=self.provide_label)


class ResizeIter(DataIter):
    """Truncate/extend an iterator to a fixed number of batches
    (reference: io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators (reference:
    io.PrefetchingIter — hides iterator latency behind compute).

    Lifecycle: the prefetch threads live in a ThreadPoolExecutor that
    must be shut down — ``close()`` (idempotent; also called by
    ``__del__`` and ``with``-statement exit) drains the in-flight
    batches and releases the threads, so a training job that churns
    through many iterators doesn't leak a pool per iterator.  A
    prefetch worker that raises is surfaced by the NEXT ``next()`` call
    as an :class:`MXNetError` naming which inner iterator failed, with
    the original exception chained (``raise ... from``)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self._pool = ThreadPoolExecutor(max_workers=len(iters))
        self._futures = None
        self._closed = False
        self._submit()

    def _submit(self):
        def _one(it, i):
            try:
                return it.next()
            except StopIteration:
                return None
            except Exception as e:
                raise MXNetError(
                    "PrefetchingIter: inner iterator %d (%s) raised "
                    "%s: %s" % (i, type(it).__name__, type(e).__name__,
                                e)) from e
        self._futures = [self._pool.submit(_one, it, i)
                         for i, it in enumerate(self.iters)]

    @property
    def provide_data(self):
        return sum([i.provide_data for i in self.iters], [])

    @property
    def provide_label(self):
        return sum([i.provide_label for i in self.iters], [])

    def reset(self):
        if self._closed:
            raise MXNetError("PrefetchingIter is closed")
        for f in self._futures:
            try:
                f.result()
            except MXNetError:
                pass        # reset clears a poisoned prefetch slot
        for it in self.iters:
            it.reset()
        self._submit()

    def next(self):
        if self._closed:
            raise MXNetError("PrefetchingIter is closed")
        batches = [f.result() for f in self._futures]
        if any(b is None for b in batches):
            raise StopIteration
        self._submit()
        if len(batches) == 1:
            return batches[0]
        return DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([(b.label or []) for b in batches], []),
            pad=max(b.pad for b in batches))

    def close(self):
        """Shut down the prefetch threads.  Safe to call repeatedly;
        further next()/reset() calls raise.  Never blocks: pending
        fetches are cancelled and an in-flight one releases its thread
        when it returns — close() (and __del__, possibly running inside
        GC on the training thread) must not hang on a wedged inner
        iterator."""
        if self._closed:
            return
        self._closed = True
        for f in self._futures or []:
            f.cancel()
        self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass    # interpreter shutdown: executor internals may be gone


class CSVIter(DataIter):
    """Batches from CSV files (reference: src/io/iter_csv.cc via io.CSVIter).
    Loads eagerly (host RAM) — the reference streams, but CSV workloads
    that matter fit; documented trade."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **_kw):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32,
                           ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32,
                                ndmin=2).reshape((-1,) + tuple(label_shape))
        else:
            label = _np.zeros((data.shape[0], 1), _np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """Sparse batches from LibSVM text files (reference:
    src/io/iter_libsvm.cc LibSVMIterParam/LibSVMIter via io.LibSVMIter).

    Each line is ``label idx:val idx:val ...``; batches come out as
    CSRNDArray of shape (batch_size, num_features) — the sparse-iterator
    integration path (feeds rowsparse/CSR pipelines).  ``label_libsvm``
    optionally reads labels (possibly multi-output, also sparse text)
    from a second file, like the reference.  Loads eagerly (host RAM);
    the reference streams, same documented trade as CSVIter."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, label_shape=(1,), round_batch=True,
                 **_kw):
        super().__init__(batch_size)
        n_feat = int(data_shape[0] if isinstance(data_shape, (tuple, list))
                     else data_shape)
        labels, rows = self._parse(data_libsvm)
        if label_libsvm is not None:
            n_lab = int(label_shape[0] if isinstance(label_shape,
                                                     (tuple, list))
                        else label_shape)
            lab_rows = self._parse(label_libsvm)[1]
            labels = _np.zeros((len(lab_rows), n_lab), _np.float32)
            for i, row in enumerate(lab_rows):
                for j, v in row:
                    if j < n_lab:
                        labels[i, j] = v
        else:
            labels = _np.asarray(labels, _np.float32).reshape(-1, 1)
        if len(rows) != len(labels):
            raise ValueError("libsvm data has %d rows but labels have %d"
                             % (len(rows), len(labels)))
        self._rows = rows
        self._labels = labels
        self._n_feat = n_feat
        self._round_batch = round_batch
        self._cursor = 0

    @staticmethod
    def _parse(path):
        labels, rows = [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                head = parts[0]
                if ":" in head:          # label-less line (label file use)
                    labels.append(0.0)
                    ents = parts
                else:
                    labels.append(float(head))
                    ents = parts[1:]
                row = []
                for ent in ents:
                    idx, val = ent.split(":")
                    row.append((int(idx), float(val)))
                rows.append(row)
        return labels, rows

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._n_feat),
                         _np.float32)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label",
                         (self.batch_size,) + tuple(self._labels.shape[1:]),
                         _np.float32)]

    def reset(self):
        self._cursor = 0

    def next(self) -> DataBatch:
        from ..ndarray.sparse import csr_matrix
        n = len(self._rows)
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        idxs = list(range(self._cursor, min(end, n)))
        pad = 0
        if end > n:
            if not self._round_batch:
                raise StopIteration
            pad = end - n
            idxs += list(range(pad))     # wrap like the reference round_batch
        self._cursor = end
        data_vals, data_cols, indptr = [], [], [0]
        for i in idxs:
            for j, v in sorted(self._rows[i]):
                data_cols.append(j)
                data_vals.append(v)
            indptr.append(len(data_cols))
        csr = csr_matrix((_np.asarray(data_vals, _np.float32),
                          _np.asarray(data_cols, _np.int64),
                          _np.asarray(indptr, _np.int64)),
                         shape=(len(idxs), self._n_feat))
        label = nd.array(self._labels[idxs])
        return DataBatch([csr], [label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


def _read_idx_ubyte(path):
    """Parse the MNIST IDX format (magic 0x801/0x803)."""
    import gzip
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    magic = int.from_bytes(raw[:4], "big")
    ndim = magic & 0xFF
    dims = [int.from_bytes(raw[4 + 4 * i:8 + 4 * i], "big")
            for i in range(ndim)]
    data = _np.frombuffer(raw, _np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


class MNISTIter(DataIter):
    """Batches over the classic MNIST idx-ubyte pair (reference:
    src/io/iter_mnist.cc MNISTIter — the v1.x `mx.io.MNISTIter` surface).

    ``flat=True`` yields (batch, 784) float rows scaled to [0,1);
    ``flat=False`` yields (batch, 1, 28, 28).  ``part_index``/``num_parts``
    shard for distributed training like the reference."""

    def __init__(self, image, label, batch_size=128, shuffle=False,
                 flat=False, seed=0, silent=True, num_parts=1, part_index=0,
                 **_kw):
        super().__init__(batch_size)
        images = _read_idx_ubyte(image).astype(_np.float32) / 255.0
        labels = _read_idx_ubyte(label).astype(_np.float32)
        if images.ndim != 3 or labels.ndim != 1 or                 images.shape[0] != labels.shape[0]:
            raise ValueError("not an MNIST idx pair: %r %r"
                             % (images.shape, labels.shape))
        images = images[part_index::num_parts]
        labels = labels[part_index::num_parts]
        self._flat = flat
        data = images.reshape(len(images), -1) if flat else             images[:, None, :, :]
        self._inner = NDArrayIter(
            data, labels, batch_size, shuffle=shuffle,
            last_batch_handle="pad", label_name="softmax_label")
        if not silent:
            print("MNISTIter: loaded %d images %s" % (len(images),
                                                      data.shape[1:]))

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class ImageRecordIter(DataIter):
    """.rec → decode → augment → NCHW batches (reference:
    src/io/iter_image_recordio_2.cc ImageRecordIOParser2::ParseNext).

    Decode+augment runs on ``preprocess_threads`` workers (PIL releases
    the GIL in its C codec); records are dealt round-robin into an order
    that is reshuffled per epoch when ``shuffle``.  ``part_index``/
    ``num_parts`` shard the record set for multi-host data parallelism,
    matching the reference's distributed slicing.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, label_width=1, shuffle=False,
                 rand_crop=False, rand_resize=False, rand_mirror=False,
                 mean_r=0, mean_g=0, mean_b=0, std_r=0, std_g=0, std_b=0,
                 resize=0, preprocess_threads=4, num_parts=1, part_index=0,
                 round_batch=True, seed=0, aug_list=None, dtype="float32",
                 **_kw):
        super().__init__(batch_size)
        from .. import recordio, image
        self._rec_path = path_imgrec
        self._idx_path = path_imgidx or path_imgrec[:-4] + ".idx"
        self._label_width = label_width
        self._dtype = _np.dtype(dtype)
        self.data_shape = tuple(data_shape)
        self._record = recordio.MXIndexedRecordIO(self._idx_path,
                                                  self._rec_path, "r")
        keys = self._record.keys
        if not keys:
            raise OSError("no .idx sidecar for %r — ImageRecordIter needs "
                          "indexed records" % path_imgrec)
        keys = keys[part_index::num_parts]  # distributed shard
        self._keys = _np.asarray(keys)
        self._shuffle = shuffle
        self._rng = _np.random.RandomState(seed)
        self._round_batch = round_batch
        mean = None
        std = None
        if mean_r or mean_g or mean_b:
            mean = _np.array([mean_r, mean_g, mean_b], _np.float32)
        if std_r or std_g or std_b:
            std = _np.array([std_r, std_g, std_b], _np.float32)
        if aug_list is None:
            aug_list = image.CreateAugmenter(
                data_shape=(3,) + tuple(data_shape[1:]), resize=resize,
                rand_crop=rand_crop, rand_resize=rand_resize,
                rand_mirror=rand_mirror, mean=mean, std=std)
        self._augs = aug_list
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self._lock = threading.Lock()  # recordio handle is stateful
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         self._dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._label_width == 1 else \
            (self.batch_size, self._label_width)
        return [DataDesc("softmax_label", shape, _np.float32)]

    def reset(self):
        self._order = self._keys.copy()
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0

    def _load_one(self, key):
        from .. import recordio as rio, image
        with self._lock:
            payload = self._record.read_idx(int(key))
        header, img_bytes = rio.unpack(payload)
        img = image.imdecode(img_bytes)
        for aug in self._augs:
            img = aug(img)
        arr = img.asnumpy()
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)  # HWC → CHW
        label = header.label
        if isinstance(label, _np.ndarray):
            label = label[:self._label_width]
        return arr, label

    def next(self) -> DataBatch:
        n = len(self._order)
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        pad = 0
        keys = self._order[self._cursor:min(end, n)]
        if end > n:
            pad = end - n
            if not self._round_batch:
                raise StopIteration
            keys = _np.concatenate([keys, self._order[:pad]])
        self._cursor = end
        results = list(self._pool.map(self._load_one, keys))
        data = _np.stack([r[0] for r in results]).astype(self._dtype,
                                                         copy=False)
        labels = _np.asarray([r[1] for r in results], _np.float32)
        return DataBatch(
            data=[nd.array(data, ctx=cpu(), dtype=data.dtype)],
            label=[nd.array(labels, ctx=cpu())],
            pad=pad, provide_data=self.provide_data,
            provide_label=self.provide_label)


class ImageRecordUInt8Iter(ImageRecordIter):
    """Reference: io.ImageRecordUInt8Iter — ImageRecordIter that hands
    out RAW uint8 pixels (no mean/std normalization), for pipelines that
    normalize on-device (e.g. the INT8 quantized path)."""

    def __init__(self, *args, **kwargs):
        for banned in ("mean_r", "mean_g", "mean_b",
                       "std_r", "std_g", "std_b"):
            if kwargs.pop(banned, 0):
                raise MXNetError(
                    "ImageRecordUInt8Iter hands out raw uint8 pixels; "
                    "%s is not applicable (normalize on-device)" % banned)
        if str(kwargs.pop("dtype", "uint8")) != "uint8":
            raise MXNetError(
                "ImageRecordUInt8Iter is uint8 by definition; use "
                "ImageRecordIter for other dtypes")
        kwargs["dtype"] = "uint8"
        super().__init__(*args, **kwargs)
