"""Sharded checkpoint / resume (SURVEY §5.4) + the failure posture (§5.3).

Reference: ``save_checkpoint``/``load_checkpoint`` (python/mxnet/model.py)
cover single-host artifacts — mx.model here does the same.  This module is
the part the reference lacks and a TPU pod needs: **sharded** checkpoints
of jitted training state (parallel.TrainStep params/opt_state living as
NamedSharding'd jax.Arrays across a Mesh), written/restored collectively
via orbax — every host writes only its shards, restore re-lays-out onto
whatever mesh the new job brings up (elastic re-sharding).

Failure posture (§5.3, documented contract): fail fast and restart from
the last checkpoint.  XLA collectives are SPMD — a lost host wedges the
step, so the job relies on (a) the launcher/scheduler restarting all
processes, and (b) ``CheckpointManager.latest_step()`` resume.  There is
deliberately NO in-band elastic shrink (the reference's dist_async had
none either); checkpoint frequency bounds lost work.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax

__all__ = ["save_sharded", "restore_sharded", "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_sharded(path: str, state: Any, force: bool = True) -> None:
    """Write a pytree of (possibly sharded) jax.Arrays collectively.

    Every process must call this with its view of the same global arrays;
    orbax writes one OCDBT store with each host's local shards.
    """
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(path), state, force=force)
    ckptr.wait_until_finished()


def restore_sharded(path: str, template: Optional[Any] = None,
                    shardings: Optional[Any] = None) -> Any:
    """Restore a pytree saved by save_sharded.

    template: a pytree of arrays or jax.ShapeDtypeStruct giving the target
    structure; pair it with ``shardings`` (a matching pytree of
    NamedSharding) to re-lay-out onto a NEW mesh — elastic restore onto a
    different topology than the one that saved.
    """
    ckptr = _checkpointer()
    path = os.path.abspath(path)
    if template is None:
        return ckptr.restore(path)
    return ckptr.restore(path, _restore_target(template, shardings))


def _restore_target(template, shardings):
    """Template pytree -> ShapeDtypeStruct target carrying the layout to
    restore onto (explicit shardings, else the template arrays' own)."""
    if shardings is not None:
        return jax.tree_util.tree_map(
            lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s),
            template, shardings)
    return jax.tree_util.tree_map(
        lambda t: jax.ShapeDtypeStruct(
            t.shape, t.dtype, sharding=getattr(t, "sharding", None)),
        template)


class CheckpointManager:
    """Step-numbered checkpoints with retention + latest-step resume
    (reference role: do_checkpoint(period) + auto-resume; here over
    sharded state)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                               create=True)
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None,
                shardings: Optional[Any] = None) -> Any:
        import orbax.checkpoint as ocp
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoints in %s" % self._dir)
        if template is None:
            return self._mgr.restore(step)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                _restore_target(template, shardings)))

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self):
        self._mgr.close()
