"""Sharded checkpoint / resume (SURVEY §5.4) + the failure posture (§5.3).

Reference: ``save_checkpoint``/``load_checkpoint`` (python/mxnet/model.py)
cover single-host artifacts — mx.model here does the same.  This module is
the part the reference lacks and a TPU pod needs: **sharded** checkpoints
of jitted training state (parallel.TrainStep params/opt_state living as
NamedSharding'd jax.Arrays across a Mesh), written/restored collectively
via orbax — every host writes only its shards, restore re-lays-out onto
whatever mesh the new job brings up (elastic re-sharding).

Failure posture (§5.3, documented contract): fail fast and restart from
the last checkpoint.  XLA collectives are SPMD — a lost host wedges the
step, so WITHIN one jitted world the job relies on (a) the
launcher/scheduler restarting all processes, and (b)
``CheckpointManager.latest_step()`` resume.  There is deliberately no
in-band shrink DURING a step; elastic membership (ISSUE 16) instead
resizes BETWEEN epochs, through this module — the supervisor quiesces
every rank at an epoch boundary, the checkpoint (params + optimizer
sidecar + per-leaf spec sidecar) is the hand-off artifact, and the
resized world restores it onto its new mesh via
``resume_or_init(mesh=...)``'s re-shard-by-axis-NAME path.  The
sidecar's ``world_size`` records how many processes wrote the
checkpoint, so a resumed job can tell a resize from a plain restart.
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Any, Callable, Optional, Tuple

import jax

__all__ = ["save_sharded", "restore_sharded", "CheckpointManager",
           "resume_or_init", "saved_specs", "saved_world_size",
           "shardings_from_saved"]

# per-leaf PartitionSpec sidecar (ISSUE 14): a sharded job's checkpoint
# records WHERE each leaf lived so a restore onto a NEW mesh re-shards
# by axis NAME — a dp×fsdp save resumes sharded on any mesh carrying an
# fsdp axis, and degrades to replicated on a plain-dp mesh, with no
# caller-side layout bookkeeping.  The sidecar is advisory metadata: a
# missing/stale one falls back to the restore template's own shardings.
SPEC_SCHEMA = 1
_SPEC_SIDECAR = ".speclayout.json"


def _spec_to_json(sharding) -> Optional[list]:
    """A NamedSharding's PartitionSpec as JSON entries (None | axis |
    [axes]); None for anything without a named spec."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    return out


def _sidecar_doc(state) -> dict:
    leaves = jax.tree_util.tree_leaves(state)
    mesh_axes = {}
    specs = []
    for leaf in leaves:
        sh = getattr(leaf, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and not mesh_axes:
            mesh_axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        specs.append(_spec_to_json(sh))
    try:
        world = int(jax.process_count())
    except Exception:
        world = 1
    return {"schema": SPEC_SCHEMA, "mesh_axes": mesh_axes,
            "leaf_specs": specs, "world_size": world}


def _sidecar_path(path: str) -> str:
    return os.path.abspath(path) + _SPEC_SIDECAR


def _write_sidecar(target: str, state) -> None:
    """Atomic (temp+rename) sidecar write; lead process only."""
    doc = _sidecar_doc(state)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.write("\n")
    os.replace(tmp, target)


def saved_specs(path: str) -> Optional[dict]:
    """The sidecar document saved next to checkpoint `path`, or None
    (absent / unreadable / wrong schema — every failure degrades to
    template-sharding restore, never an error)."""
    try:
        with open(_sidecar_path(path)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return _validate_sidecar(doc)


def saved_world_size(path: str) -> Optional[int]:
    """How many processes wrote checkpoint `path` (the sidecar's
    ``world_size``), or None when no/old sidecar exists.  An elastic
    resume compares it against the CURRENT world to tell a resize
    (re-shard, replan exchange layout) from a plain same-size restart."""
    doc = saved_specs(path)
    if doc is None:
        return None
    try:
        w = int(doc.get("world_size", 0))
    except (TypeError, ValueError):
        return None
    return w if w > 0 else None


def _spec_onto_mesh(entries, shape, mesh):
    """Rebuild one leaf's PartitionSpec onto a NEW mesh: axes are matched
    by NAME, and an axis the new mesh lacks (or that no longer divides
    the dimension) drops out — the elastic-restore contract."""
    from jax.sharding import PartitionSpec as P
    sizes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    out = []
    for dim, entry in zip(tuple(shape), tuple(entries or ())):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, list) else [entry]
        kept, whole = [], 1
        for a in axes:
            sz = sizes.get(str(a), 1)
            if sz > 1 and int(dim) % (whole * sz) == 0:
                kept.append(str(a))
                whole *= sz
        out.append(tuple(kept) if len(kept) > 1
                   else (kept[0] if kept else None))
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def _validate_sidecar(doc) -> Optional[dict]:
    """Schema gate shared by every sidecar reader: None on anything but
    a well-formed schema-1 document (degrade, never error)."""
    if not isinstance(doc, dict) or doc.get("schema") != SPEC_SCHEMA:
        return None
    if not isinstance(doc.get("leaf_specs"), list):
        return None
    return doc


def _shardings_from_doc(doc, template, mesh):
    """Per-leaf NamedShardings for `template` on `mesh` from a sidecar
    document — the one spec-rebuild loop every restore path shares.
    Leaves beyond the saved spec list (template grew) replicate."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    leaves, treedef = jax.tree_util.tree_flatten(template)
    specs = doc["leaf_specs"]
    out = []
    for i, leaf in enumerate(leaves):
        entries = specs[i] if i < len(specs) else None
        shape = tuple(getattr(leaf, "shape", ()) or ())
        spec = _spec_onto_mesh(entries, shape, mesh) \
            if entries else P()
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def shardings_from_saved(path: str, template, mesh):
    """Per-leaf NamedShardings for restoring checkpoint `path` onto
    `mesh`, from the saved sidecar; None when no sidecar exists (caller
    falls back to the template's own shardings)."""
    doc = saved_specs(path)
    if doc is None or mesh is None:
        return None
    return _shardings_from_doc(doc, template, mesh)

_TMP_MARK = ".saving-"      # in-progress save dir: <name>.saving-tmp
                            # (deterministic — every host of a collective
                            # save must hand orbax the SAME directory)


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_sharded(path: str, state: Any, force: bool = True) -> None:
    """Write a pytree of (possibly sharded) jax.Arrays collectively.

    Every process must call this with its view of the same global arrays;
    orbax writes one OCDBT store with each host's local shards.

    Crash-safe by construction (§5.3 failure posture): the tree is
    written to a sibling ``<name>.saving-tmp`` dir and renamed into
    place, so a process killed mid-save never loses the last restorable
    checkpoint — a kill during the write leaves ``path`` untouched, and
    a kill inside the two-rename commit leaves the previous checkpoint
    at ``<name>.replaced`` from which the next save/restore recovers
    automatically.  The ``checkpoint.commit`` fault site sits between
    write and rename for chaos tests to kill into.
    """
    from . import fault as _fault
    path = os.path.abspath(path)
    parent, name = os.path.split(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    _recover_commit(path)
    # force=False must fail BEFORE the (expensive, collective) write —
    # and on every host, or the lead's late error would strand the rest
    # in the commit barrier
    if not force and os.path.exists(path):
        raise FileExistsError("checkpoint %s exists (force=False)" % path)
    # the temp name is DETERMINISTIC so a multi-host job's processes all
    # hand orbax the same directory (the collective-save contract above);
    # process 0 alone performs the filesystem commit, with barriers
    # fencing the write and the rename
    nprocs = jax.process_count()
    is_lead = jax.process_index() == 0
    tmp = os.path.join(parent, name + _TMP_MARK + "tmp")
    old = os.path.join(parent, name + ".replaced")
    if is_lead:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        if os.path.exists(old):
            shutil.rmtree(old)
    _sync(nprocs, "mx_ckpt_pre_save")       # stale-tmp cleanup visible
    ckptr = _checkpointer()
    ckptr.save(tmp, state, force=True)
    ckptr.wait_until_finished()
    _sync(nprocs, "mx_ckpt_written")        # every host's shards are in
    # a kill landing here leaves `path` untouched — exactly the contract
    _fault.fire("checkpoint.commit")
    if is_lead:
        had_old = os.path.exists(path)
        if had_old:
            os.rename(path, old)
        os.rename(tmp, path)                # path momentarily absent: a
        if had_old:                         # kill here is healed by
            shutil.rmtree(old, ignore_errors=True)   # _recover_commit
        # per-leaf sharding sidecar (ISSUE 14): written AFTER the main
        # commit — a crash in between leaves a valid checkpoint whose
        # restore degrades to template shardings, never a torn one
        try:
            _write_sidecar(_sidecar_path(path), state)
        except OSError:
            pass        # advisory metadata only
    _sync(nprocs, "mx_ckpt_committed")      # rename visible everywhere


def _recover_commit(path: str) -> None:
    """Heal a crash inside save_sharded's two-rename commit window: if
    ``path`` is missing but ``<name>.replaced`` (the displaced previous
    checkpoint — known-complete) exists, put it back."""
    old = path + ".replaced"
    if not os.path.exists(path) and os.path.exists(old):
        try:
            os.rename(old, path)
        except OSError:
            pass            # a peer process won the recovery race


def _sync(nprocs: int, tag: str) -> None:
    if nprocs > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def restore_sharded(path: str, template: Optional[Any] = None,
                    shardings: Optional[Any] = None,
                    mesh: Optional[Any] = None) -> Any:
    """Restore a pytree saved by save_sharded.

    template: a pytree of arrays or jax.ShapeDtypeStruct giving the target
    structure; pair it with ``shardings`` (a matching pytree of
    NamedSharding) to re-lay-out onto a NEW mesh — elastic restore onto a
    different topology than the one that saved.

    ``mesh`` (without explicit ``shardings``) re-shards by NAME from the
    saved per-leaf spec sidecar: a dp×fsdp checkpoint restores sharded
    onto any mesh with an fsdp axis and replicated onto a plain-dp mesh
    (and vice versa — a replicated save restores replicated even onto a
    sharded-capable mesh unless the caller passes explicit shardings).
    """
    ckptr = _checkpointer()
    path = os.path.abspath(path)
    _recover_commit(path)       # heal a crash mid-commit before reading
    if template is None:
        return ckptr.restore(path)
    if shardings is None and mesh is not None:
        shardings = shardings_from_saved(path, template, mesh)
    return ckptr.restore(path, _restore_target(template, shardings))


def _restore_target(template, shardings):
    """Template pytree -> ShapeDtypeStruct target carrying the layout to
    restore onto (explicit shardings, else the template arrays' own)."""
    if shardings is not None:
        return jax.tree_util.tree_map(
            lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s),
            template, shardings)
    return jax.tree_util.tree_map(
        lambda t: jax.ShapeDtypeStruct(
            t.shape, t.dtype, sharding=getattr(t, "sharding", None)),
        template)


class CheckpointManager:
    """Step-numbered checkpoints with retention + latest-step resume
    (reference role: do_checkpoint(period) + auto-resume; here over
    sharded state)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                               create=True)
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()
        # sharding sidecar (ISSUE 14): one latest-wins document per
        # manager directory — resume_or_init(mesh=...) re-shards by name
        if jax.process_index() == 0:
            try:
                _write_sidecar(os.path.join(self._dir, "speclayout.json"),
                               state)
            except OSError:
                pass

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _saved_shardings(self, template, mesh):
        # path-based helper expects the sidecar SUFFIX convention; read
        # the manager-dir document directly, then share the one
        # validation + spec-rebuild implementation
        try:
            with open(os.path.join(self._dir, "speclayout.json")) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        doc = _validate_sidecar(doc)
        if doc is None or mesh is None:
            return None
        return _shardings_from_doc(doc, template, mesh)

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None,
                shardings: Optional[Any] = None,
                mesh: Optional[Any] = None) -> Any:
        import orbax.checkpoint as ocp
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoints in %s" % self._dir)
        if template is None:
            return self._mgr.restore(step)
        if shardings is None and mesh is not None:
            shardings = self._saved_shardings(template, mesh)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                _restore_target(template, shardings)))

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self):
        self._mgr.close()


def resume_or_init(directory: str, init_fn: Callable[[], Any], *,
                   shardings: Optional[Any] = None,
                   mesh: Optional[Any] = None,
                   max_to_keep: int = 3,
                   manager: Optional[CheckpointManager] = None,
                   ) -> Tuple[Any, int, CheckpointManager]:
    """The §5.3 recovery loop's entry point: restore the latest
    checkpoint if one exists, else build fresh state.

    ``init_fn`` constructs the fresh training state (a pytree of
    jax.Arrays); it always runs — its result is either returned as-is
    (cold start) or used as the restore template so arrays land with the
    new job's shapes/dtypes (pass ``shardings`` to re-lay-out onto a new
    mesh, or just ``mesh`` to re-shard by NAME from the saved per-leaf
    spec sidecar — a sharded job restores sharded, ISSUE 14).  Returns
    ``(state, start_step, manager)`` where
    ``start_step`` is 0 on a cold start and ``latest_step() + 1`` after
    a resume — drivers loop ``for step in range(start_step, total)`` and
    ``manager.save(step, state)`` periodically, and a crashed-and-
    restarted job continues where the last save left off.
    """
    mgr = manager or CheckpointManager(directory, max_to_keep=max_to_keep)
    state = init_fn()
    step = mgr.latest_step()
    if step is None:
        return state, 0, mgr
    restored = mgr.restore(step, template=state, shardings=shardings,
                           mesh=mesh)
    return restored, step + 1, mgr
