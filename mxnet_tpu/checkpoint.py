"""Sharded checkpoint / resume (SURVEY §5.4) + the failure posture (§5.3).

Reference: ``save_checkpoint``/``load_checkpoint`` (python/mxnet/model.py)
cover single-host artifacts — mx.model here does the same.  This module is
the part the reference lacks and a TPU pod needs: **sharded** checkpoints
of jitted training state (parallel.TrainStep params/opt_state living as
NamedSharding'd jax.Arrays across a Mesh), written/restored collectively
via orbax — every host writes only its shards, restore re-lays-out onto
whatever mesh the new job brings up (elastic re-sharding).

Failure posture (§5.3, documented contract): fail fast and restart from
the last checkpoint.  XLA collectives are SPMD — a lost host wedges the
step, so the job relies on (a) the launcher/scheduler restarting all
processes, and (b) ``CheckpointManager.latest_step()`` resume.  There is
deliberately NO in-band elastic shrink (the reference's dist_async had
none either); checkpoint frequency bounds lost work.
"""
from __future__ import annotations

import os
import shutil
from typing import Any, Callable, Optional, Tuple

import jax

__all__ = ["save_sharded", "restore_sharded", "CheckpointManager",
           "resume_or_init"]

_TMP_MARK = ".saving-"      # in-progress save dir: <name>.saving-tmp
                            # (deterministic — every host of a collective
                            # save must hand orbax the SAME directory)


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_sharded(path: str, state: Any, force: bool = True) -> None:
    """Write a pytree of (possibly sharded) jax.Arrays collectively.

    Every process must call this with its view of the same global arrays;
    orbax writes one OCDBT store with each host's local shards.

    Crash-safe by construction (§5.3 failure posture): the tree is
    written to a sibling ``<name>.saving-tmp`` dir and renamed into
    place, so a process killed mid-save never loses the last restorable
    checkpoint — a kill during the write leaves ``path`` untouched, and
    a kill inside the two-rename commit leaves the previous checkpoint
    at ``<name>.replaced`` from which the next save/restore recovers
    automatically.  The ``checkpoint.commit`` fault site sits between
    write and rename for chaos tests to kill into.
    """
    from . import fault as _fault
    path = os.path.abspath(path)
    parent, name = os.path.split(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    _recover_commit(path)
    # force=False must fail BEFORE the (expensive, collective) write —
    # and on every host, or the lead's late error would strand the rest
    # in the commit barrier
    if not force and os.path.exists(path):
        raise FileExistsError("checkpoint %s exists (force=False)" % path)
    # the temp name is DETERMINISTIC so a multi-host job's processes all
    # hand orbax the same directory (the collective-save contract above);
    # process 0 alone performs the filesystem commit, with barriers
    # fencing the write and the rename
    nprocs = jax.process_count()
    is_lead = jax.process_index() == 0
    tmp = os.path.join(parent, name + _TMP_MARK + "tmp")
    old = os.path.join(parent, name + ".replaced")
    if is_lead:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        if os.path.exists(old):
            shutil.rmtree(old)
    _sync(nprocs, "mx_ckpt_pre_save")       # stale-tmp cleanup visible
    ckptr = _checkpointer()
    ckptr.save(tmp, state, force=True)
    ckptr.wait_until_finished()
    _sync(nprocs, "mx_ckpt_written")        # every host's shards are in
    # a kill landing here leaves `path` untouched — exactly the contract
    _fault.fire("checkpoint.commit")
    if is_lead:
        had_old = os.path.exists(path)
        if had_old:
            os.rename(path, old)
        os.rename(tmp, path)                # path momentarily absent: a
        if had_old:                         # kill here is healed by
            shutil.rmtree(old, ignore_errors=True)   # _recover_commit
    _sync(nprocs, "mx_ckpt_committed")      # rename visible everywhere


def _recover_commit(path: str) -> None:
    """Heal a crash inside save_sharded's two-rename commit window: if
    ``path`` is missing but ``<name>.replaced`` (the displaced previous
    checkpoint — known-complete) exists, put it back."""
    old = path + ".replaced"
    if not os.path.exists(path) and os.path.exists(old):
        try:
            os.rename(old, path)
        except OSError:
            pass            # a peer process won the recovery race


def _sync(nprocs: int, tag: str) -> None:
    if nprocs > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def restore_sharded(path: str, template: Optional[Any] = None,
                    shardings: Optional[Any] = None) -> Any:
    """Restore a pytree saved by save_sharded.

    template: a pytree of arrays or jax.ShapeDtypeStruct giving the target
    structure; pair it with ``shardings`` (a matching pytree of
    NamedSharding) to re-lay-out onto a NEW mesh — elastic restore onto a
    different topology than the one that saved.
    """
    ckptr = _checkpointer()
    path = os.path.abspath(path)
    _recover_commit(path)       # heal a crash mid-commit before reading
    if template is None:
        return ckptr.restore(path)
    return ckptr.restore(path, _restore_target(template, shardings))


def _restore_target(template, shardings):
    """Template pytree -> ShapeDtypeStruct target carrying the layout to
    restore onto (explicit shardings, else the template arrays' own)."""
    if shardings is not None:
        return jax.tree_util.tree_map(
            lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s),
            template, shardings)
    return jax.tree_util.tree_map(
        lambda t: jax.ShapeDtypeStruct(
            t.shape, t.dtype, sharding=getattr(t, "sharding", None)),
        template)


class CheckpointManager:
    """Step-numbered checkpoints with retention + latest-step resume
    (reference role: do_checkpoint(period) + auto-resume; here over
    sharded state)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                               create=True)
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def save(self, step: int, state: Any) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None,
                shardings: Optional[Any] = None) -> Any:
        import orbax.checkpoint as ocp
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoints in %s" % self._dir)
        if template is None:
            return self._mgr.restore(step)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(
                _restore_target(template, shardings)))

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self):
        self._mgr.close()


def resume_or_init(directory: str, init_fn: Callable[[], Any], *,
                   shardings: Optional[Any] = None,
                   max_to_keep: int = 3,
                   manager: Optional[CheckpointManager] = None,
                   ) -> Tuple[Any, int, CheckpointManager]:
    """The §5.3 recovery loop's entry point: restore the latest
    checkpoint if one exists, else build fresh state.

    ``init_fn`` constructs the fresh training state (a pytree of
    jax.Arrays); it always runs — its result is either returned as-is
    (cold start) or used as the restore template so arrays land with the
    new job's shapes/dtypes (pass ``shardings`` to re-lay-out onto a new
    mesh).  Returns ``(state, start_step, manager)`` where
    ``start_step`` is 0 on a cold start and ``latest_step() + 1`` after
    a resume — drivers loop ``for step in range(start_step, total)`` and
    ``manager.save(step, state)`` periodically, and a crashed-and-
    restarted job continues where the last save left off.
    """
    mgr = manager or CheckpointManager(directory, max_to_keep=max_to_keep)
    state = init_fn()
    step = mgr.latest_step()
    if step is None:
        return state, 0, mgr
    restored = mgr.restore(step, template=state, shardings=shardings)
    return restored, step + 1, mgr
