"""mx.callback — training-loop callbacks.

Reference: ``python/mxnet/callback.py`` (Speedometer, do_checkpoint,
log_train_metric, ProgressBar) — the furniture every reference training
script wires into ``Module.fit``/``batch_end_callback``.
"""
from __future__ import annotations

import logging
import math
import time

__all__ = ["Speedometer", "ProgressBar", "do_checkpoint",
           "log_train_metric", "LogValidationMetricsCallback"]


class Speedometer:
    """Log samples/sec (and metrics) every `frequent` batches (reference:
    callback.Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s"
                    logging.info(msg, param.epoch, count, speed,
                                 "\t".join("%s=%f" % kv for kv in name_value))
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Text progress bar per batch (reference: callback.ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving `prefix-symbol.json` +
    `prefix-%04d.params` (reference: callback.do_checkpoint)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the running metric (reference:
    callback.log_train_metric)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            logging.info("Iter[%d] Batch[%d] Train-%s", param.epoch,
                         param.nbatch,
                         "\t".join("%s=%f" % kv for kv in name_value))
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class LogValidationMetricsCallback:
    """Eval-end callback (reference: callback.LogValidationMetricsCallback).
    """

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Reference: callback.module_checkpoint — epoch-end callback that
    checkpoints a Module (symbol + params, optionally optimizer
    states)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback
