"""BucketingModule: variable-length-sequence execution over shared params.

Reference: python/mxnet/module/bucketing_module.py (class BucketingModule)
— the reference's answer to variable-length sequences (example/rnn rides
it): one Module per bucket key, all binding the SAME parameter arrays, so
any bucket's update advances the single shared model.

TPU realization (SURVEY.md hard part 3): each bucket is a separate bound
Module whose static shapes compile once into the per-op jit cache — the
"bucketed jit caches" design: switching buckets switches executables, it
never retraces an existing one.  Parameter sharing is by NDArray identity
(same underlying device buffer), the rebuild's equivalent of the
reference's shared_module memory sharing.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

from ..base import MXNetError
from .. import initializer as init_mod
from . import BaseModule, Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """Reference: BucketingModule(sym_gen, default_bucket_key, ...).

    ``sym_gen(bucket_key) -> (symbol, data_names, label_names)``."""

    def __init__(self, sym_gen: Callable, default_bucket_key=None,
                 logger=logging, context=None, fixed_param_names=None):
        super().__init__(logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets: Dict = {}
        self._curr_module: Optional[Module] = None
        self._curr_bucket_key = None
        self._monitor = None
        self._for_training = True
        self._grad_req = "write"

    # -- properties ---------------------------------------------------------
    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def data_names(self):
        return self._curr_module.data_names if self.binded else \
            self._sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        return self._curr_module.output_names if self.binded else \
            self._sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    @property
    def buckets(self):
        """bucket_key -> bound Module (one compiled executable set each)."""
        return self._buckets

    # -- bind / switch ------------------------------------------------------
    def _make_module(self, bucket_key) -> Module:
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names=data_names,
                      label_names=label_names, logger=self.logger,
                      context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        """Bind the DEFAULT bucket (reference: BucketingModule.bind)."""
        if self.binded and not force_rebind:
            return
        if force_rebind:
            self._buckets = {}
        self._for_training = for_training
        self._grad_req = grad_req
        self._inputs_need_grad = inputs_need_grad
        module = self._make_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, grad_req=grad_req)
        self._buckets[self._default_bucket_key] = module
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def _share_params(self, child: Module) -> None:
        """Point the child's parameter (and grad) buffers at the master's —
        NDArray identity is buffer identity, so one update serves all
        buckets (the reference's shared_module)."""
        master = self._buckets[self._default_bucket_key]
        mexec, cexec = master._exec, child._exec
        for name in child._param_names:
            if name not in mexec.arg_dict:
                raise MXNetError(
                    "bucket introduces parameter %r absent from the default "
                    "bucket — sym_gen must produce a shape-compatible "
                    "parameter set (reference requirement)" % name)
            if mexec.arg_dict[name].shape != cexec.arg_dict[name].shape:
                raise MXNetError(
                    "parameter %r changes shape across buckets: %s vs %s"
                    % (name, mexec.arg_dict[name].shape,
                       cexec.arg_dict[name].shape))
            cexec.arg_dict[name] = mexec.arg_dict[name]
            if name in mexec.grad_dict and name in cexec.grad_dict:
                cexec.grad_dict[name] = mexec.grad_dict[name]
        for name in child._aux_names:
            if name in mexec.aux_dict:
                cexec.aux_dict[name] = mexec.aux_dict[name]
        # one optimizer/updater instance across buckets (shared state),
        # applied over the MASTER's param order so state indices agree
        child._param_names = list(master._param_names)
        child._optimizer = master._optimizer
        child._updater = master._updater
        child.optimizer_initialized = master.optimizer_initialized
        child.params_initialized = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Reference: BucketingModule.switch_bucket — bind-once per key,
        then O(1) switches (each key keeps its own compiled executables)."""
        assert self.binded, "call bind before switch_bucket"
        if bucket_key not in self._buckets:
            module = self._make_module(bucket_key)
            module.bind(data_shapes, label_shapes, self._for_training,
                        self._inputs_need_grad, grad_req=self._grad_req)
            self._share_params(module)
            if self._monitor is not None:
                # late-created buckets get the monitor too (reference
                # re-installs in switch_bucket)
                module.install_monitor(self._monitor)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    # -- params / optimizer (delegate to the default bucket) ---------------
    def init_params(self, initializer=init_mod.Uniform(0.01),
                    arg_params=None, aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        assert self.binded
        if arg_params is None and aux_params is None and not force_init \
                and getattr(self, "_preloaded_params", None):
            # one-shot install of checkpoint params from load();
            # force_init or explicit params always win, and the preload
            # is consumed so later re-inits behave normally
            arg_params, aux_params = self._preloaded_params
            self._preloaded_params = None
        self._buckets[self._default_bucket_key].init_params(
            initializer, arg_params, aux_params, allow_missing, force_init,
            allow_extra)
        self.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self._buckets[self._default_bucket_key].set_params(
            arg_params, aux_params, allow_missing, force_init, allow_extra)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        master = self._buckets[self._default_bucket_key]
        master.init_optimizer(kvstore, optimizer, optimizer_params,
                              force_init)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                mod._optimizer = master._optimizer
                mod._updater = master._updater
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    # -- compute (delegate to the current bucket) ---------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        key = getattr(data_batch, "bucket_key", None)
        if key is not None and key != self._curr_bucket_key:
            data_shapes = getattr(data_batch, "provide_data", None)
            label_shapes = getattr(data_batch, "provide_label", None)
            self.switch_bucket(key, data_shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self):
        return self._curr_module.get_outputs()

    def get_input_grads(self):
        return self._curr_module.get_input_grads()

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, monitor):
        self._monitor = monitor
        for mod in self._buckets.values():
            mod.install_monitor(monitor)

    # -- checkpoints --------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Reference: BucketingModule.save_checkpoint — the DEFAULT
        bucket's symbol + the shared params (all buckets alias them)."""
        assert self.binded and self.params_initialized
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)

    @staticmethod
    def load(prefix, epoch, sym_gen, default_bucket_key,
             logger=logging, context=None, fixed_param_names=None,
             load_optimizer_states=False):
        """Reference: BucketingModule.load — rebuild from sym_gen and a
        Module-format checkpoint; params install at bind+init time."""
        if load_optimizer_states:
            raise MXNetError(
                "BucketingModule.load(load_optimizer_states=True) is not "
                "supported: restore trainer state via "
                "init_optimizer + updater.set_states after binding")
        from ..model import load_checkpoint
        _sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        bm = BucketingModule(sym_gen, default_bucket_key, logger=logger,
                             context=context,
                             fixed_param_names=fixed_param_names)
        bm._preloaded_params = (arg_params, aux_params)
        return bm
