"""mx.mod — the v1.x Module API over the symbol executor.

Reference: ``python/mxnet/module/module.py`` (class Module — bind,
init_params, init_optimizer, forward/backward/update, fit/score/predict,
save_checkpoint/Module.load) and ``base_module.py`` (the fit loop).

TPU-first notes: the reference Module owns executor groups over GPU lists
and a kvstore; here the bound Executor evaluates the symbol DAG through
the per-op jit cache on the chosen context, and the *output-layer loss
gradients* (SoftmaxOutput & friends compute their loss gradient in-op in
the reference: ``src/operator/softmax_output.cc``) are injected as head
cotangents so the tape reproduces exactly ``(p - onehot)``-style grads.
Multi-device data parallelism belongs to ``parallel.TrainStep``/Gluon
Trainer in this rebuild; Module executes on its first context and is the
compatibility surface for v1.x-era scripts (checkpoints interchange via
mx.model).
"""
from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax
import jax.numpy as jnp

from .. import fault as _fault
from .. import telemetry as _telemetry
from ..base import MXNetError, get_env
from ..device import Context, cpu, current_context
from .. import initializer as init_mod
from .. import metric as metric_mod
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt_mod
from ..io import DataDesc, DataBatch
from ..model import BatchEndParam, save_checkpoint, load_checkpoint

__all__ = ["BaseModule", "Module", "BucketingModule"]


def _as_descs(shapes) -> List[DataDesc]:
    out = []
    for s in shapes or []:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], s[1]
            out.append(DataDesc(name, shape, *s[2:]))
    return out


# Loss-output heads (reference: src/operator/softmax_output.cc etc. compute
# their loss gradient in-op).  Module binds the executor over the *backbone*
# — the loss op's input z becomes the head — applies the output transform
# itself, and injects the exact reference gradient w.r.t. z as the backward
# cotangent.  This sidesteps inverting the op's vjp, which zeroes out at
# saturation (sigmoid(z)→1 makes the p(1-p) factor exactly 0 in fp32).

def _attr_f(attrs, key, default):
    v = attrs.get(key, default)
    return float(v) if not isinstance(v, bool) else v


def _attr_b(attrs, key, default=False):
    v = attrs.get(key, default)
    if isinstance(v, str):
        return v in ("1", "True", "true")
    return bool(v)


# Each head rule has a PURE jnp core (usable inside the whole-graph jit)
# and an NDArray wrapper for the eager executor path.


def _softmax_core(zj, yj, attrs):
    """ND softmax head: class axis 1 when multi_output (reference layout
    (B, C, d1..)), else last; integer labels of any matching shape;
    use_ignore/ignore_label mask + 'valid' normalization honored."""
    scale = _attr_f(attrs, "grad_scale", 1.0)
    axis = 1 if _attr_b(attrs, "multi_output") else -1
    zm = jnp.moveaxis(zj, axis, -1)               # classes last
    p = jax.nn.softmax(zm, axis=-1)
    out = jnp.moveaxis(p, -1, axis)
    if yj is None:
        return out, None
    yi = yj.astype(jnp.int32).reshape(zm.shape[:-1])
    onehot = jax.nn.one_hot(yi, zm.shape[-1], dtype=p.dtype)
    g = p - onehot
    norm = attrs.get("normalization", "null")
    if _attr_b(attrs, "use_ignore"):
        valid = (yi != int(_attr_f(attrs, "ignore_label", -1.0)))
        g = g * valid[..., None]
        if norm == "valid":
            scale = scale / jnp.maximum(valid.sum(), 1)
    elif norm == "valid":
        scale = scale / yi.size
    if norm == "batch":
        scale = scale / yi.shape[0]
    return out, jnp.moveaxis(g * scale, -1, axis)


def _linreg_core(zj, yj, attrs):
    if yj is None:
        return zj, None
    scale = _attr_f(attrs, "grad_scale", 1.0)
    return zj, (zj - yj.reshape(zj.shape)) * scale


def _maereg_core(zj, yj, attrs):
    if yj is None:
        return zj, None
    scale = _attr_f(attrs, "grad_scale", 1.0)
    return zj, jnp.sign(zj - yj.reshape(zj.shape)) * scale


def _logreg_core(zj, yj, attrs):
    scale = _attr_f(attrs, "grad_scale", 1.0)
    p = jax.nn.sigmoid(zj)
    if yj is None:
        return p, None
    return p, (p - yj.reshape(zj.shape)) * scale


def _wrap_rule(core):
    def rule(z, y, attrs):
        out, g = core(z._jax, None if y is None else y._jax, attrs)
        out_nd = nd.from_jax(out, ctx=z.context)
        return out_nd, (None if g is None
                        else nd.from_jax(g, ctx=z.context))
    return rule


_softmax_rule = _wrap_rule(_softmax_core)
_linreg_rule = _wrap_rule(_linreg_core)
_maereg_rule = _wrap_rule(_maereg_core)
_logreg_rule = _wrap_rule(_logreg_core)

_RULE_CORES = {
    "SoftmaxOutput": _softmax_core,
    "LinearRegressionOutput": _linreg_core,
    "MAERegressionOutput": _maereg_core,
    "LogisticRegressionOutput": _logreg_core,
}


# shape-only ops a label may pass through between its variable and the
# loss head (replayed on the fed array): reshape/flatten family
_LABEL_SHAPE_OPS = {"reshape", "Reshape", "_npi_reshape", "_np_reshape",
                    "Flatten", "flatten", "expand_dims", "squeeze"}


def _trace_label_var(node):
    chain = []
    while node.op in _LABEL_SHAPE_OPS and len(node.inputs) == 1:
        chain.append((node.op, dict(node.attrs)))
        node = node.inputs[0][0]
    if node.op == "null":
        return node.name, tuple(reversed(chain))
    # untraceable label subgraph: keep the op-node name so the positional
    # fallback stays DISABLED and a missing feed errors loudly
    return node.name, ()


_HEAD_RULES = {
    "SoftmaxOutput": _softmax_rule,
    "LinearRegressionOutput": _linreg_rule,
    "MAERegressionOutput": _maereg_rule,
    "LogisticRegressionOutput": _logreg_rule,
}


class BaseModule:
    """Reference: module/base_module.py — shared fit/score/predict loops."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.for_training = False

    # subclass surface ------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    # shared loops ----------------------------------------------------------
    def forward_backward(self, data_batch):
        # step-phase spans (ISSUE 8): dispatch-time only — forward/
        # backward enqueue async XLA work, the span never syncs it
        with _telemetry.phase("forward"):
            self.forward(data_batch, is_train=True)
        with _telemetry.phase("backward"):
            self.backward()

    def _compiled_fit_batch(self, data_batch, eval_metric):
        """Whole-step-compiled fit iteration (MX_STEP_COMPILE=1): run
        forward+backward+update+metric as one dispatch and return True,
        or return False to run the classic eager body.  Base modules
        (FeedForward) have no compiled lane."""
        return False

    def _named_update_grads(self):
        """(name, grad NDArray) pairs the next update() will apply —
        what health.GradientGuard scans for NaN/Inf.  Module exposes its
        executor's grad_dict; BucketingModule delegates to the bucket
        currently bound."""
        exec_ = getattr(self, "_exec", None)
        if exec_ is None:
            cur = getattr(self, "_curr_module", None)
            return cur._named_update_grads() if cur is not None else []
        return [(n, g) for n, g in exec_.grad_dict.items()
                if g is not None]

    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0, batch_end_callback=None):
        """Reference: BaseModule.score."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric, locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        """Reference: BaseModule.predict — concatenated outputs."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            if batch.pad:
                outs = [o[:o.shape[0] - batch.pad] for o in outs]
            outputs.append([o.copy() for o in outs])
        if not outputs:
            return []
        merged = [nd.concatenate([b[i] for b in outputs], axis=0)
                  for i in range(len(outputs[0]))]
        return merged[0] if len(merged) == 1 else merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None,
            arg_params=None, aux_params=None, allow_missing=False,
            force_init=False, begin_epoch=0, num_epoch=None,
            validation_metric=None, monitor=None,
            checkpoint_dir=None, checkpoint_period=1, auto_resume=True):
        """The reference training loop (reference: BaseModule.fit).

        Fault tolerance (§5.3 failure posture): pass ``checkpoint_dir``
        to install periodic crash-safe checkpointing — every
        ``checkpoint_period`` epochs the params land in a step-numbered
        :class:`~mxnet_tpu.checkpoint.CheckpointManager` store, and with
        ``auto_resume=True`` (default) a restarted job picks up from
        ``latest_step() + 1`` instead of epoch 0, so a crash costs at
        most ``checkpoint_period`` epochs of work.

        Health guards (:mod:`mxnet_tpu.health`, env-armed): the loop
        installs ``StepGuard.from_env()`` — ``MX_NAN_POLICY`` scans each
        step's gradients before update (``skip_batch`` drops poisoned
        updates so the params stay finite), ``MX_STEP_TIMEOUT`` arms a
        hung-step watchdog that dumps thread stacks and exits nonzero
        for the launch.py supervisor to restart, and
        ``MX_HEARTBEAT_FILE`` keeps a per-rank liveness file fresh every
        batch.  The per-batch ``worker.step`` fault site is what
        ``launch.py --fault 'worker.step:crash:after=N'`` chaos specs
        kill into.
        """
        assert num_epoch is not None, "please specify number of epochs"
        initializer = initializer or init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        ckpt_mgr = None
        if checkpoint_dir is not None:
            from ..checkpoint import CheckpointManager
            ckpt_mgr = CheckpointManager(checkpoint_dir)
            if auto_resume:
                latest = ckpt_mgr.latest_step()
                if latest is not None:
                    arg, aux = self.get_params()
                    template = {"arg": {k: v._jax for k, v in arg.items()},
                                "aux": {k: v._jax for k, v in aux.items()}}
                    restored = ckpt_mgr.restore(latest, template=template)
                    self.set_params(
                        {k: NDArray(v) for k, v in restored["arg"].items()},
                        {k: NDArray(v) for k, v in restored["aux"].items()},
                        force_init=True)
                    # optimizer slot state (momentum/Adam moments) rides
                    # in a sidecar so the resumed trajectory matches an
                    # uninterrupted run, not a cold optimizer restart
                    states = _read_opt_states(checkpoint_dir, latest)
                    if states is not None and \
                            getattr(self, "_updater", None) is not None:
                        self._updater.set_states(states)
                    begin_epoch = max(begin_epoch, latest + 1)
                    self.logger.info(
                        "fit: auto-resumed from checkpoint epoch %d; "
                        "starting at epoch %d", latest, begin_epoch)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        if not isinstance(validation_metric, metric_mod.EvalMetric):
            validation_metric = metric_mod.create(validation_metric)

        from ..health import StepGuard
        guard = StepGuard.from_env(logger=self.logger)
        # elastic drain (ISSUE 16): under MX_ELASTIC the supervisor's
        # resize path SIGTERMs every worker — the handler only sets a
        # flag, and the epoch loop quiesces at its next epoch BOUNDARY
        # (checkpoint + optimizer sidecar saved, then exit 0) so the
        # respawned world resumes the exact trajectory.  The rank does
        # NOT send LEAVE here: a drained rank usually comes straight
        # back under the same rank id (restart or resize survivor), and
        # membership departure is the supervisor's call — it LEAVEs
        # only the ranks the new world size actually removed.
        drain_flag = None
        drain_armed = False
        prev_sigterm = None
        if get_env("MX_ELASTIC", 0, int):
            import signal as _signal
            import threading as _threading
            if _threading.current_thread() is _threading.main_thread():
                drain_flag = _threading.Event()

                def _on_sigterm(signum, frame):
                    drain_flag.set()
                prev_sigterm = _signal.signal(_signal.SIGTERM,
                                              _on_sigterm)
                drain_armed = True
        try:
            self._fit_epochs(
                train_data, eval_data, eval_metric, validation_metric,
                begin_epoch, num_epoch, monitor=monitor, guard=guard,
                ckpt_mgr=ckpt_mgr, checkpoint_dir=checkpoint_dir,
                checkpoint_period=checkpoint_period,
                batch_end_callback=batch_end_callback,
                epoch_end_callback=epoch_end_callback,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                drain_flag=drain_flag)
        except BaseException as e:
            # flight recorder (ISSUE 8): a fit loop dying for ANY reason
            # — injected crash (nonzero SystemExit), NaN raise, OOM,
            # data error — leaves its last MX_TELEMETRY_RING step
            # records in MX_CRASH_DIR before the exception propagates.
            # SystemExit(0) is the elastic drain's clean quiesce, not a
            # death — no crash record for it.
            if not (isinstance(e, SystemExit) and not e.code):
                _telemetry.dump_crash("fit: %r" % (e,))
            raise
        finally:
            if drain_armed:
                import signal as _signal
                _signal.signal(_signal.SIGTERM, prev_sigterm)
            if guard.skipped_batches:
                self.logger.warning(
                    "fit: skipped %d poisoned batch update(s) "
                    "(MX_NAN_POLICY=skip_batch)", guard.skipped_batches)
            guard.close()

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, begin_epoch, num_epoch, *,
                    monitor, guard, ckpt_mgr, checkpoint_dir,
                    checkpoint_period, batch_end_callback,
                    epoch_end_callback, eval_end_callback,
                    eval_batch_end_callback, drain_flag=None):
        from ..step import step_compile_enabled
        # whole-step compiled lane (ISSUE 7): fwd+bwd+fused update+
        # metric accumulate in ONE donated jit per batch.  The eager body
        # remains the debug path — per-node monitors and the NaN grad
        # guard need materialized per-step gradients, so they keep it.
        use_compiled = step_compile_enabled() and monitor is None and \
            guard.grad_guard is None
        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            batches = iter(train_data)
            nbatch = -1
            while True:
                # data_wait phase (ISSUE 8): time spent blocked on the
                # input pipeline — the one step phase that is HOST wait
                # by definition, so an input-bound run shows up as a fat
                # data_wait bar instead of vanishing into "forward"
                with _telemetry.phase("data_wait"):
                    data_batch = next(batches, None)
                if data_batch is None:
                    break
                nbatch += 1
                guard.batch_start()
                # chaos site: launch.py --fault 'worker.step:crash:
                # after=N' (or a delay spec the watchdog converts into a
                # restart) kills the rank on an exact batch ordinal; the
                # watchdog is armed first so an injected hang here is
                # detected like any mid-step wedge
                _fault.fire("worker.step")
                if monitor is not None:
                    monitor.tic()
                if not (use_compiled and
                        self._compiled_fit_batch(data_batch, eval_metric)):
                    self.forward_backward(data_batch)
                    # the grad scan is built only when a NaN policy is
                    # armed — an unconfigured run pays one attribute
                    # check here
                    if guard.grad_guard is None or \
                            guard.allow_update(self._named_update_grads()):
                        self.update()
                    elif getattr(self, "_grad_req", None) == "add":
                        # skipped batch under accumulating gradients:
                        # purge the poisoned sums, or the NaN would infect
                        # every later backward's += and freeze training
                        # silently
                        for _n, g in self._named_update_grads():
                            g._set_jax(jnp.zeros_like(g._jax))
                    self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                guard.batch_end(epoch, nbatch)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(BatchEndParam(epoch, nbatch, eval_metric,
                                         locals()))
            guard.epoch_end(epoch)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            # chaos hook: tests kill the loop here to exercise resume
            _fault.fire("module.fit.epoch")
            # elastic drain: a SIGTERM seen mid-epoch quiesces HERE —
            # the epoch boundary — and forces a checkpoint regardless
            # of checkpoint_period, so the resized world loses nothing
            draining = drain_flag is not None and drain_flag.is_set()
            if ckpt_mgr is not None and (
                    draining
                    or (epoch + 1) % max(1, checkpoint_period) == 0
                    or epoch == num_epoch - 1):
                arg, aux = self.get_params()
                ckpt_mgr.save(epoch,
                              {"arg": {k: v._jax for k, v in arg.items()},
                               "aux": {k: v._jax for k, v in aux.items()}})
                if getattr(self, "_updater", None) is not None:
                    _write_opt_states(checkpoint_dir, epoch,
                                      self._updater.get_states(False),
                                      keep=ckpt_mgr.all_steps())
            if draining:
                self.logger.info(
                    "fit: elastic drain - checkpointed epoch %d, "
                    "exiting 0 for the supervisor to resize/respawn",
                    epoch)
                raise SystemExit(0)
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg, aux)
            if eval_data is not None:
                # reference contract: eval_BATCH_end fires per eval batch,
                # eval_end fires ONCE per evaluation with final metrics
                res = self.score(eval_data, validation_metric, epoch=epoch,
                                 batch_end_callback=eval_batch_end_callback)
                if eval_end_callback is not None:
                    for cb in _as_list(eval_end_callback):
                        cb(BatchEndParam(epoch, 0, validation_metric,
                                         locals()))
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)


def _module_census_arrays(mod):
    """A bound Module's parameter/aux/grad device buffers for the
    buffer census ("params" owner; data/label slots stay unclaimed)."""
    ex = getattr(mod, "_exec", None)
    if ex is None:
        return []
    out = []
    for name in getattr(mod, "_param_names", ()) or ():
        for store in (ex.arg_dict, ex.grad_dict):
            a = getattr(store.get(name), "_jax", None)
            if a is not None:
                out.append(a)
    for name in getattr(mod, "_aux_names", ()) or ():
        a = getattr(ex.aux_dict.get(name), "_jax", None)
        if a is not None:
            out.append(a)
    return out


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def _opt_states_path(directory, epoch):
    return os.path.join(directory, "optstate-%d.bin" % epoch)


def _write_opt_states(directory, epoch, blob, keep=()):
    """Crash-safe optimizer-state sidecar next to the orbax step dirs
    (write sibling + rename), pruned to the manager's retained steps."""
    path = _opt_states_path(directory, epoch)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    retained = set(keep) | {epoch}
    for entry in os.listdir(directory):
        if entry.startswith("optstate-") and entry.endswith(".bin"):
            try:
                step = int(entry[len("optstate-"):-len(".bin")])
            except ValueError:
                continue
            if step not in retained:
                try:
                    os.remove(os.path.join(directory, entry))
                except OSError:
                    pass


def _read_opt_states(directory, epoch):
    path = _opt_states_path(directory, epoch)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return f.read()


class Module(BaseModule):
    """Reference: module/module.py (class Module)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger)
        if isinstance(context, (list, tuple)):
            if len(context) > 1:
                logger.warning(
                    "Module executes on %s; multi-device data parallelism "
                    "is parallel.TrainStep's job in this rebuild", context[0])
            context = context[0] if context else None
        self._context = context or current_context()
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        # split heads into (backbone head, loss rule): the executor runs the
        # backbone; loss-output forward transforms + exact grads are ours
        from ..symbol import Symbol as _Sym
        self._head_rules = []
        exec_heads = []
        for node, idx in symbol._heads:
            rule = _HEAD_RULES.get(node.op)
            if rule is not None:
                exec_heads.append(node.inputs[0])
                # label bound by VARIABLE NAME (node.inputs[1]), not head
                # position — multi-head models feed each head its own
                # label.  A chain of shape-only ops between the variable
                # and the head (the classic Reshape(label, (-1,)) in
                # bucketing LMs) is traced through and replayed on the
                # fed array at forward time.
                label_name, label_chain = (None, ())
                if len(node.inputs) > 1:
                    label_name, label_chain = _trace_label_var(
                        node.inputs[1][0])
                self._head_rules.append((rule, node.attrs, label_name,
                                         label_chain))
            else:
                exec_heads.append((node, idx))
                self._head_rules.append(None)
        self._exec_symbol = _Sym(exec_heads)
        # loss-head label variables are labels even when not declared in
        # label_names (they're stripped with their head from the backbone)
        head_labels = {r[2] for r in self._head_rules
                       if r is not None and r[2] is not None}
        self._param_names = [n for n in self._param_names
                             if n not in head_labels]
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._data_shapes = None
        self._label_shapes = None
        # whole-graph jit fast path (reference role: GraphExecutor
        # compiles the graph once; None = untried, False = not jittable)
        self._jit_step = {}
        self._fast_grads = None
        self._jit_ok = None
        # MX_STEP_COMPILE lane: fwd+bwd+fused update+metric as ONE jit;
        # _compiled_owned tracks the arrays the lane's own dispatches
        # produced — only those may be donated (foreign arrays can be
        # aliased by shared modules / set_params sources and must be
        # copied before donation)
        self._compiled_fit = {}
        self._compiled_owned: set = set()
        self._compiled_owned_refs: list = []

    # -- properties ---------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, o.shape) for n, o in
                zip(self.output_names, self._exec.outputs)] \
            if self._exec.outputs else None

    # -- bind ---------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write",
             shared_module=None, group2ctx=None):
        """Allocate the executor (reference: Module.bind; ``group2ctx``
        maps AttrScope(ctx_group=...) names to devices — manual model
        parallelism, reference GraphExecutor PlaceDevice)."""
        if self.binded and not force_rebind:
            return
        self._group2ctx = dict(group2ctx or {})
        self._shared_module = shared_module
        self.for_training = for_training
        self._data_shapes = _as_descs(data_shapes)
        self._label_shapes = _as_descs(label_shapes)
        feed = {d.name: d.shape for d in self._data_shapes +
                self._label_shapes}
        arg_shapes, _, aux_shapes = self._exec_symbol.infer_shape(
            **{k: v for k, v in feed.items()
               if k in self._exec_symbol.list_arguments()})
        arg_names = self._exec_symbol.list_arguments()
        # group2ctx: allocate each arg on ITS group's device (the reference
        # GraphExecutor PlaceDevice) so only activations cross boundaries
        # per step, never the weights
        node_ctx: Dict[str, Context] = {}
        if self._group2ctx:
            from .. import symbol as _sym_mod
            for n in _sym_mod._topo(self._exec_symbol._heads):
                if n.op == "null":
                    grp = n.attrs.get("__ctx_group__")
                    if grp in self._group2ctx:
                        node_ctx[n.name] = self._group2ctx[grp]
        shared_exec = None
        if self._shared_module is not None:
            if not getattr(self._shared_module, "binded", False):
                raise MXNetError(
                    "bind(shared_module=...): the shared module must be "
                    "bound first (reference Module asserts the same)")
            shared_exec = self._shared_module._exec
        args: Dict[str, NDArray] = {}
        grads: Dict[str, NDArray] = {}
        for name, shape in zip(arg_names, arg_shapes):
            if shared_exec is not None and name in self._param_names \
                    and name in shared_exec.arg_dict:
                # share by identity — never allocate a throwaway buffer
                shared_arr = shared_exec.arg_dict[name]
                if tuple(shared_arr.shape) != tuple(shape):
                    raise MXNetError(
                        "shared_module: parameter %r shape mismatch "
                        "(%s vs %s)" % (name, shared_arr.shape, shape))
                args[name] = shared_arr
                # share the grad buffer only if THIS module trains the
                # param — a fixed_param_names entry here must not write
                # into the master's gradients
                wants_grad_shared = name not in self._fixed_param_names
                if for_training and wants_grad_shared \
                        and name in shared_exec.grad_dict:
                    grads[name] = shared_exec.grad_dict[name]
                continue
            args[name] = nd.zeros(shape,
                                  ctx=node_ctx.get(name, self._context))
            wants_grad = (name in self._param_names and
                          name not in self._fixed_param_names) or \
                (inputs_need_grad and name in self._data_names)
            if for_training and wants_grad:
                grads[name] = nd.zeros(shape,
                                       ctx=node_ctx.get(name, self._context))
        self.inputs_need_grad = inputs_need_grad
        aux = {name: nd.zeros(shape, ctx=self._context)
               for name, shape in zip(self._aux_names, aux_shapes)}
        self._grad_req = grad_req if for_training else "null"
        self._exec = self._exec_symbol.bind(
            self._context, args, grads,
            grad_req if for_training else "null", aux,
            group2ctx=self._group2ctx)
        # buffer-census attribution (ISSUE 10): a Module's weights live
        # in its executor's arg/aux/grad dicts, not gluon Parameters —
        # claim them for the "params" owner bucket
        from .. import programs as _programs
        _programs.track_buffers("params", self, _module_census_arrays)
        if shared_exec is not None:
            for aname in self._aux_names:
                if aname in shared_exec.aux_dict:
                    self._exec.aux_dict[aname] = shared_exec.aux_dict[aname]
            self.params_initialized = self._shared_module.params_initialized
        self.binded = True

    # -- params -------------------------------------------------------------
    def init_params(self, initializer=init_mod.Uniform(0.01),
                    arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        """Reference: Module.init_params (initializer=None leaves
        unmatched params untouched, as set_params needs)."""
        assert self.binded, "call bind before init_params"
        if self.params_initialized and not force_init:
            return
        if not allow_extra:
            extra = [k for k in (arg_params or {}) if k not in
                     self._param_names]
            extra += [k for k in (aux_params or {}) if k not in
                      self._aux_names]
            if extra:
                raise MXNetError(
                    "init_params/set_params got params not in the symbol: "
                    "%s (pass allow_extra=True to ignore)" % extra)
        attr_map = self._symbol.attr_dict()
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._set_jax(arg_params[name]._jax if isinstance(
                    arg_params[name], NDArray)
                    else jnp.asarray(arg_params[name]))
            elif arg_params is not None and not allow_missing:
                raise MXNetError(
                    "missing parameter %r (pass allow_missing=True to "
                    "initialize absent params)" % name)
            elif initializer is not None:
                initializer(init_mod.InitDesc(
                    name, attrs=attr_map.get(name)), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._set_jax(aux_params[name]._jax if isinstance(
                    aux_params[name], NDArray)
                    else jnp.asarray(aux_params[name]))
            elif initializer is not None:
                initializer(init_mod.InitDesc(
                    name, attrs=attr_map.get(name)), arr)
        self.params_initialized = True

    def get_params(self):
        """Reference: Module.get_params → (arg_params, aux_params)."""
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # -- optimizer ----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Reference: Module.init_optimizer (kvstore collapses to the local
        updater — one device owns the weights here)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            idx2name = dict(enumerate(self._param_names))
            params = dict(optimizer_params or {})
            if "rescale_grad" not in params:
                # reference Module.init_optimizer: loss heads emit
                # PER-EXAMPLE gradients (SoftmaxOutput normalization
                # 'null'), so the optimizer divides by the batch size —
                # read off the DataDesc's batch axis (layout-aware)
                batch = 1
                if self._data_shapes:
                    desc = self._data_shapes[0]
                    axis = 0
                    layout = getattr(desc, "layout", None)
                    if layout:
                        from ..io import DataDesc as _DD
                        axis = max(_DD.get_batch_axis(layout), 0)
                    batch = desc[1][axis]
                params["rescale_grad"] = 1.0 / max(batch, 1)
            optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, **params)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        self.optimizer_initialized = True

    # -- compute ------------------------------------------------------------
    def _resolve_head_labels(self):
        """Per-head label NDArray (or None), applying the traced
        shape-only chains and the positional fallback — shared by the
        eager and the whole-graph-jit paths."""
        label_map = dict(zip(self._label_names, self._labels))
        positional = list(self._labels)
        resolved = []
        for rule in self._head_rules:
            if rule is None:
                resolved.append(None)
                continue
            _fn, _attrs, label_name, label_chain = rule
            label = label_map.get(label_name)
            if label is not None:
                # drop the ORIGINAL fed object from the positional pool
                # before any shape-chain replay rebinds `label` to a new
                # NDArray — otherwise a later unnamed head could pop the
                # consumed label positionally and train on the wrong one
                positional = [l for l in positional if l is not label]
            if label is not None and label_chain:
                from ..ndarray.ndarray import invoke as _invoke
                from ..symbol import _attr_parse as _ap
                for op_n, op_attrs in label_chain:
                    label = _invoke(op_n, label,
                                    **{k: _ap(v)
                                       for k, v in op_attrs.items()
                                       if not k.startswith("__")})
            if label is None and label_name is None and positional:
                label = positional.pop(0)
            resolved.append(label)
        return resolved

    def _try_fast_forward(self, feeds, is_train):
        """One-executable forward (+backward when training): the whole
        graph, the loss-head transforms, their exact gradients and the
        vjp run as a single jitted function (reference: GraphExecutor
        compiles the graph; per-node dispatch is the fallback)."""
        from ..symbol import whole_graph_jit_enabled
        if self._jit_ok is False or self._exec._group2ctx \
                or not whole_graph_jit_enabled():
            # per-op AMP casting and device groups live in the eager
            # dispatcher — those configurations keep the per-node path
            return None
        head_nodes = [n for n, _ in self._symbol._heads]
        labels = self._resolve_head_labels()
        if is_train:
            # the fused backward needs every head to be a loss head with
            # a label; anything else falls back to the eager tape
            if any(r is None or l is None
                   for r, l in zip(self._head_rules, labels)):
                return None
        key = bool(is_train)
        step = self._jit_step.get(key)
        if step is None:
            from ..symbol import build_pure_fn, NotJittableGraph
            try:
                pure = build_pure_fn(self._exec_symbol, is_train=is_train)
            except NotJittableGraph:
                self._jit_ok = False
                return None
            cores = []
            for node, rule in zip(head_nodes, self._head_rules):
                if rule is None:
                    cores.append((None, None))
                else:
                    attrs = {k: v for k, v in rule[1].items()}
                    cores.append((_RULE_CORES[node.op], attrs))

            if is_train:
                def step(diff_vals, other_vals, label_vals, rng):
                    def f(dv):
                        heads, aux_new = pure({**dv, **other_vals}, rng)
                        return tuple(heads), aux_new
                    heads, vjp_fn, aux_new = jax.vjp(f, diff_vals,
                                                     has_aux=True)
                    outs, cots = [], []
                    for z, (core, attrs), lab in zip(heads, cores,
                                                     label_vals):
                        out, g = core(z, lab, attrs)
                        outs.append(out)
                        cots.append(g)
                    (d_diff,) = vjp_fn(tuple(cots))
                    return tuple(outs), d_diff, aux_new
            else:
                def step(all_vals, label_vals, rng):
                    heads, _aux = pure(all_vals, rng)
                    outs = []
                    for z, (core, attrs), lab in zip(heads, cores,
                                                     label_vals):
                        if core is None:
                            outs.append(z)
                        else:
                            outs.append(core(z, lab, attrs)[0])
                    return tuple(outs)
            from ..programs import register_program
            step = register_program(
                "module.step_train" if is_train else "module.step_infer",
                step)
            self._jit_step[key] = step
            self._jit_ok = True

        if self._exec._rng_needed():
            from ..ops.random import next_key
            rng = next_key()
        else:
            rng = jax.random.PRNGKey(0)
        from ..engine import engine as _engine
        _engine.count_dispatch()   # the whole fwd(+bwd) is ONE executable
        label_vals = [None if l is None else l._jax for l in labels]
        if is_train:
            diff = {}
            other = {}
            for name, arr in self._exec.arg_dict.items():
                v = feeds[name]._jax if name in feeds else arr._jax
                if name in self._exec.grad_dict:
                    diff[name] = v
                else:
                    other[name] = v
            for name, arr in self._exec.aux_dict.items():
                other[name] = arr._jax
            outs, d_diff, aux_new = step(diff, other, label_vals, rng)
            self._fast_grads = d_diff
            for name, val in aux_new.items():
                tgt = self._exec.aux_dict.get(name)
                if tgt is not None:
                    tgt._set_jax(val.astype(tgt.dtype))
        else:
            vals = {}
            for name, arr in self._exec.arg_dict.items():
                vals[name] = feeds[name]._jax if name in feeds \
                    else arr._jax
            for name, arr in self._exec.aux_dict.items():
                vals[name] = arr._jax
            outs = step(vals, label_vals, rng)
            self._fast_grads = None
        ctx = self._context
        self._outputs = [nd.from_jax(o, ctx=ctx) for o in outs]
        self._head_grads = [None] * len(outs)
        # keep the executor's feed cache coherent for get_input_grads etc.
        for name, arr in feeds.items():
            self._exec.arg_dict[name] = arr
        return True

    def _collect_feeds(self, data_batch):
        """Name-matched feeds for one batch (sets self._labels) — shared
        by forward() and the whole-step compiled fit path."""
        def in_batch_order(arrays, descs, wanted):
            """Reference DataParallelExecutorGroup matches batch arrays to
            module slots by NAME (DataDesc), not position — NDArrayIter
            sorts dict-fed names, so positional zip would swap slots."""
            names = []
            for d in descs or []:
                names.append(d[0] if isinstance(d, (tuple, list))
                             else getattr(d, "name", d))
            if len(names) == len(arrays):
                by_name = dict(zip(names, arrays))
                if all(n in by_name for n in wanted):
                    # superset is fine: extra batch slots are ignored
                    return [(n, by_name[n]) for n in wanted]
            return list(zip(wanted, arrays))

        feeds = {}
        for name, arr in in_batch_order(
                data_batch.data, getattr(data_batch, "provide_data", None),
                self._data_names):
            feeds[name] = arr.as_in_context(self._context)
        self._labels = []
        if data_batch.label:
            for name, arr in in_batch_order(
                    data_batch.label,
                    getattr(data_batch, "provide_label", None),
                    self._label_names):
                arr = arr.as_in_context(self._context)
                if name in self._exec.arg_dict:  # labels a non-loss head uses
                    feeds[name] = arr
                self._labels.append(arr)
        return feeds

    def forward(self, data_batch, is_train=None):
        """Reference: Module.forward."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feeds = self._collect_feeds(data_batch)
        if self._try_fast_forward(feeds, is_train):
            return
        self._fast_grads = None
        raw = self._exec.forward(is_train=is_train, **feeds)
        # apply loss-output forward transforms (always — predict without
        # labels must still see probabilities); cache exact head grads
        # when this head's label was fed
        labels = self._resolve_head_labels()
        self._outputs = []
        self._head_grads = []
        for z, rule, label in zip(raw, self._head_rules, labels):
            if rule is None:
                self._outputs.append(z)
                self._head_grads.append(None)
                continue
            fn, attrs, _label_name, _chain = rule
            if label is not None and isinstance(z, NDArray) \
                    and label.context != z.context:
                # group2ctx: the head may live on another device than the
                # label feed — align (the reference's cross-device copy)
                label = label.as_in_context(z.context)
            out, grad = fn(z, label, attrs)
            self._outputs.append(out)
            self._head_grads.append(grad)

    def backward(self, out_grads=None):
        """Reference: Module.backward — loss-output heads use the exact
        in-op gradient cached at forward; other heads need out_grads."""
        assert self.binded and self.params_initialized
        if self._fast_grads is not None and out_grads is not None:
            raise MXNetError(
                "Module.backward(out_grads=...) needs the per-op eager "
                "path, but this forward ran the whole-graph jit; set "
                "MX_MODULE_JIT=0 (or install a monitor) to disable it")
        if self._fast_grads is not None and out_grads is None:
            # the fused jit step already produced every argument gradient
            for name, g in self._fast_grads.items():
                tgt = self._exec.grad_dict.get(name)
                if tgt is None:
                    continue
                if self._grad_req == "add":
                    tgt._set_jax(tgt._jax + g.astype(tgt.dtype))
                else:
                    tgt._set_jax(g.astype(tgt.dtype))
                    # overlap scheduling (ISSUE 5): each gradient write is
                    # a readiness event for the bucketed exchange
                    if tgt._grad_hook is not None:
                        tgt._grad_hook()
            self._fast_grads = None
            return
        if out_grads is None:
            out_grads = []
            for (node, _), g in zip(self._symbol._heads, self._head_grads):
                if g is None:
                    raise MXNetError(
                        "Module.backward: head %r is not a loss output with "
                        "a label feed; pass out_grads explicitly (reference "
                        "requires the same)" % node.name)
                out_grads.append(g)
        self._exec.backward(out_grads)

    # -- whole-step compiled fit (ISSUE 7: MX_STEP_COMPILE) ------------------
    def _compiled_fit_batch(self, data_batch, eval_metric):
        """One fit-loop iteration — forward, loss-head gradients, vjp,
        fused optimizer apply and (when the metric has a device kernel)
        the metric accumulate — as ONE jitted dispatch.  Returns False
        when this configuration cannot compile (the caller runs the
        classic eager body): per-node monitors, group2ctx, non-loss
        heads, grad_req='add', or an optimizer without a pure tree
        kernel."""
        from ..symbol import whole_graph_jit_enabled
        from ..step import metric_trace_kernel
        from ..ops.optimizer import tree_body
        if self._jit_ok is False or self._exec._group2ctx \
                or not whole_graph_jit_enabled() \
                or self._grad_req != "write" or self.inputs_need_grad:
            return False
        opt = self._updater.optimizer
        spec = opt._compiled_spec()
        if spec is None:
            return False
        feeds = self._collect_feeds(data_batch)
        labels = self._resolve_head_labels()
        if any(r is None or l is None
               for r, l in zip(self._head_rules, labels)):
            return False

        trainable = [n for n in self._param_names
                     if n in self._exec.grad_dict]
        name2idx = {n: i for i, n in enumerate(self._param_names)}
        mp_flags = []
        for n in trainable:
            i = name2idx[n]
            w = self._exec.arg_dict[n]
            if i not in self._updater.states:
                self._updater.states[i] = \
                    opt.create_state_multi_precision(i, w)
                self._updater.states_synced[i] = True
            mp_flags.append(bool(opt._is_mp_state(
                w, self._updater.states[i])))
        diff_names = sorted(self._exec.grad_dict)
        other = {}
        diff = {}
        for name, arr in self._exec.arg_dict.items():
            v = feeds[name]._jax if name in feeds else arr._jax
            (diff if name in self._exec.grad_dict else other)[name] = v
        for name, arr in self._exec.aux_dict.items():
            other[name] = arr._jax
        from ..step import metric_cache_key
        metric_info = metric_trace_kernel(eval_metric)
        # wd/clip are baked into the trace as statics: they belong in the
        # cache key so a mid-run mutation retraces instead of silently
        # reusing the stale values (the eager path reads them per step)
        wds = tuple(opt._get_wds([name2idx[n] for n in trainable]))
        clip = -1.0 if opt.clip_gradient is None else \
            float(opt.clip_gradient)
        key = ("fit",
               tuple((n, tuple(v.shape), str(v.dtype))
                     for n, v in sorted(diff.items())),
               tuple((n, tuple(v.shape), str(v.dtype))
                     for n, v in sorted(other.items())),
               tuple((tuple(l._jax.shape), str(l.dtype)) for l in labels),
               spec["kind"], tuple(sorted(spec["static"].items())),
               tuple(mp_flags), float(opt.rescale_grad), wds, clip,
               metric_cache_key(eval_metric, metric_info))
        step = self._compiled_fit.get(key)
        if step is None:
            step = self._build_compiled_fit(spec, trainable, mp_flags,
                                            metric_info, tree_body,
                                            wds, clip)
            if step is None:
                return False
            self._compiled_fit[key] = step
        # host-side optimizer bookkeeping: num_update advance + per-param
        # effective lr/decay as traced scalars (schedulers never recompile)
        idxs = [name2idx[n] for n in trainable]
        ctx = self._context
        opt._set_current_context((ctx.canonical_type, ctx.device_id))
        opt._update_count(idxs)
        raw = opt._get_lrs(idxs)
        wds = opt._get_wds(idxs)
        decay_vec = None
        if spec.get("decay_fn") is not None:
            decay_vec = jnp.asarray(_np.asarray(
                [spec["decay_fn"](i, lr, wd)
                 for i, lr, wd in zip(idxs, raw, wds)], _np.float32))
        if spec.get("lr_fn") is not None:
            raw = [spec["lr_fn"](i, lr) for i, lr in zip(idxs, raw)]
        lr_vec = jnp.asarray(_np.asarray(raw, _np.float32))
        if self._exec._rng_needed():
            from ..ops.random import next_key
            rng = next_key()
        else:
            rng = jax.random.PRNGKey(0)
        states, w32s = [], []
        for pos, n in enumerate(trainable):
            inner, w32 = spec["unpack"](self._updater.states[name2idx[n]],
                                        mp_flags[pos])
            states.append(tuple(s._jax for s in inner))
            w32s.append(w32._jax if w32 is not None else None)
        mstate = None
        if metric_info is not None:
            ds = getattr(eval_metric, "_dev_sum", None)
            mstate = (ds, eval_metric._dev_inst) if ds is not None else \
                (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))
        from ..engine import engine as _engine
        label_vals = [l._jax for l in labels]

        def donatable(a):
            if a is None or id(a) in self._compiled_owned:
                return a
            return jnp.array(a, copy=True)   # foreign: may be aliased

        diff = {n: donatable(v) for n, v in diff.items()}
        states = tuple(tuple(donatable(s) for s in inner)
                       for inner in states)
        w32s = tuple(donatable(w) for w in w32s)
        # ISSUE 8: the one-dispatch fit batch shows up in profiler
        # dumps() and the per-phase breakdown like any eager phase would
        with _telemetry.phase("compiled_step"):
            (new_diff, new_states, new_w32, aux_new, outs,
             new_mstate) = step(diff, other, states, w32s,
                                label_vals, rng, lr_vec, decay_vec, mstate)
        self._compiled_owned_refs = [
            a for a in jax.tree_util.tree_leaves(
                (new_diff, new_states, new_w32))
            if a is not None]
        self._compiled_owned = {id(a) for a in self._compiled_owned_refs}
        _engine.count_step_window(1)
        for name, val in new_diff.items():
            arr = self._exec.arg_dict[name]
            arr._set_jax(val.astype(arr.dtype))
        for pos, n in enumerate(trainable):
            inner, w32 = spec["unpack"](self._updater.states[name2idx[n]],
                                        mp_flags[pos])
            for s_nd, val in zip(inner, new_states[pos]):
                s_nd._set_jax(val.astype(s_nd.dtype))
            if w32 is not None and new_w32[pos] is not None:
                w32._set_jax(new_w32[pos])
        for name, val in aux_new.items():
            tgt = self._exec.aux_dict.get(name)
            if tgt is not None:
                tgt._set_jax(val.astype(tgt.dtype))
        self._outputs = [nd.from_jax(o, ctx=ctx) for o in outs]
        self._fast_grads = None
        if new_mstate is not None:
            eval_metric._dev_sum, eval_metric._dev_inst = new_mstate
        else:
            self.update_metric(eval_metric, data_batch.label)
        return True

    def _build_compiled_fit(self, spec, trainable, mp_flags,
                            metric_info, tree_body, wds, clip):
        from ..symbol import build_pure_fn, NotJittableGraph
        try:
            pure = build_pure_fn(self._exec_symbol, is_train=True)
        except NotJittableGraph:
            self._jit_ok = False
            return None
        head_nodes = [n for n, _ in self._symbol._heads]
        cores = []
        for node, rule in zip(head_nodes, self._head_rules):
            cores.append((_RULE_CORES[node.op],
                          {k: v for k, v in rule[1].items()}))
        body = tree_body(spec["kind"])
        statics = dict(spec["static"])
        n_state = spec["n_state"]
        groups: Dict[bool, List[int]] = {}
        for pos, mp in enumerate(mp_flags):
            groups.setdefault(mp, []).append(pos)
        mp_groups = sorted(groups.items())
        opt = self._updater.optimizer
        rescale = float(opt.rescale_grad)
        order = metric_info[1] if metric_info is not None else None
        kernel = metric_info[0] if metric_info is not None else None

        def _traced_fit_step(diff_vals, other_vals, states, w32s,
                             label_vals, rng, lr_vec, decay_vec, mstate):
            def f(dv):
                heads, aux_new = pure({**dv, **other_vals}, rng)
                return tuple(heads), aux_new

            heads, vjp_fn, aux_new = jax.vjp(f, diff_vals, has_aux=True)
            outs, cots = [], []
            for z, (core, attrs), lab in zip(heads, cores, label_vals):
                out, g = core(z, lab, attrs)
                outs.append(out)
                cots.append(g)
            (d_diff,) = vjp_fn(tuple(cots))
            new_diff = dict(diff_vals)
            new_states = list(states)
            new_w32 = list(w32s)
            for mp, poss in mp_groups:
                names = [trainable[p] for p in poss]
                ws = tuple(diff_vals[n] for n in names)
                gs = tuple(d_diff[n].astype(diff_vals[n].dtype)
                           for n in names)
                cols = [tuple(states[p][j] for p in poss)
                        for j in range(n_state)]
                args = [ws, gs] + cols
                args.append(tuple(w32s[p] for p in poss) if mp else None)
                args.append(lr_vec[jnp.asarray(poss, jnp.int32)])
                if decay_vec is not None:
                    args.append(decay_vec[jnp.asarray(poss, jnp.int32)])
                out_w, out_states, out_w32 = body(
                    *args, wds=tuple(wds[p] for p in poss),
                    rescale_grad=rescale, clip_gradient=clip, mp=mp,
                    **statics)
                for j, (p, n) in enumerate(zip(poss, names)):
                    new_diff[n] = out_w[j]
                    if out_states is not None:
                        new_states[p] = tuple(col[j] for col in out_states)
                    if mp and out_w32 is not None:
                        new_w32[p] = out_w32[j]
            if mstate is not None and kernel is not None:
                msum, minst = mstate
                if order == "loss":
                    new_mstate = tuple(kernel(msum, minst, outs[0]))
                elif order == "label_pred":
                    new_mstate = tuple(kernel(msum, minst, label_vals[0],
                                              outs[0]))
                else:
                    new_mstate = tuple(kernel(msum, minst, outs[0],
                                              label_vals[0]))
            else:
                new_mstate = mstate
            return (new_diff, tuple(new_states), tuple(new_w32), aux_new,
                    tuple(outs), new_mstate)

        from ..programs import register_program
        return register_program("module.fit_step", _traced_fit_step,
                                donate_argnums=(0, 2, 3))

    def update(self):
        """Reference: Module.update — updater over (grad, weight) pairs,
        batched into ONE call so an aggregate-enabled optimizer applies
        the whole parameter set as a single fused pytree dispatch."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        idxs, grads, weights = [], [], []
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            idxs.append(i)
            grads.append(grad)
            weights.append(self._exec.arg_dict[name])
        if idxs:
            with _telemetry.phase("optimizer_apply"):
                self._updater(idxs, grads, weights)

    def get_outputs(self):
        assert self.binded
        return getattr(self, "_outputs", None) or self._exec.outputs

    def get_input_grads(self):
        assert self.binded
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        # forward() has already name-matched the batch labels into module
        # slot order; the raw data_batch.label list may be sorted
        # differently (NDArrayIter sorts dict-fed names)
        if getattr(self, "_labels", None) and len(self._labels) == \
                len(labels):
            labels = self._labels
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, monitor):
        # the monitor taps per-node intermediates, which the whole-graph
        # jit never materializes — monitored modules run the eager path
        # at BOTH layers (the executor has its own inference fast path)
        self._jit_ok = False
        self._exec._pure_ok = False
        monitor.install(self._exec)

    # -- checkpoints ---------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Reference: Module.save_checkpoint."""
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            with open("%s-%04d.states" % (prefix, epoch), "wb") as f:
                f.write(self._updater.get_states(dump_optimizer=True))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Reference: Module.load."""
        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)

        orig_bind = mod.bind

        def bind_and_load(*a, **kw):
            orig_bind(*a, **kw)
            mod.init_params(arg_params=arg, aux_params=aux)
        mod.bind = bind_and_load

        if load_optimizer_states:
            states_file = "%s-%04d.states" % (prefix, epoch)
            orig_init_opt = mod.init_optimizer

            def init_opt_and_load(*a, **kw):
                orig_init_opt(*a, **kw)
                with open(states_file, "rb") as f:
                    mod._updater.set_states(f.read())
                mod._optimizer = mod._updater.optimizer
            mod.init_optimizer = init_opt_and_load
        return mod


from .bucketing_module import BucketingModule  # noqa: E402
