"""TensorBoard event-file writer (reference role: the external ``mxboard``
package — SURVEY §5.5 "optional TensorBoard scalar writer since the
profiler already emits TB traces").

No tensorboard package offline, so the wire format is written directly
(the ONNX module's approach): TFRecord framing (u64 length + masked
crc32c + payload) around Event protos (field numbers from
tensorboard/compat/proto/event.proto).  Scalars, text, and histograms;
readable by a stock TensorBoard pointed at the logdir.
"""
from __future__ import annotations

import os
import struct
import time
from typing import Optional

import numpy as _np

__all__ = ["SummaryWriter"]


# -- crc32c (Castagnoli), required by TFRecord framing ----------------------

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


# -- protobuf primitives (shared shape with onnx/__init__.py) ---------------

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(int(value))


def _f_bytes(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _f_double(field: int, value: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", value)


def _f_float(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", value)


def _summary_value(tag: str, simple_value: Optional[float] = None,
                   histo: Optional[bytes] = None,
                   text: Optional[str] = None) -> bytes:
    # Summary.Value: tag=1, simple_value=2, histo=5, tensor=8
    out = _f_bytes(1, tag.encode())
    if simple_value is not None:
        out += _f_float(2, float(simple_value))
    if histo is not None:
        out += _f_bytes(5, histo)
    if text is not None:
        payload = text.encode()
        # TensorProto: dtype=1 (field 1, DT_STRING=7), string_val=8
        tensor = _f_varint(1, 7) + _f_bytes(8, payload)
        out += _f_bytes(8, tensor)
        # metadata plugin_name="text" (SummaryMetadata field 9:
        # plugin_data{plugin_name=1})
        out += _f_bytes(9, _f_bytes(1, _f_bytes(1, b"text")))
    return out


def _histogram_proto(values: _np.ndarray, bins: int = 30) -> bytes:
    v = _np.asarray(values, _np.float64).ravel()
    counts, edges = _np.histogram(v, bins=bins)
    out = _f_double(1, float(v.min()))
    out += _f_double(2, float(v.max()))
    out += _f_double(3, float(v.size))
    out += _f_double(4, float(v.sum()))
    out += _f_double(5, float((v * v).sum()))
    # bucket_limit=6 (packed double), bucket=7 (packed double)
    limits = b"".join(struct.pack("<d", e) for e in edges[1:])
    buckets = b"".join(struct.pack("<d", float(c)) for c in counts)
    out += _f_bytes(6, limits)
    out += _f_bytes(7, buckets)
    return out


class SummaryWriter:
    """Append-only TB event file (reference role: mxboard.SummaryWriter).

    >>> sw = SummaryWriter('./logs')
    >>> sw.add_scalar('loss', 0.5, step)
    >>> sw.add_histogram('weights', nd_array, step)
    >>> sw.close()
    """

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        fname = "events.out.tfevents.%010d.%s%s" % (
            int(time.time()), os.uname().nodename, filename_suffix)
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "wb")
        # file header event: wall_time + file_version
        self._write_event(_f_double(1, time.time()) +
                          _f_bytes(3, b"brain.Event:2"))

    def _write_event(self, event_pb: bytes) -> None:
        length = struct.pack("<Q", len(event_pb))
        self._f.write(length)
        self._f.write(struct.pack("<I", _masked_crc(length)))
        self._f.write(event_pb)
        self._f.write(struct.pack("<I", _masked_crc(event_pb)))
        self._f.flush()

    def _event(self, summary: bytes, step: int) -> bytes:
        return (_f_double(1, time.time()) + _f_varint(2, step) +
                _f_bytes(5, summary))

    def add_scalar(self, tag: str, value, global_step: int = 0) -> None:
        if hasattr(value, "asnumpy"):
            value = float(value.asnumpy())
        self._write_event(self._event(
            _f_bytes(1, _summary_value(tag, simple_value=float(value))),
            global_step))

    def add_histogram(self, tag: str, values, global_step: int = 0,
                      bins: int = 30) -> None:
        if hasattr(values, "asnumpy"):
            values = values.asnumpy()
        self._write_event(self._event(
            _f_bytes(1, _summary_value(
                tag, histo=_histogram_proto(values, bins))), global_step))

    def add_text(self, tag: str, text: str, global_step: int = 0) -> None:
        self._write_event(self._event(
            _f_bytes(1, _summary_value(tag, text=text)), global_step))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- reader (round-trip testing without tensorboard) ------------------------

def _read_varint(buf, pos):
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def read_events(path):
    """Parse an event file back into [(step, tag, value-or-kind)] —
    the round-trip gate (stock TB is the real consumer)."""
    out = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        (lcrc,) = struct.unpack_from("<I", data, pos + 8)
        if lcrc != _masked_crc(data[pos:pos + 8]):
            raise ValueError("corrupt length crc at %d" % pos)
        event = data[pos + 12:pos + 12 + length]
        (dcrc,) = struct.unpack_from("<I", data, pos + 12 + length)
        if dcrc != _masked_crc(event):
            raise ValueError("corrupt data crc at %d" % pos)
        pos += 12 + length + 4
        # walk the Event proto
        epos, step, summary = 0, 0, None
        while epos < len(event):
            key, epos = _read_varint(event, epos)
            field, wire = key >> 3, key & 7
            if wire == 0:
                val, epos = _read_varint(event, epos)
                if field == 2:
                    step = val
            elif wire == 1:
                epos += 8
            elif wire == 5:
                epos += 4
            elif wire == 2:
                ln, epos = _read_varint(event, epos)
                if field == 5:
                    summary = event[epos:epos + ln]
                epos += ln
        if summary is None:
            continue
        spos = 0
        while spos < len(summary):
            key, spos = _read_varint(summary, spos)
            field, wire = key >> 3, key & 7
            if wire != 2:
                raise ValueError("unexpected summary wire %d" % wire)
            ln, spos = _read_varint(summary, spos)
            value = summary[spos:spos + ln]
            spos += ln
            vpos, tag, payload = 0, "", None
            while vpos < len(value):
                k2, vpos = _read_varint(value, vpos)
                f2, w2 = k2 >> 3, k2 & 7
                if w2 == 2:
                    ln2, vpos = _read_varint(value, vpos)
                    body = value[vpos:vpos + ln2]
                    vpos += ln2
                    if f2 == 1:
                        tag = body.decode()
                    elif f2 == 5:
                        payload = ("histo", body)
                    elif f2 == 8:
                        payload = ("text", body)
                elif w2 == 5:
                    (sv,) = struct.unpack_from("<f", value, vpos)
                    vpos += 4
                    if f2 == 2:
                        payload = ("scalar", sv)
                elif w2 == 1:
                    vpos += 8
                else:
                    _, vpos = _read_varint(value, vpos)
            out.append((step, tag, payload))
    return out
