"""INT8 quantization driver: calibrate a float network and rewrite its
Dense/Conv2D layers onto the int8 MXU ops.

Reference: python/mxnet/contrib/quantization.py (quantize_model,
quantize_net, _LayerOutputCollector, _get_optimal_thresholds — the
KL-divergence "entropy" calibration), src/operator/quantization/
quantize_graph_pass.cc (the graph rewrite inserting quantize/dequantize
pairs).

TPU-native design: instead of an nnvm graph pass, quantization is a
*block rewrite* — each Dense/Conv2D is wrapped so its forward runs
quantize_v2(input) → int8 GEMM/conv (MXU int8×int8→int32) → dequantize.
Weights are pre-quantized once at conversion time.  Calibration modes
match the reference: 'naive' (observed min/max) and 'entropy'
(KL-optimal thresholds over a 255-bin histogram).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError

__all__ = ["quantize_net", "CalibrationCollector",
           "_get_optimal_threshold"]


def _smooth(p: _np.ndarray, eps: float = 1e-4) -> _np.ndarray:
    """Laplace-style smoothing the reference applies before KL."""
    is_zero = p == 0
    n_zero = int(is_zero.sum())
    n_nonzero = p.size - n_zero
    if n_nonzero == 0:
        return p
    take = eps * n_zero / n_nonzero
    out = p.astype(_np.float64).copy()
    out[is_zero] = eps
    out[~is_zero] -= take
    return out


def _get_optimal_threshold(arr: _np.ndarray, num_bins: int = 8001,
                           num_quantized_bins: int = 255) -> float:
    """KL-divergence calibration (reference: quantization.py
    _get_optimal_threshold): pick the |threshold| whose clipped+requantized
    distribution diverges least from the original histogram.

    The decisive detail (matching the reference): p carries the clipped
    outlier mass in its last bin, but q is built from the UNCLIPPED slice —
    so aggressive clipping shows up as p-mass with no q-mass and is
    penalized by the KL term.  Every candidate bin from num_quantized_bins
    to num_bins is scanned (no subsampling); the inner merge uses
    ``_np.bincount`` so the full scan stays fast."""
    a = _np.abs(arr.ravel())
    # exact zeros carry no quantization information (0 requantizes exactly
    # at any threshold) and a post-relu zero spike would otherwise dominate
    # the KL optimum; the reference strips them before the histogram
    a = a[a != 0]
    amax = float(a.max()) if a.size else 0.0
    if amax == 0.0:
        return 1e-30
    hist, edges = _np.histogram(a, bins=num_bins, range=(0, amax))
    hist = hist.astype(_np.float64)
    csum = _np.cumsum(hist)
    total = csum[-1]
    arange = _np.arange(num_bins)
    best_kl, best_t = _np.inf, amax
    for i in range(num_quantized_bins, num_bins + 1):
        t = edges[i]
        sliced = hist[:i]
        p = sliced.copy()
        p[-1] += total - csum[i - 1]  # clip outliers into the last bin
        nonzero = p != 0
        # merge the unclipped slice into num_quantized_bins groups,
        # then expand back, spreading each group over its nonzero bins
        num_merged = i // num_quantized_bins
        idx = _np.minimum(arange[:i] // num_merged, num_quantized_bins - 1)
        q_small = _np.bincount(idx, weights=sliced,
                               minlength=num_quantized_bins)
        counts = _np.bincount(idx, weights=nonzero.astype(_np.float64),
                              minlength=num_quantized_bins)
        q = _np.zeros(i)
        valid = counts[idx] > 0
        q[valid] = (q_small[idx] / _np.maximum(counts[idx], 1.0))[valid]
        q[~nonzero] = 0.0
        qsum = q.sum()
        if qsum <= 0:
            continue
        ps = _smooth(p / p.sum())
        qs = _smooth(q / qsum)
        kl = float(_np.sum(ps * _np.log(_np.maximum(ps, 1e-30)
                                        / _np.maximum(qs, 1e-30))))
        if kl < best_kl:
            best_kl, best_t = kl, float(t)
    return max(best_t, 1e-30)


class CalibrationCollector:
    """Collects per-layer input statistics during calibration forward
    passes (reference: _LayerOutputCollector)."""

    def __init__(self, mode: str = "naive"):
        if mode not in ("naive", "entropy"):
            raise MXNetError("calib_mode must be 'naive' or 'entropy'")
        self.mode = mode
        self.min_max: Dict[str, Tuple[float, float]] = {}
        self._samples: Dict[str, List[_np.ndarray]] = {}

    def collect(self, name: str, x: _np.ndarray) -> None:
        mn, mx = float(x.min()), float(x.max())
        if name in self.min_max:
            omn, omx = self.min_max[name]
            self.min_max[name] = (min(mn, omn), max(mx, omx))
        else:
            self.min_max[name] = (mn, mx)
        if self.mode == "entropy":
            self._samples.setdefault(name, []).append(
                _np.asarray(x, _np.float32).ravel())

    def thresholds(self) -> Dict[str, Tuple[float, float]]:
        if self.mode == "naive":
            return dict(self.min_max)
        out = {}
        for name, chunks in self._samples.items():
            t = _get_optimal_threshold(_np.concatenate(chunks))
            out[name] = (-t, t)
        return out


class _QuantizedForward:
    """Replacement forward for a calibrated Dense/Conv2D block."""

    def __init__(self, block, kind: str, in_range: Tuple[float, float],
                 quantized_dtype: str):
        from .. import ndarray as nd
        self.block = block
        self.kind = kind
        self.in_min, self.in_max = in_range
        self.dtype = quantized_dtype
        # pre-quantize weights once (symmetric int8)
        w = block.weight.data()
        wnp = w.asnumpy()
        self.w_min = float(wnp.min())
        self.w_max = float(wnp.max())
        self.qweight, _, _ = nd.invoke("_contrib_quantize", w,
                                       nd.array([self.w_min]),
                                       nd.array([self.w_max]),
                                       out_type="int8")
        # bias is pre-quantized once here too (the reference quantizes bias
        # at conversion time) — never in the inference hot path
        bias = block.bias.data() if getattr(block, "bias", None) \
            is not None else None
        if bias is not None:
            bnp = bias.asnumpy()
            self.b_min = float(bnp.min())
            self.b_max = float(bnp.max())
            self.qbias, _, _ = nd.invoke("_contrib_quantize", bias,
                                         nd.array([self.b_min]),
                                         nd.array([self.b_max]),
                                         out_type="int8")
        else:
            self.qbias, self.b_min, self.b_max = None, 0.0, 0.0
        # all range scalars are conversion-time constants: build the device
        # arrays ONCE so the inference hot path does zero host->device work
        self._wmn = nd.array([self.w_min])
        self._wmx = nd.array([self.w_max])
        self._bmn = nd.array([self.b_min])
        self._bmx = nd.array([self.b_max])

    def __call__(self, x):
        from .. import ndarray as nd
        qx, mn, mx_ = nd.invoke("_contrib_quantize_v2", x,
                                out_type=self.dtype,
                                min_calib_range=self.in_min,
                                max_calib_range=self.in_max)
        qb = self.qbias
        if self.kind == "dense":
            acc, omn, omx = nd.invoke(
                "_contrib_quantized_fully_connected", qx, self.qweight, qb,
                mn, mx_, self._wmn, self._wmx, self._bmn, self._bmx,
                num_hidden=self.block._units, no_bias=qb is None,
                flatten=self.block._flatten)
        else:
            blk = self.block
            acc, omn, omx = nd.invoke(
                "_contrib_quantized_conv", qx, self.qweight, qb,
                mn, mx_, self._wmn, self._wmx, self._bmn, self._bmx,
                kernel=blk._kernel, stride=blk._stride, dilate=blk._dilate,
                pad=blk._pad, num_filter=blk._channels,
                num_group=blk._groups, no_bias=qb is None)
        out = nd.invoke("_contrib_dequantize", acc, omn, omx)
        act = getattr(self.block, "_act", None)
        if act:
            out = nd.invoke("Activation", out, act_type=act)
        return out


def quantize_net(network, quantized_dtype: str = "int8",
                 exclude_layers: Optional[Sequence[str]] = None,
                 calib_data=None, calib_mode: str = "naive",
                 num_calib_batches: Optional[int] = None,
                 logger=None):
    """Calibrate `network` on `calib_data` and return it with Dense/Conv2D
    forwards rewritten onto int8 ops (reference: quantize_net).

    `network` must be an initialized (shape-known) gluon net; `calib_data`
    iterates over input batches (NDArray, or (data, label) tuples whose
    first element is fed)."""
    from ..gluon.nn import Dense
    from ..gluon.nn.conv_layers import Conv2D
    from ..ndarray import NDArray

    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise MXNetError("quantized_dtype must be int8/uint8/auto")
    if quantized_dtype == "auto":
        quantized_dtype = "int8"
    if calib_data is None:
        raise MXNetError("TPU quantize_net requires calib_data (the "
                         "reference's calib_mode='none' weight-only path "
                         "is not supported)")
    # A hybridized net would run its CACHED fp32 executable, bypassing
    # both the calibration hooks and the rewritten int8 forwards — the
    # quantized net is python-dispatched (each int8 op rides the per-op
    # jit cache instead).
    network.hybridize(active=False)
    exclude = set(exclude_layers or ())

    def walk(block, prefix=""):
        for cname, child in block._children.items():
            full = prefix + cname if not prefix else prefix + "." + cname
            yield full, child
            yield from walk(child, full)

    targets: List[Tuple[str, object, str]] = []
    for name, blk in walk(network):
        if name in exclude:
            continue
        if isinstance(blk, Dense):
            targets.append((name, blk, "dense"))
        elif isinstance(blk, Conv2D) and blk._groups == 1:
            targets.append((name, blk, "conv"))

    # ---- calibration pass: hook each target's forward to observe inputs ----
    collector = CalibrationCollector(calib_mode)
    originals = {}

    def make_hook(name, blk):
        fwd = blk.forward

        def hooked(x, *a, **k):
            collector.collect(name, x.asnumpy())
            return fwd(x, *a, **k)
        return fwd, hooked

    for name, blk, _ in targets:
        originals[name], hooked = make_hook(name, blk)
        blk.forward = hooked
    try:
        n = 0
        for batch in calib_data:
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            network(x)
            n += 1
            if num_calib_batches is not None and n >= num_calib_batches:
                break
        if n == 0:
            raise MXNetError("calib_data yielded no batches")
    finally:
        for name, blk, _ in targets:
            blk.forward = originals[name]

    ranges = collector.thresholds()

    # ---- rewrite pass ----
    n_rewritten = 0
    for name, blk, kind in targets:
        if name not in ranges:
            continue  # block never ran during calibration
        blk.forward = _QuantizedForward(blk, kind, ranges[name],
                                        quantized_dtype)
        blk._quantized = True
        n_rewritten += 1
    if logger:
        logger.info("quantized %d layers (%s calibration over %d batches)",
                    n_rewritten, calib_mode, n)
    return network
