"""mx.contrib: control flow, detection ops, misc extensions.

Reference: python/mxnet/contrib/__init__.py (ndarray/symbol contrib
namespaces), python/mxnet/ndarray/contrib.py (foreach/while_loop/cond),
src/operator/contrib/*.

`mx.contrib.nd.<op>` mirrors the reference's contrib.ndarray namespace;
the control-flow combinators live at both `mx.contrib.nd.foreach` and the
2.x-style `mx.npx`-free top level here.
"""
from ..ops.control_flow import foreach, while_loop, cond
from .. import amp  # 1.x location: mx.contrib.amp (2.x: mx.amp)
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from . import quantization
from . import summary
from . import text
from . import summary as tensorboard   # the mxboard-role module
from .. import onnx                    # 1.x location: mx.contrib.onnx

__all__ = ["foreach", "while_loop", "cond", "nd", "ndarray", "amp",
           "quantization"]
