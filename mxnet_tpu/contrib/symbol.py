"""mx.contrib.symbol — contrib ops through the Symbol API (reference:
python/mxnet/contrib/symbol.py; the op set is the registry's _contrib_
family, composed symbolically)."""
from ..symbol import __getattr__ as _sym_getattr


def __getattr__(name):
    # resolve contrib names against the symbol op namespace, accepting
    # both spellings (box_nms and _contrib_box_nms)
    for cand in (name, "_contrib_" + name):
        try:
            return _sym_getattr(cand)
        except AttributeError:
            continue
    raise AttributeError("contrib.symbol has no op %r" % name)
