"""mx.contrib.text — vocabularies and pretrained token embeddings
(reference: python/mxnet/contrib/text/__init__.py)."""
from . import utils
from . import vocab
from . import embedding
from .vocab import Vocabulary
from .embedding import (TokenEmbedding, GloVe, FastText, CustomEmbedding,
                        CompositeEmbedding)

__all__ = ["utils", "vocab", "embedding", "Vocabulary"]
