"""Pretrained token embeddings (reference:
python/mxnet/contrib/text/embedding.py — GloVe/FastText/CustomEmbedding,
registry, CompositeEmbedding).

Offline posture: this environment has no network, so the reference's
download path is replaced by a local `embedding_root` drop directory —
``<embedding_root>/<embedding_name>/<pretrained_file_name>``.  Drop the
(publicly distributed) GloVe/FastText text files there and the loaders
activate without code changes; absent files raise a clear error instead
of attempting a download.  File FORMATS are parsed exactly as the
reference does (whitespace-delimited text; FastText .vec's first line is
a "count dim" header and is skipped).
"""
import io
import logging
import os

from ... import ndarray as nd
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding"]

_REGISTRY = {}


def register(embedding_cls):
    """Reference: embedding.register — decorator adding a TokenEmbedding
    subclass to the create()/get_pretrained_file_names() registry."""
    name = embedding_cls.__name__.lower()
    _REGISTRY[name] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Reference: embedding.create('glove', pretrained_file_name=...)."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError(
            "Cannot find `embedding_name` %r. Valid: %s"
            % (embedding_name, ", ".join(sorted(_REGISTRY))))
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Reference: embedding.get_pretrained_file_names — the catalog of
    publicly distributed files per registered embedding (or a dict of
    all of them)."""
    if embedding_name is not None:
        name = embedding_name.lower()
        if name not in _REGISTRY:
            raise KeyError(
                "Cannot find `embedding_name` %r. Valid: %s"
                % (embedding_name, ", ".join(sorted(_REGISTRY))))
        return list(_REGISTRY[name].pretrained_file_names)
    return {n: list(c.pretrained_file_names)
            for n, c in _REGISTRY.items()}


class TokenEmbedding(Vocabulary):
    """Reference: embedding._TokenEmbedding — a Vocabulary whose indices
    additionally map to embedding vectors (`idx_to_vec`)."""

    pretrained_file_names = ()

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    # -- offline file resolution -------------------------------------------
    @classmethod
    def _default_root(cls):
        return os.path.join(os.path.expanduser("~"), ".mxnet",
                            "embeddings")

    @classmethod
    def _resolve_pretrained_path(cls, embedding_root, pretrained_file_name):
        cls._check_pretrained_file_names(pretrained_file_name)
        path = os.path.join(os.path.expanduser(embedding_root),
                            cls.__name__.lower(), pretrained_file_name)
        if not os.path.isfile(path):
            raise OSError(
                "%s not found. This environment is offline: download %r "
                "elsewhere and drop it at exactly this path to activate "
                "the loader." % (path, pretrained_file_name))
        return path

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if pretrained_file_name not in cls.pretrained_file_names:
            raise KeyError(
                "Cannot find pretrained file %r for %s. Valid: %s"
                % (pretrained_file_name, cls.__name__,
                   ", ".join(cls.pretrained_file_names)))

    # -- loading ------------------------------------------------------------
    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        """Parse a whitespace-delimited embedding text file exactly as the
        reference does: tolerate a FastText header line, warn-and-skip
        malformed lines, first occurrence of a token wins."""
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise OSError("`pretrained_file_path` %r must be a valid path "
                          "to the pre-trained token embedding file."
                          % pretrained_file_path)
        all_elems = []
        tokens = set()
        loaded_unknown_vec = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                elems = line.rstrip().split(elem_delim)
                assert len(elems) > 1, \
                    "line %d in %r: unexpected data format" \
                    % (line_num, pretrained_file_path)
                token, elems = elems[0], elems[1:]
                if token == self.unknown_token \
                        and loaded_unknown_vec is None:
                    loaded_unknown_vec = [float(i) for i in elems]
                elif token in tokens:
                    logging.warning(
                        "line %d in %r: duplicate token %r, skipped",
                        line_num, pretrained_file_path, token)
                elif len(elems) == 1 and line_num == 0:
                    # FastText .vec "count dim" header
                    logging.info("skipped header line of %r",
                                 pretrained_file_path)
                else:
                    try:
                        vec = [float(i) for i in elems]
                    except ValueError:
                        logging.warning(
                            "line %d in %r: unparsable vector for %r, "
                            "skipped", line_num, pretrained_file_path,
                            token)
                        continue
                    if self._vec_len and len(vec) != self._vec_len:
                        logging.warning(
                            "line %d in %r: dim %d != %d, skipped",
                            line_num, pretrained_file_path, len(vec),
                            self._vec_len)
                        continue
                    if not self._vec_len:
                        self._vec_len = len(vec)
                    self._idx_to_token.append(token)
                    self._token_to_idx[token] = \
                        len(self._idx_to_token) - 1
                    tokens.add(token)
                    all_elems.extend(vec)
        import numpy as _np
        mat = _np.zeros((len(self), self._vec_len), dtype="float32")
        if all_elems:
            mat[len(self) - len(tokens):] = _np.asarray(
                all_elems, dtype="float32").reshape(len(tokens),
                                                    self._vec_len)
        self._idx_to_vec = nd.array(mat)
        if loaded_unknown_vec is None:
            self._idx_to_vec[0] = init_unknown_vec(shape=self._vec_len)
        else:
            self._idx_to_vec[0] = nd.array(loaded_unknown_vec)

    def _index_tokens_from_vocabulary(self, vocabulary):
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens

    def _set_idx_to_vec_by_embeddings(self, token_embeddings, vocab_len,
                                      vocab_idx_to_token):
        """Reference: compose idx_to_vec for an explicit vocabulary from
        one or more already-loaded embeddings (concatenated)."""
        new_vec_len = sum(e.vec_len for e in token_embeddings)
        import numpy as _np
        mat = _np.zeros((vocab_len, new_vec_len), dtype="float32")
        col = 0
        for e in token_embeddings:
            col_end = col + e.vec_len
            mat[:, col:col_end] = e.get_vecs_by_tokens(
                vocab_idx_to_token).asnumpy()
            col = col_end
        self._vec_len = new_vec_len
        self._idx_to_vec = nd.array(mat)

    # -- queries ------------------------------------------------------------
    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Token(s) → vector(s); unknown tokens get idx 0's vector.  With
        lower_case_backup, miss falls back to the lower-cased token."""
        to_reduce = False
        if not isinstance(tokens, list):
            tokens = [tokens]
            to_reduce = True
        if not lower_case_backup:
            indices = [self.token_to_idx.get(t, 0) for t in tokens]
        else:
            indices = [self.token_to_idx.get(
                t, self.token_to_idx.get(t.lower(), 0)) for t in tokens]
        import numpy as _np
        vecs = self._idx_to_vec.asnumpy()[_np.asarray(indices)]
        out = nd.array(vecs)
        return out[0] if to_reduce else out

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of existing tokens (reference semantics:
        unknown tokens are an error)."""
        assert self._idx_to_vec is not None, \
            "The property `idx_to_vec` has not been properly set."
        if not isinstance(tokens, list) or len(tokens) == 1:
            assert isinstance(new_vectors, nd.NDArray) and \
                len(new_vectors.shape) in (1, 2), \
                "`new_vectors` must be a 1-D or 2-D NDArray if `tokens` " \
                "is a single token."
            if not isinstance(tokens, list):
                tokens = [tokens]
            if len(new_vectors.shape) == 1:
                new_vectors = new_vectors.expand_dims(0)
        else:
            assert isinstance(new_vectors, nd.NDArray) and \
                len(new_vectors.shape) == 2, \
                "`new_vectors` must be a 2-D NDArray if `tokens` is a " \
                "list of multiple strings."
        assert new_vectors.shape == (len(tokens), self.vec_len), \
            "The length of `new_vectors` must be equal to the number of " \
            "`tokens` and the width of `new_vectors` must be equal to " \
            "the dimension of embeddings"
        indices = []
        for token in tokens:
            if token in self.token_to_idx:
                indices.append(self.token_to_idx[token])
            else:
                raise ValueError(
                    "Token %r is unknown. To update the embedding vector "
                    "for an unknown token, please specify it explicitly "
                    "as the `unknown_token` %r in `tokens`."
                    % (token, self.unknown_token))
        vecs = self._idx_to_vec.asnumpy().copy()
        vecs[indices] = new_vectors.asnumpy()
        self._idx_to_vec = nd.array(vecs)

    # keep the reference's underscore alias working
    @staticmethod
    def _get_pretrained_file_names(embedding_name=None):
        return get_pretrained_file_names(embedding_name)


@register
class GloVe(TokenEmbedding):
    """Reference: embedding.GloVe — Common Crawl / Wikipedia GloVe text
    files (`glove.<corpus>.<dim>d.txt`)."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=None, init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        root = embedding_root or self._default_root()
        path = self._resolve_pretrained_path(root, pretrained_file_name)
        if vocabulary is not None:
            self._index_tokens_from_vocabulary(vocabulary)
            whole = type(self).__new__(type(self))
            TokenEmbedding.__init__(whole)
            whole._load_embedding(path, " ", init_unknown_vec)
            self._set_idx_to_vec_by_embeddings(
                [whole], len(self), self.idx_to_token)
        else:
            self._load_embedding(path, " ", init_unknown_vec)


@register
class FastText(TokenEmbedding):
    """Reference: embedding.FastText — `wiki.<lang>.vec` files (first
    line is a "count dim" header)."""

    pretrained_file_names = (
        "wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec", "wiki.de.vec",
        "wiki.fr.vec", "wiki.es.vec", "wiki.ja.vec", "wiki.ru.vec",
        "crawl-300d-2M.vec")

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=None, init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        root = embedding_root or self._default_root()
        path = self._resolve_pretrained_path(root, pretrained_file_name)
        if vocabulary is not None:
            self._index_tokens_from_vocabulary(vocabulary)
            whole = type(self).__new__(type(self))
            TokenEmbedding.__init__(whole)
            whole._load_embedding(path, " ", init_unknown_vec)
            self._set_idx_to_vec_by_embeddings(
                [whole], len(self), self.idx_to_token)
        else:
            self._load_embedding(path, " ", init_unknown_vec)


@register
class CustomEmbedding(TokenEmbedding):
    """Reference: embedding.CustomEmbedding — user-supplied embedding
    file: ``token<elem_delim>v1<elem_delim>v2...`` per line."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        if vocabulary is not None:
            self._index_tokens_from_vocabulary(vocabulary)
            whole = TokenEmbedding()
            whole._load_embedding(pretrained_file_path, elem_delim,
                                  init_unknown_vec, encoding)
            self._set_idx_to_vec_by_embeddings(
                [whole], len(self), self.idx_to_token)
        else:
            self._load_embedding(pretrained_file_path, elem_delim,
                                 init_unknown_vec, encoding)


class CompositeEmbedding(TokenEmbedding):
    """Reference: embedding.CompositeEmbedding — index a vocabulary with
    the CONCATENATION of multiple token embeddings' vectors."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        for e in token_embeddings:
            assert isinstance(e, TokenEmbedding), \
                "The parameter `token_embeddings` must be an instance or " \
                "a list of instances of `TokenEmbedding`"
        self._vocab = vocabulary
        self._index_tokens_from_vocabulary(vocabulary)
        self._vec_len = 0
        self._idx_to_vec = None
        self._set_idx_to_vec_by_embeddings(
            token_embeddings, len(self), self.idx_to_token)
