"""Text vocabulary (reference: python/mxnet/contrib/text/vocab.py).

Indexing convention matches the reference exactly: index 0 is the
unknown token, reserved tokens follow, then corpus tokens sorted by
descending frequency (ties broken alphabetically for determinism).
"""
from collections import Counter

__all__ = ["Vocabulary"]


class Vocabulary:
    """Reference: vocab.Vocabulary — token/index mappings built from a
    ``collections.Counter``."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0, "`min_freq` must be set to a positive value"
        if reserved_tokens is not None:
            reserved_set = set(reserved_tokens)
            assert unknown_token not in reserved_set, \
                "`reserved_tokens` must not contain the `unknown_token`"
            assert len(reserved_set) == len(reserved_tokens), \
                "`reserved_tokens` must not contain duplicates"
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens is not None else None)
        self._index_unknown_and_reserved_tokens()
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_unknown_and_reserved_tokens(self):
        self._idx_to_token = [self._unknown_token]
        if self._reserved_tokens is not None:
            self._idx_to_token.extend(self._reserved_tokens)
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        assert isinstance(counter, Counter), \
            "`counter` must be an instance of collections.Counter"
        unknown_and_reserved = set(self._idx_to_token)
        # descending frequency, alphabetical within a frequency class
        token_freqs = sorted(counter.items(), key=lambda x: x[0])
        token_freqs.sort(key=lambda x: x[1], reverse=True)
        token_cap = len(unknown_and_reserved) + (
            len(counter) if most_freq_count is None else most_freq_count)
        for token, freq in token_freqs:
            if freq < min_freq or len(self._idx_to_token) == token_cap:
                break
            if token not in unknown_and_reserved:
                self._idx_to_token.append(token)
                self._token_to_idx[token] = len(self._idx_to_token) - 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token (or list of tokens) → index (or list of indices);
        unknown tokens map to index 0 (reference semantics)."""
        to_reduce = False
        if not isinstance(tokens, list):
            tokens = [tokens]
            to_reduce = True
        indices = [self._token_to_idx.get(t, 0) for t in tokens]
        return indices[0] if to_reduce else indices

    def to_tokens(self, indices):
        """Index (or list of indices) → token (or list of tokens)."""
        to_reduce = False
        if not isinstance(indices, list):
            indices = [indices]
            to_reduce = True
        max_idx = len(self._idx_to_token) - 1
        tokens = []
        for idx in indices:
            if not isinstance(idx, int) or idx > max_idx:
                raise ValueError(
                    "Token index %s in the provided `indices` is invalid."
                    % idx)
            tokens.append(self._idx_to_token[idx])
        return tokens[0] if to_reduce else tokens
