"""Text token-counting utilities (reference:
python/mxnet/contrib/text/utils.py)."""
import re
from collections import Counter

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Reference: utils.count_tokens_from_str — split `source_str` on the
    token/sequence delimiters and tally tokens into a Counter (optionally
    updating an existing one in place)."""
    source_str = filter(None,
                        re.split(token_delim + "|" + seq_delim, source_str))
    if to_lower:
        source_str = [t.lower() for t in source_str]
    if counter_to_update is None:
        return Counter(source_str)
    counter_to_update.update(source_str)
    return counter_to_update
