"""mx.contrib.nd: contrib op namespace over NDArrays.

Reference: python/mxnet/contrib/ndarray generated namespace — every
`_contrib_*` registry op appears here without the prefix (MultiBoxPrior,
box_nms, ROIAlign, interleaved attention ops, ...), plus the control-flow
combinators.
"""
from __future__ import annotations

import sys

from ..ops import registry as _registry
from ..ndarray.ndarray import invoke
from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401


def _make(opname):
    def fn(*args, out=None, **kwargs):
        return invoke(opname, *args, out=out, **kwargs)
    fn.__name__ = opname
    fn.__doc__ = _registry.get_op(opname).doc
    return fn


_this = sys.modules[__name__]
for _name in _registry.list_ops():
    if _name.startswith("_contrib_"):
        _short = _name[len("_contrib_"):]
        if _short.isidentifier() and not hasattr(_this, _short):
            setattr(_this, _short, _make(_name))
# detection/spatial ops registered under bare names are contrib surface too
for _name in ("MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection",
              "ROIAlign", "box_iou", "box_nms"):
    if not hasattr(_this, _name):
        try:
            setattr(_this, _name, _make(_name))
        except KeyError:
            pass
