"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Import as ``import mxnet_tpu as mx`` — the public surface mirrors the
reference (`python/mxnet/__init__.py`): mx.nd (+sparse), mx.np/mx.npx,
mx.sym, mx.mod, mx.autograd, mx.gluon, mx.optimizer, mx.kvstore, mx.io,
mx.image, mx.recordio, mx.metric, mx.amp, mx.profiler, mx.runtime,
mx.callback, mx.monitor, mx.model, mx.init, mx.random, device helpers —
rebuilt on JAX/XLA/PJRT (see SURVEY.md; README "Status" lists the scope
cuts).
"""
from __future__ import annotations

__version__ = "0.1.0"

from .base import MXNetError, get_env, set_env, environment

# Honor an explicit CPU pin (MX_FORCE_CPU=1 / JAX_PLATFORMS=cpu) at import:
# PJRT plugins can force-override the platform list via jax.config.update,
# ignoring the env var, and a backend probe on a wedged accelerator tunnel
# blocks forever.  Doing this here covers subprocesses (im2rec, bench
# children, launchers) that inherit only the environment.
from .base import cpu_pinned_by_user as _cpu_pinned, pin_cpu as _pin_cpu
if _cpu_pinned():
    _pin_cpu()
from .device import (Context, Device, cpu, gpu, tpu, cpu_pinned, num_gpus,
                     num_tpus, current_context, current_device,
                     tpu_memory_info, gpu_memory_info)
from . import runtime
from . import engine
from . import programs
from . import ops
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .ndarray import NDArray, waitall

from . import amp
from . import profiler
from . import visualization
from . import visualization as viz
from . import onnx
from . import numpy as np
from . import npx
from . import recordio
from . import io
from . import image
from . import symbol
from . import name
from . import symbol as sym
from .symbol import AttrScope
from . import contrib
from . import subgraph
from . import initializer
from . import initializer as init
from . import metric
from . import lr_scheduler
from . import optimizer
from . import kvstore
from . import kvstore as kv
from . import gluon
from . import parallel
from . import callback
from . import checkpoint
from . import fault
from . import health
from . import model
from . import monitor
from . import module
from . import module as mod
from . import rnn
from . import util
from . import device as context

# compat: the reference's context.py is a REAL module — register the alias
# so `import mxnet_tpu.context` / `from mxnet_tpu.context import Context`
# work like they do upstream
import sys as _sys
_sys.modules[__name__ + ".context"] = context
from . import operator
from . import attribute
from . import npx as numpy_extension    # 2.x alias: mx.numpy_extension IS npx
_sys.modules[__name__ + ".numpy_extension"] = numpy_extension
from . import tpu_kernel

# Subsystems land milestone-by-milestone (SURVEY.md §7.1); this list grows
# until it covers the reference's full `python/mxnet/__init__.py` surface.
from . import test_utils
