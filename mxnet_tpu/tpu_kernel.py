"""mx.tpu_kernel: user-authored TPU kernels (the RTC equivalent).

Reference: python/mxnet/rtc.py (CudaModule, CudaKernel — runtime-compiled
CUDA via NVRTC, launched with explicit grid/block dims), src/common/rtc.cc.

TPU-native design: the user writes a *Pallas* kernel body instead of CUDA C
— Mosaic compiles it for the MXU/VPU the way NVRTC compiled CUDA for SMs.
``Kernel`` plays CudaKernel (explicit launch over NDArrays: grid ≙ the
pallas grid, BlockSpecs ≙ block dims + shared-mem tiling); ``register``
additionally installs the kernel as a first-class framework op so it
dispatches like any built-in (usable from nd/gluon, differentiable when a
``grad`` is supplied — the role FGradient plays for built-ins).

On non-TPU backends kernels run in Pallas ``interpret`` mode, the same
"works everywhere, fast on the target" posture the reference's RTC had
(CUDA-only there; here CPU interprets, TPU compiles).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["Kernel", "kernel", "register"]


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _as_shape_structs(out_shape, out_dtype):
    """Normalize (shape(s), dtype(s)) into ShapeDtypeStruct(s)."""
    if isinstance(out_shape, jax.ShapeDtypeStruct):
        return out_shape, True
    if (isinstance(out_shape, (list, tuple)) and out_shape
            and isinstance(out_shape[0], (list, tuple, jax.ShapeDtypeStruct))):
        dts = (out_dtype if isinstance(out_dtype, (list, tuple))
               else [out_dtype] * len(out_shape))
        structs = tuple(
            s if isinstance(s, jax.ShapeDtypeStruct)
            else jax.ShapeDtypeStruct(tuple(s), _np.dtype(d or _np.float32))
            for s, d in zip(out_shape, dts))
        return structs, False
    return jax.ShapeDtypeStruct(tuple(out_shape),
                                _np.dtype(out_dtype or _np.float32)), True


class Kernel:
    """A launchable Pallas kernel (reference: rtc.py CudaKernel.launch).

    ``body(*refs)`` receives input Refs then output Refs, Pallas-style.
    ``grid``/``in_specs``/``out_specs`` map onto pallas_call verbatim;
    grid plays the role of CudaKernel.launch's grid_dims and the
    BlockSpecs the role of block_dims + shared memory shaping."""

    def __init__(self, body: Callable, name: Optional[str] = None,
                 grid=None, in_specs=None, out_specs=None,
                 interpret: Optional[bool] = None, **pallas_kwargs):
        self.body = body
        self.name = name or getattr(body, "__name__", "tpu_kernel")
        self.grid = grid
        self.in_specs = in_specs
        self.out_specs = out_specs
        self.interpret = interpret
        self.pallas_kwargs = pallas_kwargs

    def _interpret_for(self, xs) -> bool:
        if self.interpret is not None:
            return self.interpret
        # decide from where the inputs actually live (the global default
        # backend can be TPU while the arrays are committed to host CPU)
        for x in xs:
            try:
                plat = next(iter(x.devices())).platform
                return plat not in ("tpu", "axon")
            except Exception:
                continue  # tracer: no committed device, fall through
        return not _on_tpu()

    def _build(self, structs, interpret: bool) -> Callable:
        import jax.experimental.pallas as pl
        kw = dict(self.pallas_kwargs)
        if self.grid is not None:
            kw["grid"] = self.grid
        if self.in_specs is not None:
            kw["in_specs"] = self.in_specs
        if self.out_specs is not None:
            kw["out_specs"] = self.out_specs
        return pl.pallas_call(self.body, out_shape=structs,
                              interpret=interpret, **kw)

    def _call_jax(self, out_shape, *xs, out_dtype=None):
        structs, single = _as_shape_structs(
            out_shape, out_dtype or (xs[0].dtype if xs else _np.float32))
        return self._build(structs, self._interpret_for(xs))(*xs), single

    def _call_traced(self, structs, *xs):
        """Inside a jit trace the inputs carry no committed device; defer
        the interpret-vs-Mosaic choice to lowering time, per platform."""
        if self.interpret is not None:
            return self._build(structs, self.interpret)(*xs)
        from jax import lax as _lax
        return _lax.platform_dependent(*xs,
                                       cpu=self._build(structs, True),
                                       default=self._build(structs, False))

    def launch(self, args: Sequence, out_shape,
               out_dtype=None) -> Union[Any, Tuple]:
        """Launch over NDArrays; returns NDArray(s) on the args' context."""
        from .ndarray import ndarray as _ndmod
        from .ndarray.ndarray import NDArray
        from .device import current_context
        if _ndmod._sym_tracer is not None:
            raise MXNetError(
                "Kernel.launch bypasses the op registry and cannot be "
                "traced into symbol.json — use tpu_kernel.register() to "
                "make the kernel a named, exportable op")
        nd_in = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
                 for a in args]
        ctx = nd_in[0].context if nd_in else current_context()
        outs, single = self._call_jax(out_shape, *[x._jax for x in nd_in],
                                      out_dtype=out_dtype)
        if single:
            return NDArray(outs, ctx=ctx)
        return tuple(NDArray(o, ctx=ctx) for o in outs)

    def __call__(self, *args, out_shape=None, out_dtype=None):
        if out_shape is None:
            raise MXNetError("Kernel() requires out_shape=")
        return self.launch(list(args), out_shape, out_dtype)


def kernel(name: Optional[str] = None, *, grid=None, in_specs=None,
           out_specs=None, interpret: Optional[bool] = None,
           **pallas_kwargs):
    """Decorator form: ``@mx.tpu_kernel.kernel(grid=...)`` over a Pallas
    body returns a launchable :class:`Kernel`."""

    def _wrap(body: Callable) -> Kernel:
        return Kernel(body, name=name, grid=grid, in_specs=in_specs,
                      out_specs=out_specs, interpret=interpret,
                      **pallas_kwargs)

    return _wrap


def register(name: str, *, out_shape_fn: Callable,
             grad: Optional[Callable] = None, grid=None, in_specs=None,
             out_specs=None, interpret: Optional[bool] = None,
             aliases: Sequence[str] = (), **pallas_kwargs):
    """Register a Pallas kernel as a framework op: after

        @mx.tpu_kernel.register("my_op", out_shape_fn=lambda *xs: xs[0])

    ``mx.nd.my_op(...)`` dispatches it like a built-in (jit-cached,
    tape-recorded).  ``out_shape_fn(*avals) -> ShapeDtypeStruct(s)``
    computes output shapes from input avals (the FInferShape role).
    ``grad(cotangents, *inputs) -> input-cotangent tuple`` supplies the
    backward (FGradient); without it the op is marked non-differentiable.
    """
    from .ops import registry as _registry

    def _wrap(body: Callable):
        k = Kernel(body, name=name, grid=grid, in_specs=in_specs,
                   out_specs=out_specs, interpret=interpret, **pallas_kwargs)

        def impl(*xs):
            avals = [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs]
            structs, _ = _as_shape_structs(
                out_shape_fn(*avals), xs[0].dtype if xs else _np.float32)
            return k._call_traced(structs, *xs)

        if grad is not None:
            fwd_impl = jax.custom_vjp(impl)

            def _f(*xs):
                return impl(*xs), xs

            def _b(res, cts):
                # single-output vjp hands the cotangent bare; the user grad
                # contract is always a tuple (like out_grad lists in FGradient)
                cts_t = cts if isinstance(cts, (tuple, list)) else (cts,)
                return tuple(grad(cts_t, *res))

            fwd_impl.defvjp(_f, _b)
            fn = fwd_impl
        else:
            fn = impl
        fn.__name__ = name
        fn.__doc__ = body.__doc__ or ("user tpu_kernel %s" % name)
        _registry.register(name, fn, differentiable=grad is not None,
                           aliases=aliases, replace=True)
        # surface on the live mx.nd namespace like generated op wrappers
        import sys
        ndmod = sys.modules.get("mxnet_tpu.ndarray")
        if ndmod is not None and not hasattr(ndmod, name):
            setattr(ndmod, name, ndmod._make_op_func(name))
        return k

    return _wrap
