"""INT8 quantization ops.

Reference: src/operator/quantization/quantize.cc (_contrib_quantize),
quantize_v2.cc, dequantize.cc, requantize.cc,
quantized_fully_connected.cc, quantized_conv.cc, quantized_pooling.cc,
quantized_flatten.cc, quantized_activation.cc.

TPU-native design: int8 GEMM/conv run on the MXU via
``lax.dot_general``/``conv_general_dilated`` with
``preferred_element_type=int32`` — the role cuDNN/cuBLAS int8 paths (and
oneDNN's s8s8s32) play in the reference.  Quantization follows MXNet's
convention: int8 is SYMMETRIC (scale = 127 / max|range|, zero-point 0,
which is what keeps int8×int8→int32 a plain matmul on the systolic
array), uint8 is affine with zero-point 0 over [0, max].  Every
quantized op carries (min, max) calibration scalars alongside the data
tensor and returns its own output range, exactly like the reference's
3-ary outputs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["grad_compress_block", "quantize_int8_blocks",
           "dequantize_int8_blocks", "roundtrip_int8_blocks",
           "dequant_sum_requant_int8", "quantize_2bit_ef",
           "pack_2bit_words", "unpack_2bit_words", "int8_wire_bytes",
           "two_bit_wire_bytes"]

_INT8_MAX = 127.0
_UINT8_MAX = 255.0


def _range_scale(mn, mx, out_type="int8"):
    """MXNet FloatToQuantized convention: symmetric for int8."""
    mn = jnp.asarray(mn, jnp.float32).reshape(())
    mx = jnp.asarray(mx, jnp.float32).reshape(())
    if out_type == "uint8":
        real_range = jnp.maximum(mx, 1e-30)
        return _UINT8_MAX / real_range
    real_range = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-30)
    return _INT8_MAX / real_range


@register("_contrib_quantize", num_outputs=3, differentiable=False,
          aliases=["quantize"])
def _quantize(data, min_range, max_range, out_type="int8"):
    """float → int8/uint8 with a provided calibration range (reference:
    quantize.cc QuantizeCompute)."""
    scale = _range_scale(min_range, max_range, out_type)
    if out_type == "uint8":
        q = jnp.clip(jnp.rint(data * scale), 0, _UINT8_MAX).astype(jnp.uint8)
        return q, jnp.zeros((1,), jnp.float32), jnp.reshape(
            jnp.asarray(max_range, jnp.float32), (1,))
    q = jnp.clip(jnp.rint(data * scale), -_INT8_MAX, _INT8_MAX)
    q = q.astype(jnp.int8)
    amax = _INT8_MAX / scale
    return (q, jnp.reshape(-amax, (1,)), jnp.reshape(amax, (1,)))


@register("_contrib_quantize_v2", num_outputs=3, differentiable=False,
          aliases=["quantize_v2"])
def _quantize_v2(data, out_type="int8", min_calib_range=None,
                 max_calib_range=None):
    """Like quantize but computes the range from the data when no
    calibrated range is given (reference: quantize_v2.cc)."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(data, mn, mx, out_type=out_type)


@register("_contrib_dequantize", differentiable=False,
          aliases=["dequantize"])
def _dequantize(data, min_range, max_range, out_type="float32"):
    """int8/uint8/int32 → float (reference: dequantize.cc)."""
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx = jnp.asarray(max_range, jnp.float32).reshape(())
    if data.dtype == jnp.uint8:
        scale = jnp.maximum(mx, 1e-30) / _UINT8_MAX
        return data.astype(jnp.float32) * scale
    qmax = {jnp.int8.dtype: _INT8_MAX,
            jnp.int32.dtype: 2147483647.0}.get(jnp.dtype(data.dtype),
                                               _INT8_MAX)
    real_range = jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-30)
    return data.astype(jnp.float32) * (real_range / qmax)


@register("_contrib_requantize", num_outputs=3, differentiable=False,
          aliases=["requantize"])
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    """int32 accumulator → int8 (reference: requantize.cc).  With no
    calibrated range, uses the int32 tensor's actual range."""
    f = _dequantize(data, min_range, max_range)
    if min_calib_range is None or max_calib_range is None:
        mn, mx = jnp.min(f), jnp.max(f)
    else:
        mn = jnp.asarray(min_calib_range, jnp.float32)
        mx = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(f, mn, mx, out_type="int8")


def _deq_scale(mn, mx, dtype):
    if dtype == jnp.uint8.dtype:
        return jnp.maximum(jnp.asarray(mx, jnp.float32).reshape(()), 1e-30) \
            / _UINT8_MAX
    mn = jnp.asarray(mn, jnp.float32).reshape(())
    mx = jnp.asarray(mx, jnp.float32).reshape(())
    return jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-30) / _INT8_MAX


@register("_contrib_quantized_fully_connected", num_outputs=3,
          differentiable=False, aliases=["quantized_fully_connected"])
def _quantized_fc(data, weight, bias, min_data, max_data, min_weight,
                  max_weight, min_bias=None, max_bias=None, num_hidden=None,
                  no_bias=False, flatten=True):
    """int8 GEMM on the MXU: int8×int8→int32 dot, bias folded in at the
    accumulator scale (reference: quantized_fully_connected.cc)."""
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    # uint8 activations must NOT be cast to int8 (values >127 would wrap
    # modulo 256): widen both sides to int32 and accumulate in int32 —
    # correct u8×s8 math at the cost of leaving the s8s8 MXU path.
    lt = jnp.int32 if x.dtype == jnp.uint8.dtype else jnp.int8
    acc = lax.dot_general(
        x.astype(lt), weight.astype(lt),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    sx = _deq_scale(min_data, max_data, x.dtype)
    sw = _deq_scale(min_weight, max_weight, jnp.int8.dtype)
    out_scale = sx * sw  # one int32 step == this many float units
    if bias is not None and not no_bias:
        sb = _deq_scale(min_bias, max_bias, jnp.int8.dtype)
        b32 = jnp.rint(bias.astype(jnp.float32) * (sb / out_scale))
        acc = acc + b32.astype(jnp.int32)
    amax = 2147483647.0 * out_scale
    return acc, jnp.reshape(-amax, (1,)), jnp.reshape(amax, (1,))


@register("_contrib_quantized_conv", num_outputs=3, differentiable=False,
          aliases=["quantized_conv"])
def _quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                    max_weight, min_bias=None, max_bias=None, kernel=None,
                    stride=None, dilate=None, pad=None, num_filter=None,
                    num_group=1, no_bias=False, layout=None,
                    cudnn_tune=None, cudnn_off=False, workspace=1024):
    """int8 convolution accumulating in int32 on the MXU (reference:
    quantized_conv.cc; NCHW/OIHW layouts like the float op)."""
    n = len(kernel)
    stride = tuple(stride) if stride else (1,) * n
    dilate = tuple(dilate) if dilate else (1,) * n
    pad = tuple(pad) if pad else (0,) * n
    spatial = "DHW"[-n:] if n != 2 else "HW"
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    # see _quantized_fc: uint8 data would wrap under an int8 cast
    lt = jnp.int32 if data.dtype == jnp.uint8.dtype else jnp.int8
    acc = lax.conv_general_dilated(
        data.astype(lt), weight.astype(lt),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    sx = _deq_scale(min_data, max_data, data.dtype)
    sw = _deq_scale(min_weight, max_weight, jnp.int8.dtype)
    out_scale = sx * sw
    if bias is not None and not no_bias:
        sb = _deq_scale(min_bias, max_bias, jnp.int8.dtype)
        b32 = jnp.rint(bias.astype(jnp.float32) * (sb / out_scale))
        acc = acc + b32.astype(jnp.int32).reshape((1, -1) + (1,) * n)
    amax = 2147483647.0 * out_scale
    return acc, jnp.reshape(-amax, (1,)), jnp.reshape(amax, (1,))


@register("_contrib_quantized_pooling", num_outputs=3, differentiable=False,
          aliases=["quantized_pooling"])
def _quantized_pooling(data, min_data, max_data, kernel=None,
                       pool_type="max", global_pool=False, stride=None,
                       pad=None, pooling_convention="valid",
                       count_include_pad=True, cudnn_off=False, layout=None,
                       p_value=2):
    """Pooling stays in the quantized domain — ranges pass through
    (reference: quantized_pooling.cc)."""
    from .nn import _pooling
    if pool_type == "max":
        out = _pooling(data.astype(jnp.int32), kernel=kernel,
                       pool_type="max", global_pool=global_pool,
                       stride=stride, pad=pad,
                       pooling_convention=pooling_convention,
                       count_include_pad=count_include_pad)
        out = out.astype(data.dtype)
    else:  # avg pooling must average in a wider type
        out = _pooling(data.astype(jnp.float32), kernel=kernel,
                       pool_type=pool_type, global_pool=global_pool,
                       stride=stride, pad=pad,
                       pooling_convention=pooling_convention,
                       count_include_pad=count_include_pad)
        out = jnp.rint(out).astype(data.dtype)
    return (out, jnp.reshape(jnp.asarray(min_data, jnp.float32), (1,)),
            jnp.reshape(jnp.asarray(max_data, jnp.float32), (1,)))


@register("_contrib_quantized_flatten", num_outputs=3, differentiable=False,
          aliases=["quantized_flatten"])
def _quantized_flatten(data, min_data, max_data):
    return (data.reshape(data.shape[0], -1),
            jnp.reshape(jnp.asarray(min_data, jnp.float32), (1,)),
            jnp.reshape(jnp.asarray(max_data, jnp.float32), (1,)))


@register("_contrib_quantized_act", num_outputs=3, differentiable=False,
          aliases=["quantized_act"])
def _quantized_act(data, min_data, max_data, act_type="relu"):
    """relu in the int domain: clamp at the zero point (reference:
    quantized_activation.cc — only relu is supported there too)."""
    if act_type != "relu":
        raise ValueError("quantized activation supports only relu")
    out = jnp.maximum(data, 0).astype(data.dtype)
    mx_ = jnp.asarray(max_data, jnp.float32)
    return (out, jnp.zeros((1,), jnp.float32),
            jnp.reshape(jnp.maximum(mx_, 0.0), (1,)))


# ---------------------------------------------------------------------------
# quantized op tail (reference: src/operator/quantization/
# quantized_batch_norm.cc, quantized_elemwise_add.cc,
# quantized_elemwise_mul.cc, quantized_indexing_op.cc (embedding),
# quantized_concat.cc, calibrate.cc) and the intgemm bridge
# (src/operator/contrib/intgemm/*.cc — here the MXU plays VNNI's role).
# ---------------------------------------------------------------------------


@register("_contrib_quantized_batch_norm", num_outputs=3,
          differentiable=False, aliases=["quantized_batch_norm"])
def _quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                          min_data, max_data, eps=1e-3, momentum=0.9,
                          fix_gamma=True, use_global_stats=True, axis=1):
    """int8 BN folded to an affine per-channel op in the float domain, then
    requantized (reference: quantized_batch_norm.cc inference-only path)."""
    f = _dequantize(data, min_data, max_data)
    shape = [1] * f.ndim
    shape[axis] = -1
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = g / jnp.sqrt(moving_var + eps)
    out = (f - moving_mean.reshape(shape)) * inv.reshape(shape) \
        + beta.reshape(shape)
    omax = jnp.max(jnp.abs(out))
    return _quantize(out, -omax, omax, out_type="int8")


@register("_contrib_quantized_elemwise_add", num_outputs=3,
          differentiable=False, aliases=["quantized_elemwise_add"])
def _quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    f = _dequantize(lhs, lhs_min, lhs_max) + _dequantize(rhs, rhs_min,
                                                         rhs_max)
    amax = jnp.max(jnp.abs(f))
    return _quantize(f, -amax, amax, out_type="int8")


@register("_contrib_quantized_elemwise_mul", num_outputs=3,
          differentiable=False, aliases=["quantized_elemwise_mul"])
def _quantized_elemwise_mul(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    sl = _deq_scale(lhs_min, lhs_max, lhs.dtype)
    sr = _deq_scale(rhs_min, rhs_max, rhs.dtype)
    acc = lhs.astype(jnp.int32) * rhs.astype(jnp.int32)
    out_scale = sl * sr
    amax = 2147483647.0 * out_scale
    return acc, jnp.reshape(-amax, (1,)), jnp.reshape(amax, (1,))


@register("_contrib_quantized_embedding", num_outputs=3,
          differentiable=False, aliases=["quantized_embedding"])
def _quantized_embedding(data, weight, min_weight, max_weight,
                         input_dim=None, output_dim=None, dtype="float32"):
    rows = jnp.take(weight, data.astype(jnp.int32), axis=0)
    return (rows, jnp.reshape(jnp.asarray(min_weight, jnp.float32), (1,)),
            jnp.reshape(jnp.asarray(max_weight, jnp.float32), (1,)))


@register("_contrib_quantized_concat", num_outputs=3,
          differentiable=False, aliases=["quantized_concat"])
def _quantized_concat(*args, num_args=1, dim=1):
    """Concat in the quantized domain: inputs arrive interleaved
    (d0..dn, min0, max0, ..minn, maxn); requantize to the widest range."""
    n = num_args
    datas, mins, maxs = args[:n], args[n::2][:n], args[n + 1::2][:n]
    fs = [_dequantize(d, mn, mx) for d, mn, mx in zip(datas, mins, maxs)]
    f = jnp.concatenate(fs, axis=dim)
    amax = jnp.max(jnp.abs(f))
    return _quantize(f, -amax, amax, out_type="int8")


@register("_contrib_calibrate_entropy", num_outputs=2,
          differentiable=False, aliases=["calibrate_entropy"], no_jit=True)
def _calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """KL threshold from a collected histogram (reference: calibrate.cc).
    Host-side: the scan is control-flow heavy and calibration is offline."""
    import numpy as np
    h = np.asarray(hist, np.float64)
    edges = np.asarray(hist_edges, np.float64)
    centers = np.abs((edges[:-1] + edges[1:]) / 2)
    synth = np.repeat(centers, np.minimum(h.astype(np.int64), 1 << 16))
    from ..contrib.quantization import _get_optimal_threshold
    t = _get_optimal_threshold(synth, num_bins=min(len(h), 8001),
                               num_quantized_bins=num_quantized_bins)
    return (jnp.asarray([-t], jnp.float32), jnp.asarray([t], jnp.float32))


@register("_contrib_intgemm_maxabsolute", aliases=["intgemm_maxabsolute"],
          differentiable=False)
def _intgemm_maxabsolute(data):
    return jnp.max(jnp.abs(data)).reshape((1,))


@register("_contrib_intgemm_prepare_data", aliases=["intgemm_prepare_data"],
          differentiable=False)
def _intgemm_prepare_data(data, maxabs):
    scale = 127.0 / jnp.maximum(jnp.reshape(maxabs, ()), 1e-30)
    return jnp.clip(jnp.rint(data * scale), -127, 127).astype(jnp.int8)


@register("_contrib_intgemm_prepare_weight",
          aliases=["intgemm_prepare_weight"], differentiable=False)
def _intgemm_prepare_weight(weight, maxabs=None, already_quantized=False):
    if already_quantized:
        return weight.astype(jnp.int8)
    scale = 127.0 / jnp.maximum(jnp.reshape(maxabs, ()), 1e-30)
    return jnp.clip(jnp.rint(weight * scale), -127, 127).astype(jnp.int8)


@register("_contrib_intgemm_take_weight", aliases=["intgemm_take_weight"],
          differentiable=False)
def _intgemm_take_weight(weight, indices):
    return jnp.take(weight, indices.astype(jnp.int32), axis=0)


@register("_contrib_intgemm_fully_connected",
          aliases=["intgemm_fully_connected"], differentiable=False)
def _intgemm_fully_connected(data, weight, scaling, bias=None,
                             num_hidden=None, no_bias=False, flatten=True,
                             out_type="float32"):
    """int8×int8→int32 GEMM rescaled to float (reference: intgemm's
    Multiply + UnquantizeAndWrite callback; MXU int8 path here)."""
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    acc = lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * jnp.reshape(scaling, ())
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32)
    return out if out_type == "float32" else acc


# ---------------------------------------------------------------------------
# Gradient wire quantization (ISSUE 5: quantized bucket collectives).
#
# Role model: EQuARX (arXiv:2506.17615) — quantized AllReduce inside XLA —
# plus the reference's 2-bit gradient_compression.cc error-feedback scheme.
# These kernels compress the gradient-exchange payload (a flat fusion
# bucket, kvstore/bucketing.py) before it crosses ICI/DCN or the dist_async
# TCP wire:
#
#   * int8: SYMMETRIC per-block quantization (scale = max|block| / 127,
#     zero-point 0 — same convention as the inference ops above) with a
#     persistent device-resident float32 *error-feedback residual*: what a
#     step's quantization drops is carried into the next step's payload,
#     so gradient mass is delayed, never lost (sum of dequantized payloads
#     + final residual == sum of true gradients, exactly in f32 math).
#   * 2bit: the reference's ±threshold/0 levels, same residual contract,
#     plus a 16-codes-per-uint32 packed wire format for the TCP path.
#
# All kernels are jitted and donation-aware: the residual buffer is donated
# into the quantize step (it is dead the moment its replacement exists), so
# the hot path never holds two residual copies per bucket in HBM.
# ---------------------------------------------------------------------------

GRAD_BLOCK_DEFAULT = 256


def grad_compress_block() -> int:
    """Elements per int8 scale block (MX_GRAD_COMPRESS_BLOCK)."""
    from ..base import get_env
    try:
        return max(1, int(get_env("MX_GRAD_COMPRESS_BLOCK",
                                  GRAD_BLOCK_DEFAULT, int)))
    except (TypeError, ValueError):
        return GRAD_BLOCK_DEFAULT


def int8_wire_bytes(n: int, block: int) -> int:
    """Wire footprint of an n-element int8 payload: padded codes + one
    f32 scale per block."""
    nblocks = -(-n // block)
    return nblocks * block + 4 * nblocks


def two_bit_wire_bytes(n: int) -> int:
    """Wire footprint of the packed 2-bit format: 16 codes per uint32
    word + the f32 threshold scalar."""
    return 4 * (-(-n // 16)) + 4


def _quantize_int8_kernel(flat, residual, block):
    acc = flat.astype(jnp.float32) + residual
    n = acc.shape[0]
    pad = (-n) % block
    if pad:
        acc_p = jnp.concatenate([acc, jnp.zeros((pad,), jnp.float32)])
    else:
        acc_p = acc
    blocks = acc_p.reshape(-1, block)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.maximum(amax, 1e-30) / _INT8_MAX
    q = jnp.clip(jnp.rint(blocks / scales[:, None]), -_INT8_MAX, _INT8_MAX)
    deq = q * scales[:, None]
    new_res = (blocks - deq).reshape(-1)[:n]
    return (q.astype(jnp.int8).reshape(-1), scales.astype(jnp.float32),
            new_res)


_jit_cache: dict = {}


def _jitted(name, fn, donate=()):
    key = (name, donate)
    hit = _jit_cache.get(key)
    if hit is None:
        from ..programs import register_program
        parts = name if isinstance(name, tuple) else (name,)
        pname = "quant." + "_".join(str(p) for p in parts)
        hit = register_program(pname, fn, donate_argnums=donate)
        _jit_cache[key] = hit
    return hit


def quantize_int8_blocks(flat, residual, block=None, donate=True):
    """One error-feedback int8 quantization step over a flat payload.

    Returns ``(q, scales, new_residual)``: int8 codes padded to a block
    multiple, one f32 scale per block, and the residual to feed the NEXT
    step.  ``residual`` is DONATED by default — the caller must drop its
    reference (pass a fresh ``jnp.zeros`` on the first step); pass
    ``donate=False`` to keep it readable (the overlap session's
    rollback-checkpoint path)."""
    block = int(block or grad_compress_block())
    fn = _jitted(("q8", block),
                 functools.partial(_quantize_int8_kernel, block=block),
                 donate=(1,) if donate else ())
    return fn(flat, residual)


def _dequantize_int8_kernel(q, scales, n):
    block = q.shape[0] // scales.shape[0]
    out = (q.reshape(-1, block).astype(jnp.float32)
           * scales[:, None]).reshape(-1)
    return out[:n]


def dequantize_int8_blocks(q, scales, n):
    """Inverse of :func:`quantize_int8_blocks` (first `n` elements)."""
    fn = _jitted(("dq8", int(n)),
                 functools.partial(_dequantize_int8_kernel, n=int(n)))
    return fn(q, scales)


def _roundtrip_int8_kernel(flat, residual, block):
    q, scales, new_res = _quantize_int8_kernel(flat, residual, block)
    deq = _dequantize_int8_kernel(q, scales, flat.shape[0])
    return deq.astype(flat.dtype), new_res


def roundtrip_int8_blocks(flat, residual, block=None, donate=True):
    """Quantize→dequantize in ONE dispatch: what a single-worker exchange
    observes of int8 compression (the local stores' path).  Residual is
    donated by default, like :func:`quantize_int8_blocks`."""
    block = int(block or grad_compress_block())
    fn = _jitted(("rt8", block),
                 functools.partial(_roundtrip_int8_kernel, block=block),
                 donate=(1,) if donate else ())
    return fn(flat, residual)


def rs_block_bytes(n: int, block: int, fsdp: int) -> int:
    """Padded flat length of the reduce-scatter int8 grain: whole blocks
    per fsdp shard, so shard-local blockwise quantization IS logical
    blockwise quantization."""
    grain = block * max(1, int(fsdp))
    return -(-int(n) // grain) * grain


def rs_roundtrip_int8(flat_padded, residual, block, mesh, fsdp_axis):
    """Shard-local error-feedback int8 roundtrip over the fsdp axis
    (ISSUE 14): the payload arrives fsdp-sharded (the reduce-scatter
    grain), every chip quantizes ITS whole blocks against its own
    residual shard, and the dequantized payload stays fsdp-sharded for
    the ZeRO optimizer apply (XLA all-gathers later uses on demand).

    Implemented with ``shard_map`` — manual partitioning — rather than
    ``with_sharding_constraint`` on purpose: the auto-partitioner
    miscompiles the blockwise max/scale reductions of this kernel when
    their output sharding is constrained (observed on XLA:CPU, jax
    0.4.37: per-block scales come back multiplied by the size of the
    OTHER mesh axes — a psum where a max belongs).  Inside shard_map
    the blockwise math is local per chip, so there is nothing for the
    partitioner to get wrong; ``flat_padded`` must be block-aligned per
    shard (:func:`rs_block_bytes`).
    """
    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map
    from jax.sharding import PartitionSpec as _P
    spec = _P(fsdp_axis)
    local = functools.partial(_roundtrip_int8_kernel, block=block)
    return _shard_map(local, mesh=mesh, in_specs=(spec, spec),
                      out_specs=(spec, spec))(flat_padded, residual)


def _dequant_sum_requant_kernel(q, scales):
    """Scale-merged reduction of W workers' int8 payloads: dequantize each
    at its own per-block scale, sum, requantize the sum at a fresh merged
    scale — the EQuARX AllReduce body.  q: (W, nb*block) int8, scales:
    (W, nb) f32 → (nb*block int8, nb f32)."""
    w, nb = scales.shape
    block = q.shape[1] // nb
    f = jnp.sum(q.reshape(w, nb, block).astype(jnp.float32)
                * scales[:, :, None], axis=0)
    amax = jnp.max(jnp.abs(f), axis=1)
    out_scales = jnp.maximum(amax, 1e-30) / _INT8_MAX
    qo = jnp.clip(jnp.rint(f / out_scales[:, None]), -_INT8_MAX, _INT8_MAX)
    return qo.astype(jnp.int8).reshape(-1), out_scales.astype(jnp.float32)


def dequant_sum_requant_int8(q_stacked, scales_stacked):
    """Host-callable (unsharded) form of the merge kernel — the ICI store
    wraps the same body in a mesh-sharded jit for the real collective."""
    return _jitted(("dsr8",), _dequant_sum_requant_kernel)(
        q_stacked, scales_stacked)


def _quantize_2bit_kernel(grad, residual, threshold):
    acc = residual + grad
    q = jnp.where(acc >= threshold, threshold, 0.0) + \
        jnp.where(acc <= -threshold, -threshold, 0.0)
    q = q.astype(grad.dtype)
    return q, (acc - q).astype(grad.dtype)


def quantize_2bit_ef(grad, residual, threshold, donate=True):
    """Reference Quantize2BitImpl with error feedback, one jitted
    elementwise dispatch; residual is donated by default (see int8
    notes).  Returns (levels in {-t, 0, +t}, new residual)."""
    return _jitted(("q2",), _quantize_2bit_kernel,
                   donate=(1,) if donate else ())(
        grad, residual, jnp.asarray(threshold, grad.dtype))


def _pack_2bit_kernel(levels):
    flat = levels.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % 16
    codes = jnp.where(flat > 0, 2, jnp.where(flat < 0, 1, 0)).astype(
        jnp.uint32)
    if pad:
        codes = jnp.concatenate([codes, jnp.zeros((pad,), jnp.uint32)])
    shifts = (jnp.arange(16, dtype=jnp.uint32) * 2)[None, :]
    # shifted codes occupy disjoint bit lanes, so sum == bitwise-or
    return jnp.sum(codes.reshape(-1, 16) << shifts, axis=1,
                   dtype=jnp.uint32)


def pack_2bit_words(levels):
    """Device-side packed 2-bit wire format (16 codes per uint32 word,
    code i at bits [2i, 2i+1], 00=0 01=-t 10=+t — bit-compatible with the
    host pack in kvstore/gradient_compression.py)."""
    return _jitted(("p2",), _pack_2bit_kernel)(levels)


def _unpack_2bit_kernel(words, threshold, n):
    shifts = (jnp.arange(16, dtype=jnp.uint32) * 2)[None, :]
    codes = ((words[:, None] >> shifts) & 0x3).reshape(-1)[:n]
    return jnp.where(codes == 2, threshold,
                     jnp.where(codes == 1, -threshold, 0.0)).astype(
                         jnp.float32)


def unpack_2bit_words(words, threshold, n):
    """Inverse of :func:`pack_2bit_words` (first `n` codes)."""
    fn = _jitted(("u2", int(n)),
                 functools.partial(_unpack_2bit_kernel, n=int(n)))
    return fn(words, jnp.asarray(threshold, jnp.float32))


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the gradient-wire kernels' declared
# donation/HBM invariants.  The error-feedback residual is the donated
# state here — it is rewritten every step, and a dropped donation would
# keep BOTH generations of every bucket's residual live on TPU.  The
# builders run only inside `python -m tools.mxlint --contracts`.
# ---------------------------------------------------------------------------

_CONTRACT_N = 4096          # one mid-sized flat bucket payload


def _quant_contract_cases():
    from ..programs import ContractCase
    block = GRAD_BLOCK_DEFAULT
    f32 = jnp.float32
    flat = jax.ShapeDtypeStruct((_CONTRACT_N,), f32)
    res = jax.ShapeDtypeStruct((_CONTRACT_N,), f32)
    thr = jax.ShapeDtypeStruct((), f32)
    q8 = _jitted(("q8", block),
                 functools.partial(_quantize_int8_kernel, block=block),
                 donate=(1,))
    rt8 = _jitted(("rt8", block),
                  functools.partial(_roundtrip_int8_kernel, block=block),
                  donate=(1,))
    q2 = _jitted(("q2",), _quantize_2bit_kernel, donate=(1,))
    return [
        ContractCase("quant.q8_%d" % block, (flat, res), label="q8",
                     target=q8),
        ContractCase("quant.rt8_%d" % block, (flat, res), label="rt8",
                     target=rt8),
        ContractCase("quant.q2", (flat, res, thr), label="q2",
                     target=q2),
    ]


def _declare_quant_contracts():
    from ..programs import declare_contract
    declare_contract(
        "quant.gradient_wire", _quant_contract_cases,
        donate_argnums=(1,),
        temp_budget_bytes=1 << 20,
        description="int8/2bit error-feedback kernels: the residual "
                    "donates in-place (same shape+dtype out); codes/"
                    "scales/threshold survive the call")


_declare_quant_contracts()
