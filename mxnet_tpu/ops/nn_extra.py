"""NN long-tail layers.

Reference: src/operator/nn/lrn.cc (LRN), src/operator/tensor/
elemwise_unary_op_basic.cc (BlockGrad/stop_gradient), src/operator/
make_loss.cc (MakeLoss), src/operator/svm_output.cc (SVMOutput),
src/operator/softmax_activation.cc, src/operator/crop.cc (legacy Crop),
src/operator/nn/im2col.h (col2im), src/operator/contrib/sync_batch_norm.cc,
src/operator/contrib/batch_norm_relu.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


@register("LRN", aliases=["lrn"])
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response normalization across channels, NCHW (reference:
    lrn.cc LRNForward): x / (k + alpha/n * sum_window(x²))^beta."""
    sq = jnp.square(data.astype(jnp.float32))
    half = nsize // 2
    # window-sum over C via padded cumulative trick (static nsize)
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(sq)
    for i in range(nsize):
        acc = acc + lax.dynamic_slice_in_dim(padded, i, sq.shape[1], axis=1)
    norm = jnp.power(knorm + (alpha / nsize) * acc, beta)
    return (data.astype(jnp.float32) / norm).astype(data.dtype)


@register("BlockGrad", aliases=["stop_gradient", "block_grad"])
def _block_grad(data):
    return lax.stop_gradient(data)


@register("MakeLoss", aliases=["make_loss"])
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0,
               normalization="null"):
    """Identity forward; backward feeds grad_scale (reference:
    make_loss.cc).  Normalization 'batch'/'valid' divide like the
    reference."""
    scale = grad_scale
    if normalization == "batch":
        scale = grad_scale / data.shape[0]

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        if normalization == "valid":
            nvalid = jnp.maximum(
                jnp.sum((x > valid_thresh).astype(jnp.float32)), 1.0)
            return x, nvalid
        return x, None

    def bwd(res, g):
        s = scale if res is None else grad_scale / res
        return (g * s,)
    f.defvjp(fwd, bwd)
    return f(data)


@register("SVMOutput", aliases=["svm_output"])
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    """Forward is identity (scores); backward applies the hinge-loss
    gradient (reference: svm_output.cc)."""
    coef = regularization_coefficient

    @jax.custom_vjp
    def f(x, lab):
        return x

    def fwd(x, lab):
        return x, (x, lab)

    def bwd(res, g):
        x, lab = res
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), x.shape[-1],
                                dtype=x.dtype)
        sign = 2.0 * onehot - 1.0            # +1 at label, -1 elsewhere
        viol = (margin - sign * x) > 0
        dx = jnp.where(viol, -sign, 0.0)
        if not use_linear:                    # squared hinge
            dx = dx * 2.0 * jnp.maximum(margin - sign * x, 0.0)
        return (coef * dx.astype(x.dtype), jnp.zeros_like(lab))
    f.defvjp(fwd, bwd)
    return f(data, label)


@register("SoftmaxActivation", aliases=["softmax_activation"])
def _softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    flat = data.reshape(data.shape[0], -1)
    return jax.nn.softmax(flat, axis=-1).reshape(data.shape)


@register("Crop")  # NB lowercase "crop" stays an alias of slice (matrix.py),
def _crop_legacy(data, *like, offset=(0, 0), h_w=(0, 0), num_args=1,
                 center_crop=False):
    """Legacy Crop (reference: crop.cc): crop NCHW `data` to `like`'s
    spatial size (2-input form) or to h_w."""
    if like:
        th, tw = like[0].shape[2], like[0].shape[3]
    else:
        th, tw = h_w
    H, W = data.shape[2], data.shape[3]
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = offset
    return data[:, :, y0:y0 + th, x0:x0 + tw]


@register("col2im")
def _col2im(data, output_size=(1, 1), kernel=(1, 1), stride=(1, 1),
            dilate=(1, 1), pad=(0, 0)):
    """Inverse of im2col: scatter-add (B, C*kh*kw, L) patches back to
    (B, C, H, W) (reference: im2col.h col2im)."""
    kh, kw = kernel
    H, W = output_size
    B, CKK, L = data.shape
    C = CKK // (kh * kw)
    Ho = (H + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
    Wo = (W + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1
    x = data.reshape(B, C, kh, kw, Ho, Wo)
    Hp, Wp = H + 2 * pad[0], W + 2 * pad[1]
    out = jnp.zeros((B, C, Hp, Wp), data.dtype)
    for i in range(kh):
        for j in range(kw):
            yi = i * dilate[0]
            xi = j * dilate[1]
            ys = slice(yi, yi + Ho * stride[0], stride[0])
            xs = slice(xi, xi + Wo * stride[1], stride[1])
            out = out.at[:, :, ys, xs].add(x[:, :, i, j])
    return out[:, :, pad[0]:Hp - pad[0], pad[1]:Wp - pad[1]] \
        if pad[0] or pad[1] else out


@register("_contrib_BatchNormWithReLU", aliases=["BatchNormWithReLU"],
          num_outputs=3, aux_writeback={1: 3, 2: 4})
def _batch_norm_with_relu(data, gamma, beta, moving_mean, moving_var,
                          eps=1e-3, momentum=0.9, fix_gamma=True,
                          use_global_stats=False, axis=1):
    """Fused BatchNorm+ReLU (reference: batch_norm_relu.cc) — XLA fuses the
    relu into the normalization epilogue."""
    from .nn import _batch_norm
    out, new_mean, new_var = _batch_norm(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats, axis=axis)
    return jnp.maximum(out, 0), new_mean, new_var


@register("_contrib_SyncBatchNorm", aliases=["SyncBatchNorm"],
          num_outputs=3, aux_writeback={1: 3, 2: 4})
def _sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                     momentum=0.9, fix_gamma=True, use_global_stats=False,
                     ndev=1, key=None, axis_name=None):
    """Cross-device BatchNorm (reference: sync_batch_norm.cc).  Inside
    shard_map/pmap pass axis_name to psum the batch statistics over the
    data-parallel axis; single-device it equals BatchNorm."""
    red = tuple(i for i in range(data.ndim) if i != 1)
    x = data.astype(jnp.float32)
    if use_global_stats:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    else:
        mean = jnp.mean(x, axis=red)
        sq = jnp.mean(x * x, axis=red)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            sq = lax.pmean(sq, axis_name)
        var = sq - mean * mean
        new_mean = momentum * moving_mean + (1.0 - momentum) * mean
        new_var = momentum * moving_var + (1.0 - momentum) * var
    shape = [1] * data.ndim
    shape[1] = -1
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    out = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
    out = out * g.reshape(shape) + beta.reshape(shape)
    return out.astype(data.dtype), new_mean, new_var


@register("Convolution_v1", aliases=["convolution_v1"])
def _convolution_v1(data, weight, bias=None, kernel=(1, 1), stride=(),
                    dilate=(), pad=(), num_filter=1, num_group=1,
                    workspace=1024, no_bias=False, cudnn_tune=None,
                    cudnn_off=False, layout=None):
    """Legacy Convolution_v1 (reference: src/operator/convolution_v1.cc —
    kept as a distinct op for checkpoint compat; 2-D only, NCHW)."""
    from .nn import _convolution
    return _convolution(data, weight, bias, kernel=kernel,
                        stride=stride or (1, 1), dilate=dilate or (1, 1),
                        pad=pad or (0, 0), num_filter=num_filter,
                        num_group=num_group, no_bias=no_bias)


@register("Pooling_v1", aliases=["pooling_v1"])
def _pooling_v1(data, kernel=(1, 1), pool_type="max", global_pool=False,
                stride=(), pad=()):
    """Legacy Pooling_v1 (reference: src/operator/pooling_v1.cc): always
    the CEIL ('full') output-shape convention — the semantic difference
    that kept it a separate op."""
    from .nn import _pooling
    return _pooling(data, kernel=kernel, pool_type=pool_type,
                    global_pool=global_pool, stride=stride or kernel,
                    pad=pad or (0, 0), pooling_convention="full")
