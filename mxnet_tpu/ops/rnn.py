"""Fused RNN op (RNN/LSTM/GRU, multi-layer, bidirectional).

Reference: src/operator/rnn.cc (RNNParam, NNVM_REGISTER_OP(RNN)) and the
cuDNN path src/operator/cudnn_rnn-inl.h.  MXNet exposes ONE fused op taking
the packed parameter vector in cuDNN layout; Gluon's rnn_layer packs its
per-layer parameters into that vector.

TPU-native (SURVEY.md §2.1 cuDNN row: "RNN → lax.scan cell loop"): each
layer/direction is a `lax.scan` over time whose body is one fused
matmul+gate-nonlinearity step; XLA pipelines the h2h matmul chain onto the
MXU.  The packed layout is preserved bit-for-bit so reference checkpoints
load (SURVEY.md §7.2 hard part 5):
  for layer ∈ 0..L-1, direction ∈ (fwd[, bwd]):  W_i2h (G*H, I), W_h2h (G*H, H)
  then same order again for biases:              b_i2h (G*H),   b_h2h (G*H)
Gate order: LSTM i,f,g,o; GRU r,z,n (cuDNN order, matching MXNet).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _cell_step(mode):
    """Returns step(h_prev, c_prev, x_proj, w_hh, b_hh) -> (h, c)."""
    if mode == "rnn_relu":
        def step(h, c, xp, w_hh, b_hh):
            return jax.nn.relu(xp + h @ w_hh.T + b_hh), c
    elif mode == "rnn_tanh":
        def step(h, c, xp, w_hh, b_hh):
            return jnp.tanh(xp + h @ w_hh.T + b_hh), c
    elif mode == "lstm":
        def step(h, c, xp, w_hh, b_hh):
            gates = xp + h @ w_hh.T + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            return o * jnp.tanh(c_new), c_new
    elif mode == "gru":
        def step(h, c, xp, w_hh, b_hh):
            # cuDNN GRU: r,z,n with n = tanh(x_n + r * (h @ Whn + bhn))
            hp = h @ w_hh.T + b_hh
            x_r, x_z, x_n = jnp.split(xp, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(x_r + h_r)
            z = jax.nn.sigmoid(x_z + h_z)
            n = jnp.tanh(x_n + r * h_n)
            return (1 - z) * n + z * h, c
    else:
        raise ValueError("unknown RNN mode %r" % mode)
    return step


def _seq_reverse(x, lens):
    """Reverse each sample's first `lens[n]` steps of (T, N, ...) in place."""
    T = x.shape[0]
    steps = jnp.arange(T)[:, None]
    src = jnp.where(steps < lens[None, :], lens[None, :] - 1 - steps, steps)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=0)


def _run_layer(x, h0, c0, w_ih, w_hh, b_ih, b_hh, mode, reverse=False,
               seq_len=None):
    """x: (T, N, I) → (T, N, H); one direction of one layer.

    With seq_len (N,): states freeze past each sample's length (so final
    h/c are the last VALID step's), padded outputs are zeroed, and the
    reverse direction runs over the per-sample-reversed valid region —
    the reference RNN op's use_sequence_length semantics."""
    step = _cell_step(mode)
    if seq_len is not None and reverse:
        x = _seq_reverse(x, seq_len)
        reverse = False
    # hoist the input projection out of the scan: one big (T*N, I)@(I, G*H)
    # matmul the MXU tiles well, leaving only the h2h matmul sequential
    xp = jnp.einsum("tni,gi->tng", x, w_ih) + b_ih

    if seq_len is None:
        def body(carry, xpt):
            h, c = carry
            h_new, c_new = step(h, c, xpt, w_hh, b_hh)
            return (h_new, c_new), h_new

        (h_T, c_T), ys = lax.scan(body, (h0, c0), xp, reverse=reverse)
        return ys, h_T, c_T

    T = x.shape[0]

    def body(carry, inp):
        h, c = carry
        xpt, t = inp
        h_new, c_new = step(h, c, xpt, w_hh, b_hh)
        valid = (t < seq_len)[:, None]
        h_keep = jnp.where(valid, h_new, h)
        c_keep = jnp.where(valid, c_new, c)
        return (h_keep, c_keep), jnp.where(valid, h_new, 0).astype(h_new.dtype)

    (h_T, c_T), ys = lax.scan(body, (h0, c0), (xp, jnp.arange(T)))
    return ys, h_T, c_T


def _unpack_params(params, num_layers, bidirectional, input_size, state_size,
                   gates):
    """Static unpacking of the cuDNN-layout flat vector."""
    dirs = 2 if bidirectional else 1
    gh = gates * state_size
    shapes_w = []
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            shapes_w.append((gh, isz))
            shapes_w.append((gh, state_size))
    offset = 0
    weights = []
    for shp in shapes_w:
        n = shp[0] * shp[1]
        weights.append(params[offset:offset + n].reshape(shp))
        offset += n
    biases = []
    for _ in range(num_layers * dirs * 2):
        biases.append(params[offset:offset + gh])
        offset += gh
    return weights, biases


def rnn_param_size(num_layers, input_size, state_size, mode,
                   bidirectional=False):
    """Total packed-parameter length (reference: RNNParam size calc)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    gh = gates * state_size
    total = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * dirs
        total += dirs * gh * (isz + state_size)   # weights
    total += num_layers * dirs * 2 * gh           # biases
    return total


@register("RNN", aliases=["rnn"], num_outputs=3, needs_rng=True)
def _rnn(key, data, params, state, state_cell=None, sequence_length=None,
         state_size=0, num_layers=1, mode="lstm", bidirectional=False, p=0.0,
         state_outputs=True, lstm_state_clip_min=None,
         lstm_state_clip_max=None, use_sequence_length=False,
         projection_size=None, training=False):
    """data: (T, N, I) [MXNet TNC]; state: (L*D, N, H); LSTM adds
    state_cell; sequence_length (N,) activates variable-length handling
    when use_sequence_length=True (reference RNN op [1.7+]).
    Returns (output, state_h_out, state_cell_out)."""
    T, N, input_size = data.shape
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    weights, biases = _unpack_params(params, num_layers, bidirectional,
                                     input_size, state_size, gates)
    if state_cell is None:
        state_cell = jnp.zeros_like(state)
    seq_len = None
    if use_sequence_length and sequence_length is not None:
        seq_len = sequence_length.astype(jnp.int32)
    x = data
    h_outs, c_outs = [], []
    for layer in range(num_layers):
        ys = []
        for d in range(dirs):
            idx = layer * dirs + d
            w_ih = weights[2 * idx]
            w_hh = weights[2 * idx + 1]
            b_ih = biases[2 * idx]
            b_hh = biases[2 * idx + 1]
            y, h_T, c_T = _run_layer(x, state[idx], state_cell[idx], w_ih,
                                     w_hh, b_ih, b_hh, mode, reverse=(d == 1),
                                     seq_len=seq_len)
            if seq_len is not None and d == 1:
                y = _seq_reverse(y, seq_len)
            ys.append(y)
            h_outs.append(h_T)
            c_outs.append(c_T)
        x = ys[0] if dirs == 1 else jnp.concatenate(ys, axis=-1)
        if p > 0 and training and layer < num_layers - 1:
            key, sub = jax.random.split(key)
            keep = 1.0 - p
            mask = jax.random.bernoulli(sub, keep, x.shape)
            x = jnp.where(mask, x / keep, 0).astype(x.dtype)
    if mode == "lstm" and lstm_state_clip_min is not None:
        c_outs = [jnp.clip(c, lstm_state_clip_min, lstm_state_clip_max)
                  for c in c_outs]
    h_out = jnp.stack(h_outs)
    c_out = jnp.stack(c_outs)
    return x, h_out, c_out
