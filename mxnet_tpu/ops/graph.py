"""DGL graph-sampling contrib ops.

Reference: ``src/operator/contrib/dgl_graph.cc`` (`_contrib_dgl_csr_neighbor_
uniform_sample`, `_contrib_dgl_csr_neighbor_non_uniform_sample`,
`_contrib_dgl_subgraph`, `_contrib_dgl_adjacency`,
`_contrib_dgl_graph_compact`) — the graph-neural-network sampling kernels
MXNet grew for DGL.  They are CPU ops with value-dependent output shapes in
the reference too, so the TPU rebuild keeps them host-side (``no_jit``),
numpy-computed over CSR storage; the padded fixed-size outputs (``max_num_
vertices``) exist precisely so downstream compute CAN be jitted on static
shapes.

Contract notes (mount empty — see SURVEY.md caveat): output layouts follow
the upstream operator docs: samplers return, per seed array,
``(padded vertex ids with count in the last slot, sub-CSR over local ids,
per-vertex layer/hop)``; ``dgl_subgraph`` returns induced sub-CSRs and,
with ``return_mapping``, CSRs whose data are parent edge ids.
"""
from __future__ import annotations

import numpy as _np

import jax.numpy as jnp

from .registry import register

__all__ = []


def _csr_parts(g):
    """CSRNDArray | dense-like -> numpy (data, indices, indptr, shape)."""
    if hasattr(g, "stype") and g.stype == "csr":
        return (_np.asarray(g.data.asnumpy()),
                _np.asarray(g.indices.asnumpy()).astype(_np.int64),
                _np.asarray(g.indptr.asnumpy()).astype(_np.int64),
                tuple(g.shape))
    raise TypeError("dgl graph ops need a CSRNDArray adjacency, got %r"
                    % type(g))


def _make_csr(data, indices, indptr, shape):
    from ..ndarray import sparse as _sp
    from ..ndarray.ndarray import array as _arr
    return _sp.CSRNDArray(
        _arr(_np.asarray(data)),
        _arr(_np.asarray(indices, _np.int64)),
        _arr(_np.asarray(indptr, _np.int64)), tuple(shape))


def _neigh(indices, indptr, v):
    return indices[indptr[v]:indptr[v + 1]]


@register("_contrib_dgl_adjacency", aliases=["dgl_adjacency"],
          differentiable=False, no_jit=True)
def _dgl_adjacency(g):
    """Same sparsity structure, data replaced by 1.0 (edge indicator)."""
    data, indices, indptr, shape = _csr_parts(g)
    return _make_csr(_np.ones_like(data, _np.float32), indices, indptr,
                     shape)


@register("_contrib_dgl_subgraph", aliases=["dgl_subgraph"],
          differentiable=False, no_jit=True, num_outputs=-1)
def _dgl_subgraph(g, *vids, return_mapping=False):
    """Induced subgraph(s) of `g` over each given vertex-id array.

    Outputs: one sub-CSR per vid array (vertices remapped to local ids,
    data = 1-based local edge ids); with return_mapping, additionally one
    CSR per vid array whose data are the PARENT edge ids."""
    _data, indices, indptr, shape = _csr_parts(g)
    subs, maps = [], []
    for v in vids:
        v = _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v,
                        _np.int64).ravel()
        n = v.shape[0]
        local = {int(x): i for i, x in enumerate(v)}
        s_indptr = _np.zeros(n + 1, _np.int64)
        s_cols, s_orig = [], []
        for i, x in enumerate(v):
            row = _neigh(indices, indptr, int(x))
            eids = _np.arange(indptr[int(x)], indptr[int(x) + 1])
            for c, e in zip(row, eids):
                j = local.get(int(c))
                if j is not None:
                    s_cols.append(j)
                    s_orig.append(int(e))
            s_indptr[i + 1] = len(s_cols)
        nnz = len(s_cols)
        subs.append(_make_csr(_np.arange(1, nnz + 1, dtype=_np.float32),
                              _np.asarray(s_cols, _np.int64), s_indptr,
                              (n, n)))
        if return_mapping:
            maps.append(_make_csr(_np.asarray(s_orig, _np.float32),
                                  _np.asarray(s_cols, _np.int64), s_indptr,
                                  (n, n)))
    return tuple(subs + maps)


def _neighbor_sample(rng, indices, indptr, seeds, num_hops, num_neighbor,
                     max_num_vertices, prob=None):
    """BFS expansion with per-vertex neighbor subsampling."""
    seeds = _np.asarray(seeds, _np.int64).ravel()
    seeds = seeds[seeds >= 0]
    visited = {}
    layer_of = {}
    frontier = []
    for s in seeds:
        if len(visited) >= max_num_vertices:
            break               # seed list larger than the vertex budget
        if int(s) not in visited:
            visited[int(s)] = len(visited)
            layer_of[int(s)] = 0
            frontier.append(int(s))
    edges = []                      # (src_local, dst_parent) pairs
    for hop in range(1, num_hops + 1):
        nxt = []
        for v in frontier:
            nb = _neigh(indices, indptr, v)
            if nb.shape[0] == 0:
                continue
            if nb.shape[0] > num_neighbor:
                if prob is not None:
                    p = prob[nb]
                    psum = p.sum()
                    if psum <= 0:
                        continue
                    chosen = rng.choice(nb, size=num_neighbor, replace=False,
                                        p=p / psum)
                else:
                    chosen = rng.choice(nb, size=num_neighbor, replace=False)
            else:
                chosen = nb
            for c in chosen:
                c = int(c)
                if len(visited) >= max_num_vertices and c not in visited:
                    continue
                if c not in visited:
                    visited[c] = len(visited)
                    layer_of[c] = hop
                    nxt.append(c)
                edges.append((visited[v], c))
        frontier = nxt
    n = len(visited)
    verts = _np.full(max_num_vertices + 1, -1, _np.int64)
    layer = _np.full(max_num_vertices + 1, -1, _np.int64)
    order = sorted(visited, key=visited.get)
    verts[:n] = order
    verts[-1] = n                   # count in the final slot
    for x in order:
        layer[visited[x]] = layer_of[x]
    # build sub-CSR over local ids, padded to max_num_vertices rows
    rows = [[] for _ in range(max_num_vertices)]
    for src_local, dst_parent in edges:
        j = visited.get(dst_parent)
        if j is not None:
            rows[src_local].append(j)
    s_indptr = _np.zeros(max_num_vertices + 1, _np.int64)
    s_cols = []
    for i, r in enumerate(rows):
        s_cols.extend(sorted(set(r)))
        s_indptr[i + 1] = len(s_cols)
    nnz = len(s_cols)
    sub = (_np.arange(1, nnz + 1, dtype=_np.float32),
           _np.asarray(s_cols, _np.int64), s_indptr,
           (max_num_vertices, max_num_vertices))
    return verts, sub, layer


@register("_contrib_dgl_csr_neighbor_uniform_sample",
          aliases=["dgl_csr_neighbor_uniform_sample"],
          differentiable=False, no_jit=True, needs_rng=True, num_outputs=-1)
def _dgl_neighbor_uniform(key, g, *seeds, num_hops=1, num_neighbor=2,
                          max_num_vertices=100):
    _data, indices, indptr, _shape = _csr_parts(g)
    rng = _np.random.RandomState(
        int(_np.asarray(jnp.sum(key.astype(jnp.uint32))) % (2**31 - 1)))
    vs, subs, layers = [], [], []
    for s in seeds:
        s = s.asnumpy() if hasattr(s, "asnumpy") else s
        verts, sub, layer = _neighbor_sample(
            rng, indices, indptr, s, int(num_hops), int(num_neighbor),
            int(max_num_vertices))
        vs.append(jnp.asarray(verts))
        subs.append(_make_csr(*sub))
        layers.append(jnp.asarray(layer))
    return tuple(vs + subs + layers)


@register("_contrib_dgl_csr_neighbor_non_uniform_sample",
          aliases=["dgl_csr_neighbor_non_uniform_sample"],
          differentiable=False, no_jit=True, needs_rng=True, num_outputs=-1)
def _dgl_neighbor_non_uniform(key, g, probability, *seeds, num_hops=1,
                              num_neighbor=2, max_num_vertices=100):
    _data, indices, indptr, _shape = _csr_parts(g)
    prob = _np.asarray(probability.asnumpy()
                       if hasattr(probability, "asnumpy") else probability,
                       _np.float64).ravel()
    rng = _np.random.RandomState(
        int(_np.asarray(jnp.sum(key.astype(jnp.uint32))) % (2**31 - 1)))
    vs, subs, layers, probs = [], [], [], []
    for s in seeds:
        s = s.asnumpy() if hasattr(s, "asnumpy") else s
        verts, sub, layer = _neighbor_sample(
            rng, indices, indptr, s, int(num_hops), int(num_neighbor),
            int(max_num_vertices), prob=prob)
        n = int(verts[-1])
        pv = _np.zeros(int(max_num_vertices) + 1, _np.float64)
        pv[:n] = prob[verts[:n]] if prob.shape[0] > 0 else 0.0
        vs.append(jnp.asarray(verts))
        probs.append(jnp.asarray(pv.astype(_np.float32)))
        subs.append(_make_csr(*sub))
        layers.append(jnp.asarray(layer))
    return tuple(vs + probs + subs + layers)


def _compact_one(g, n):
    data, indices, indptr, _shape = _csr_parts(g)
    keep = indptr[n]
    mask = indices[:keep] < n
    new_cols, new_data = indices[:keep][mask], data[:keep][mask]
    new_indptr = _np.zeros(n + 1, _np.int64)
    for i in range(n):
        seg = indices[indptr[i]:indptr[i + 1]]
        new_indptr[i + 1] = new_indptr[i] + int((seg < n).sum())
    return _make_csr(new_data, new_cols, new_indptr, (n, n))


@register("_contrib_dgl_graph_compact", aliases=["dgl_graph_compact"],
          differentiable=False, no_jit=True, num_outputs=-1)
def _dgl_graph_compact(*graphs, graph_sizes=(), return_mapping=False):
    """Strip the max_num_vertices padding from sampled subgraphs: each
    input CSR is truncated to its true vertex count from graph_sizes.
    With return_mapping, inputs are (g_1..g_k, map_1..map_k) and both
    halves are compacted with the same sizes (reference arity)."""
    sizes = [int(x) for x in (graph_sizes if isinstance(graph_sizes,
                                                        (list, tuple))
                              else [graph_sizes])]
    k = len(sizes)
    expected = 2 * k if return_mapping else k
    if len(graphs) != expected:
        raise ValueError(
            "dgl_graph_compact: got %d graphs but graph_sizes has %d "
            "entries%s" % (len(graphs), k,
                           " (x2 for return_mapping)" if return_mapping
                           else ""))
    outs = [_compact_one(g, n) for g, n in zip(graphs[:k], sizes)]
    if return_mapping:
        outs += [_compact_one(g, n) for g, n in zip(graphs[k:], sizes)]
    return tuple(outs)
