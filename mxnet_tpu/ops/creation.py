"""Tensor-creation ops.

Reference: src/operator/tensor/init_op.cc (_zeros, _ones, _full, _arange,
_linspace, _eye, zeros_like/ones_like) — the no-input ops behind mx.nd.zeros
etc.  All shapes/params are static, so each call is one cached XLA
executable that materializes straight into device memory.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


def _dt(dtype):
    if dtype in (None, "None"):
        return jnp.float32
    return jnp.bfloat16 if dtype == "bfloat16" else dtype


@register("_zeros", aliases=["zeros_op"], differentiable=False)
def _zeros(shape=(), dtype=None):
    return jnp.zeros(shape, _dt(dtype))


@register("_ones", aliases=["ones_op"], differentiable=False)
def _ones(shape=(), dtype=None):
    return jnp.ones(shape, _dt(dtype))


@register("_full", aliases=["full_op"], differentiable=False)
def _full(shape=(), value=0.0, dtype=None):
    return jnp.full(shape, value, _dt(dtype))


@register("_arange", aliases=["arange_op"], differentiable=False)
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype=None):
    if stop is None:
        start, stop = 0.0, start
    out = jnp.arange(start, stop, step, _dt(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace", aliases=["linspace_op"], differentiable=False)
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype=None):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=_dt(dtype))


@register("_eye", aliases=["eye_op"], differentiable=False)
def _eye(N=1, M=0, k=0, dtype=None):
    return jnp.eye(int(N), int(M) if M else None, int(k), dtype=_dt(dtype))
