"""Operator registry + implementations.

Importing this package registers all ops (the role of C++ static-init
registration at dlopen in the reference — SURVEY.md §3.1).
"""
from . import registry
from .registry import register, get_op, list_ops, cached_jit, OpDef

from . import elemwise    # noqa: F401
from . import reduce      # noqa: F401
from . import matrix      # noqa: F401
from . import nn          # noqa: F401
from . import random     # noqa: F401
from . import optimizer  # noqa: F401
from . import rnn       # noqa: F401
from . import attention  # noqa: F401
from . import linalg     # noqa: F401
from . import extra      # noqa: F401
from . import detection  # noqa: F401
from . import spatial    # noqa: F401
from . import control_flow  # noqa: F401
from . import quantization  # noqa: F401
from . import scalar     # noqa: F401
from . import creation   # noqa: F401
from . import misc       # noqa: F401
from . import image      # noqa: F401
from . import nn_extra   # noqa: F401
from . import numpy_ops  # noqa: F401
from . import graph      # noqa: F401
