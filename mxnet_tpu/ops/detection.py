"""Detection ops: box IoU/NMS + the SSD MultiBox family.

Reference: src/operator/contrib/bounding_box.cc (box_nms, box_iou),
src/operator/contrib/multibox_prior.cc (MultiBoxPriorParam),
src/operator/contrib/multibox_target.cc (MultiBoxTargetParam),
src/operator/contrib/multibox_detection.cc (MultiBoxDetectionParam).

TPU-native design (SURVEY.md §7.2 hard part 3: dynamic shapes): every op
here is STATIC-shape — suppression/invalidity is expressed by masking
(score = -1 entries), never by compaction, so XLA compiles one executable
per shape.  NMS is the O(N²) mask-matrix formulation: compute the full
pairwise-IoU matrix once (an MXU-friendly batched computation), then a
`lax.scan` over boxes in score order flips a keep-mask — no data-dependent
control flow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# IoU
# ---------------------------------------------------------------------------


def _pairwise_iou(a, b, fmt="corner"):
    """IoU of (..., Na, 4) vs (..., Nb, 4) → (..., Na, Nb)."""
    if fmt == "center":
        def to_corner(x):
            cx, cy, w, h = jnp.split(x, 4, axis=-1)
            return jnp.concatenate(
                [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
        a, b = to_corner(a), to_corner(b)
    ax1, ay1, ax2, ay2 = [a[..., i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., i] for i in range(4)]
    ix1 = jnp.maximum(ax1[..., :, None], bx1[..., None, :])
    iy1 = jnp.maximum(ay1[..., :, None], by1[..., None, :])
    ix2 = jnp.minimum(ax2[..., :, None], bx2[..., None, :])
    iy2 = jnp.minimum(ay2[..., :, None], by2[..., None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou", differentiable=False)
def _box_iou(lhs, rhs, format="corner"):
    return _pairwise_iou(lhs, rhs, fmt=format)


alias("_contrib_box_iou", "box_iou")


# ---------------------------------------------------------------------------
# box_nms — static-shape masked suppression
# ---------------------------------------------------------------------------

@register("_contrib_box_nms", differentiable=False)
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1, background_id=-1,
             force_suppress=False, in_format="corner", out_format="corner"):
    """data: (..., N, K). Suppressed/invalid entries get all fields -1
    (the reference's convention). Output shape == input shape."""
    orig_shape = data.shape
    flat = data.reshape((-1,) + orig_shape[-2:])
    B, N, K = flat.shape
    boxes = flat[..., coord_start:coord_start + 4]
    scores = flat[..., score_index]
    ids = flat[..., id_index] if id_index >= 0 else jnp.zeros_like(scores)

    valid = scores > valid_thresh
    if background_id >= 0 and id_index >= 0:
        valid &= ids != background_id
    # sort by score descending (invalid entries pushed last)
    order = jnp.argsort(jnp.where(valid, -scores, jnp.inf), axis=-1)
    boxes_s = jnp.take_along_axis(boxes, order[..., None], axis=1)
    valid_s = jnp.take_along_axis(valid, order, axis=1)
    ids_s = jnp.take_along_axis(ids, order, axis=1)
    if topk > 0:
        idx = jnp.arange(N)
        valid_s &= idx[None, :] < topk

    iou = _pairwise_iou(boxes_s, boxes_s, fmt=in_format)   # (B, N, N)
    same_class = (ids_s[:, :, None] == ids_s[:, None, :]) \
        if (id_index >= 0 and not force_suppress) else jnp.ones(
            (B, N, N), bool)
    suppress_pair = (iou > overlap_thresh) & same_class

    def step(keep, i):
        # box i (in score order) suppresses all later boxes overlapping it,
        # but only if it itself is still kept
        row = jnp.take(suppress_pair, i, axis=1) & (jnp.arange(N)[None, :] > i)
        keep_i = jnp.take(keep, i, axis=1)[:, None]
        keep = keep & ~(row & keep_i)
        return keep, None

    keep0 = valid_s
    keep, _ = lax.scan(step, keep0, jnp.arange(N))
    # scatter keep-mask back to original order
    inv = jnp.argsort(order, axis=-1)
    keep_orig = jnp.take_along_axis(keep, inv, axis=1)
    out = jnp.where(keep_orig[..., None], flat, -jnp.ones_like(flat))
    return out.reshape(orig_shape)


alias("_contrib_box_nms", "box_nms")


# ---------------------------------------------------------------------------
# MultiBoxPrior — anchor generation
# ---------------------------------------------------------------------------

@register("MultiBoxPrior", differentiable=False)
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """data: (B, C, H, W) feature map → (1, H*W*A, 4) corner-format anchors
    where A = len(sizes) + len(ratios) - 1 (the reference's convention)."""
    H, W = data.shape[-2], data.shape[-1]
    sizes = tuple(sizes) if isinstance(sizes, (tuple, list)) else (sizes,)
    ratios = tuple(ratios) if isinstance(ratios, (tuple, list)) else (ratios,)
    step_y = steps[1] if steps[1] > 0 else 1.0 / H
    step_x = steps[0] if steps[0] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[1]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[0]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # H,W,2

    # anchor (w, h) list: all sizes at ratio[0], then size[0] at ratios[1:]
    whs = [(s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])) for s in sizes]
    whs += [(sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r))
            for r in ratios[1:]]
    w = jnp.asarray([x[0] for x in whs], jnp.float32)  # (A,)
    h = jnp.asarray([x[1] for x in whs], jnp.float32)
    A = w.shape[0]
    ctr = jnp.broadcast_to(cyx[:, :, None, :], (H, W, A, 2))
    x1 = ctr[..., 1] - w / 2
    y1 = ctr[..., 0] - h / 2
    x2 = ctr[..., 1] + w / 2
    y2 = ctr[..., 0] + h / 2
    anchors = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(1, H * W * A, 4)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


alias("MultiBoxPrior", "_contrib_MultiBoxPrior", "multibox_prior")


# ---------------------------------------------------------------------------
# MultiBoxTarget — anchor ↔ ground-truth matching
# ---------------------------------------------------------------------------

@register("MultiBoxTarget", differentiable=False, num_outputs=3)
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """anchor: (1, N, 4) corners; label: (B, M, 5) [cls, x1, y1, x2, y2]
    padded with cls=-1; cls_pred: (B, num_cls+1, N).
    Returns (loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N)).
    cls_target: 0 = background, k+1 = object class k, -1 = ignored
    (negative mining). Matching: best-anchor-per-gt forced + IoU threshold.
    """
    anchors = anchor.reshape(-1, 4)                      # (N, 4)
    N = anchors.shape[0]
    B, M = label.shape[0], label.shape[1]
    gt_cls = label[..., 0]                               # (B, M)
    gt_box = label[..., 1:5]                             # (B, M, 4)
    gt_valid = gt_cls >= 0

    iou = _pairwise_iou(jnp.broadcast_to(anchors, (B, N, 4)), gt_box)
    iou = jnp.where(gt_valid[:, None, :], iou, -1.0)     # (B, N, M)

    best_gt = jnp.argmax(iou, axis=-1)                   # (B, N)
    best_iou = jnp.max(iou, axis=-1)
    matched = best_iou >= overlap_threshold

    # force-match: each valid gt claims its best anchor
    best_anchor = jnp.argmax(iou, axis=1)                # (B, M)
    forced = jnp.zeros((B, N), bool)
    forced_gt = jnp.zeros((B, N), jnp.int32)
    batch_idx = jnp.arange(B)[:, None]
    forced = forced.at[batch_idx, best_anchor].set(gt_valid)
    forced_gt = forced_gt.at[batch_idx, best_anchor].set(
        jnp.where(gt_valid, jnp.arange(M)[None, :], 0))
    use_forced = forced
    match_gt = jnp.where(use_forced, forced_gt, best_gt)
    is_pos = matched | use_forced

    # loc targets: encode matched gt vs anchor with variances (center form)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    g = jnp.take_along_axis(gt_box, match_gt[..., None], axis=1)  # (B,N,4)
    gw = g[..., 2] - g[..., 0]
    gh = g[..., 3] - g[..., 1]
    gcx = (g[..., 0] + g[..., 2]) / 2
    gcy = (g[..., 1] + g[..., 3]) / 2
    eps = 1e-8
    tx = (gcx - acx) / jnp.maximum(aw, eps) / variances[0]
    ty = (gcy - acy) / jnp.maximum(ah, eps) / variances[1]
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, eps), eps)) / variances[2]
    th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, eps), eps)) / variances[3]
    loc_t = jnp.stack([tx, ty, tw, th], axis=-1)          # (B, N, 4)
    loc_target = jnp.where(is_pos[..., None], loc_t, 0.0).reshape(B, N * 4)
    loc_mask = jnp.where(is_pos[..., None],
                         jnp.ones_like(loc_t), 0.0).reshape(B, N * 4)

    matched_cls = jnp.take_along_axis(gt_cls, match_gt, axis=1)   # (B, N)
    cls_target = jnp.where(is_pos, matched_cls + 1.0, 0.0)

    if negative_mining_ratio > 0:
        # OHNM: keep the top (ratio × #pos) highest-background-loss
        # negatives per sample; the rest get ignore_label
        bg_prob = jax.nn.softmax(cls_pred, axis=1)[:, 0, :]       # (B, N)
        neg_score = jnp.where(is_pos, jnp.inf, bg_prob)           # small=hard
        rank = jnp.argsort(jnp.argsort(neg_score, axis=-1), axis=-1)
        n_pos = jnp.sum(is_pos, axis=-1, keepdims=True)
        n_neg = jnp.maximum(negative_mining_ratio * n_pos,
                            minimum_negative_samples)
        keep_neg = rank < n_neg
        cls_target = jnp.where(is_pos | keep_neg, cls_target,
                               ignore_label)
    return loc_target, loc_mask, cls_target


alias("MultiBoxTarget", "_contrib_MultiBoxTarget", "multibox_target")


# ---------------------------------------------------------------------------
# MultiBoxDetection — decode + per-class NMS
# ---------------------------------------------------------------------------

@register("MultiBoxDetection", differentiable=False)
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0, nms_threshold=0.5,
                        force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """cls_prob: (B, num_cls+1, N); loc_pred: (B, N*4); anchor: (1, N, 4).
    → (B, N, 6) rows [class_id, score, x1, y1, x2, y2], suppressed = -1."""
    B = cls_prob.shape[0]
    N = anchor.shape[1]
    anchors = anchor.reshape(N, 4)
    loc = loc_pred.reshape(B, N, 4)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = loc[..., 0] * variances[0] * aw + acx
    cy = loc[..., 1] * variances[1] * ah + acy
    w = jnp.exp(loc[..., 2] * variances[2]) * aw
    h = jnp.exp(loc[..., 3] * variances[3]) * ah
    x1, y1 = cx - w / 2, cy - h / 2
    x2, y2 = cx + w / 2, cy + h / 2
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)          # (B, N, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)

    # best non-background class per anchor (the reference's formulation)
    prob = jnp.moveaxis(cls_prob, 1, 2)                   # (B, N, C+1)
    fg = prob[..., 1:] if background_id == 0 else jnp.delete(
        prob, background_id, axis=-1)
    cls_id = jnp.argmax(fg, axis=-1).astype(boxes.dtype)  # (B, N)
    score = jnp.max(fg, axis=-1)
    keep = score > threshold
    rows = jnp.concatenate(
        [jnp.where(keep, cls_id, -1.0)[..., None],
         jnp.where(keep, score, -1.0)[..., None],
         jnp.where(keep[..., None], boxes, -1.0)], axis=-1)  # (B, N, 6)
    return _box_nms(rows, overlap_thresh=nms_threshold, valid_thresh=0.0,
                    topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                    force_suppress=force_suppress)


alias("MultiBoxDetection", "_contrib_MultiBoxDetection", "multibox_detection")
