"""Detection ops: box IoU/NMS + the SSD MultiBox family.

Reference: src/operator/contrib/bounding_box.cc (box_nms, box_iou),
src/operator/contrib/multibox_prior.cc (MultiBoxPriorParam),
src/operator/contrib/multibox_target.cc (MultiBoxTargetParam),
src/operator/contrib/multibox_detection.cc (MultiBoxDetectionParam).

TPU-native design (SURVEY.md §7.2 hard part 3: dynamic shapes): every op
here is STATIC-shape — suppression/invalidity is expressed by masking
(score = -1 entries), never by compaction, so XLA compiles one executable
per shape.  NMS is the O(N²) mask-matrix formulation: compute the full
pairwise-IoU matrix once (an MXU-friendly batched computation), then a
`lax.scan` over boxes in score order flips a keep-mask — no data-dependent
control flow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# IoU
# ---------------------------------------------------------------------------


def _pairwise_iou(a, b, fmt="corner"):
    """IoU of (..., Na, 4) vs (..., Nb, 4) → (..., Na, Nb)."""
    if fmt == "center":
        def to_corner(x):
            cx, cy, w, h = jnp.split(x, 4, axis=-1)
            return jnp.concatenate(
                [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
        a, b = to_corner(a), to_corner(b)
    ax1, ay1, ax2, ay2 = [a[..., i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., i] for i in range(4)]
    ix1 = jnp.maximum(ax1[..., :, None], bx1[..., None, :])
    iy1 = jnp.maximum(ay1[..., :, None], by1[..., None, :])
    ix2 = jnp.minimum(ax2[..., :, None], bx2[..., None, :])
    iy2 = jnp.minimum(ay2[..., :, None], by2[..., None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou", differentiable=False)
def _box_iou(lhs, rhs, format="corner"):
    return _pairwise_iou(lhs, rhs, fmt=format)


alias("_contrib_box_iou", "box_iou")


# ---------------------------------------------------------------------------
# box_nms — static-shape masked suppression
# ---------------------------------------------------------------------------

@register("_contrib_box_nms", differentiable=False)
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1, background_id=-1,
             force_suppress=False, in_format="corner", out_format="corner"):
    """data: (..., N, K). Suppressed/invalid entries get all fields -1
    (the reference's convention). Output shape == input shape."""
    orig_shape = data.shape
    flat = data.reshape((-1,) + orig_shape[-2:])
    B, N, K = flat.shape
    boxes = flat[..., coord_start:coord_start + 4]
    scores = flat[..., score_index]
    ids = flat[..., id_index] if id_index >= 0 else jnp.zeros_like(scores)

    valid = scores > valid_thresh
    if background_id >= 0 and id_index >= 0:
        valid &= ids != background_id
    # sort by score descending (invalid entries pushed last)
    order = jnp.argsort(jnp.where(valid, -scores, jnp.inf), axis=-1)
    boxes_s = jnp.take_along_axis(boxes, order[..., None], axis=1)
    valid_s = jnp.take_along_axis(valid, order, axis=1)
    ids_s = jnp.take_along_axis(ids, order, axis=1)
    if topk > 0:
        idx = jnp.arange(N)
        valid_s &= idx[None, :] < topk

    iou = _pairwise_iou(boxes_s, boxes_s, fmt=in_format)   # (B, N, N)
    same_class = (ids_s[:, :, None] == ids_s[:, None, :]) \
        if (id_index >= 0 and not force_suppress) else jnp.ones(
            (B, N, N), bool)
    suppress_pair = (iou > overlap_thresh) & same_class

    def step(keep, i):
        # box i (in score order) suppresses all later boxes overlapping it,
        # but only if it itself is still kept
        row = jnp.take(suppress_pair, i, axis=1) & (jnp.arange(N)[None, :] > i)
        keep_i = jnp.take(keep, i, axis=1)[:, None]
        keep = keep & ~(row & keep_i)
        return keep, None

    keep0 = valid_s
    keep, _ = lax.scan(step, keep0, jnp.arange(N))
    # scatter keep-mask back to original order
    inv = jnp.argsort(order, axis=-1)
    keep_orig = jnp.take_along_axis(keep, inv, axis=1)
    out = jnp.where(keep_orig[..., None], flat, -jnp.ones_like(flat))
    return out.reshape(orig_shape)


alias("_contrib_box_nms", "box_nms")


# ---------------------------------------------------------------------------
# MultiBoxPrior — anchor generation
# ---------------------------------------------------------------------------

@register("MultiBoxPrior", differentiable=False)
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """data: (B, C, H, W) feature map → (1, H*W*A, 4) corner-format anchors
    where A = len(sizes) + len(ratios) - 1 (the reference's convention)."""
    H, W = data.shape[-2], data.shape[-1]
    sizes = tuple(sizes) if isinstance(sizes, (tuple, list)) else (sizes,)
    ratios = tuple(ratios) if isinstance(ratios, (tuple, list)) else (ratios,)
    step_y = steps[1] if steps[1] > 0 else 1.0 / H
    step_x = steps[0] if steps[0] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[1]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[0]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # H,W,2

    # anchor (w, h) list: all sizes at ratio[0], then size[0] at ratios[1:]
    whs = [(s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])) for s in sizes]
    whs += [(sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r))
            for r in ratios[1:]]
    w = jnp.asarray([x[0] for x in whs], jnp.float32)  # (A,)
    h = jnp.asarray([x[1] for x in whs], jnp.float32)
    A = w.shape[0]
    ctr = jnp.broadcast_to(cyx[:, :, None, :], (H, W, A, 2))
    x1 = ctr[..., 1] - w / 2
    y1 = ctr[..., 0] - h / 2
    x2 = ctr[..., 1] + w / 2
    y2 = ctr[..., 0] + h / 2
    anchors = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(1, H * W * A, 4)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


alias("MultiBoxPrior", "_contrib_MultiBoxPrior", "multibox_prior")


# ---------------------------------------------------------------------------
# MultiBoxTarget — anchor ↔ ground-truth matching
# ---------------------------------------------------------------------------

@register("MultiBoxTarget", differentiable=False, num_outputs=3)
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """anchor: (1, N, 4) corners; label: (B, M, 5) [cls, x1, y1, x2, y2]
    padded with cls=-1; cls_pred: (B, num_cls+1, N).
    Returns (loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N)).
    cls_target: 0 = background, k+1 = object class k, -1 = ignored
    (negative mining). Matching: best-anchor-per-gt forced + IoU threshold.
    """
    anchors = anchor.reshape(-1, 4)                      # (N, 4)
    N = anchors.shape[0]
    B, M = label.shape[0], label.shape[1]
    gt_cls = label[..., 0]                               # (B, M)
    gt_box = label[..., 1:5]                             # (B, M, 4)
    gt_valid = gt_cls >= 0

    iou = _pairwise_iou(jnp.broadcast_to(anchors, (B, N, 4)), gt_box)
    iou = jnp.where(gt_valid[:, None, :], iou, -1.0)     # (B, N, M)

    best_gt = jnp.argmax(iou, axis=-1)                   # (B, N)
    best_iou = jnp.max(iou, axis=-1)
    matched = best_iou >= overlap_threshold

    # force-match: each valid gt claims its best anchor
    best_anchor = jnp.argmax(iou, axis=1)                # (B, M)
    forced = jnp.zeros((B, N), bool)
    forced_gt = jnp.zeros((B, N), jnp.int32)
    batch_idx = jnp.arange(B)[:, None]
    forced = forced.at[batch_idx, best_anchor].set(gt_valid)
    forced_gt = forced_gt.at[batch_idx, best_anchor].set(
        jnp.where(gt_valid, jnp.arange(M)[None, :], 0))
    use_forced = forced
    match_gt = jnp.where(use_forced, forced_gt, best_gt)
    is_pos = matched | use_forced

    # loc targets: encode matched gt vs anchor with variances (center form)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    g = jnp.take_along_axis(gt_box, match_gt[..., None], axis=1)  # (B,N,4)
    gw = g[..., 2] - g[..., 0]
    gh = g[..., 3] - g[..., 1]
    gcx = (g[..., 0] + g[..., 2]) / 2
    gcy = (g[..., 1] + g[..., 3]) / 2
    eps = 1e-8
    tx = (gcx - acx) / jnp.maximum(aw, eps) / variances[0]
    ty = (gcy - acy) / jnp.maximum(ah, eps) / variances[1]
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, eps), eps)) / variances[2]
    th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, eps), eps)) / variances[3]
    loc_t = jnp.stack([tx, ty, tw, th], axis=-1)          # (B, N, 4)
    loc_target = jnp.where(is_pos[..., None], loc_t, 0.0).reshape(B, N * 4)
    loc_mask = jnp.where(is_pos[..., None],
                         jnp.ones_like(loc_t), 0.0).reshape(B, N * 4)

    matched_cls = jnp.take_along_axis(gt_cls, match_gt, axis=1)   # (B, N)
    cls_target = jnp.where(is_pos, matched_cls + 1.0, 0.0)

    if negative_mining_ratio > 0:
        # OHNM: keep the top (ratio × #pos) highest-background-loss
        # negatives per sample; the rest get ignore_label
        bg_prob = jax.nn.softmax(cls_pred, axis=1)[:, 0, :]       # (B, N)
        neg_score = jnp.where(is_pos, jnp.inf, bg_prob)           # small=hard
        rank = jnp.argsort(jnp.argsort(neg_score, axis=-1), axis=-1)
        n_pos = jnp.sum(is_pos, axis=-1, keepdims=True)
        n_neg = jnp.maximum(negative_mining_ratio * n_pos,
                            minimum_negative_samples)
        keep_neg = rank < n_neg
        cls_target = jnp.where(is_pos | keep_neg, cls_target,
                               ignore_label)
    return loc_target, loc_mask, cls_target


alias("MultiBoxTarget", "_contrib_MultiBoxTarget", "multibox_target")


# ---------------------------------------------------------------------------
# MultiBoxDetection — decode + per-class NMS
# ---------------------------------------------------------------------------

@register("MultiBoxDetection", differentiable=False)
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0, nms_threshold=0.5,
                        force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """cls_prob: (B, num_cls+1, N); loc_pred: (B, N*4); anchor: (1, N, 4).
    → (B, N, 6) rows [class_id, score, x1, y1, x2, y2], suppressed = -1."""
    B = cls_prob.shape[0]
    N = anchor.shape[1]
    anchors = anchor.reshape(N, 4)
    loc = loc_pred.reshape(B, N, 4)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = loc[..., 0] * variances[0] * aw + acx
    cy = loc[..., 1] * variances[1] * ah + acy
    w = jnp.exp(loc[..., 2] * variances[2]) * aw
    h = jnp.exp(loc[..., 3] * variances[3]) * ah
    x1, y1 = cx - w / 2, cy - h / 2
    x2, y2 = cx + w / 2, cy + h / 2
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)          # (B, N, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)

    # best non-background class per anchor (the reference's formulation)
    prob = jnp.moveaxis(cls_prob, 1, 2)                   # (B, N, C+1)
    fg = prob[..., 1:] if background_id == 0 else jnp.delete(
        prob, background_id, axis=-1)
    cls_id = jnp.argmax(fg, axis=-1).astype(boxes.dtype)  # (B, N)
    score = jnp.max(fg, axis=-1)
    keep = score > threshold
    rows = jnp.concatenate(
        [jnp.where(keep, cls_id, -1.0)[..., None],
         jnp.where(keep, score, -1.0)[..., None],
         jnp.where(keep[..., None], boxes, -1.0)], axis=-1)  # (B, N, 6)
    return _box_nms(rows, overlap_thresh=nms_threshold, valid_thresh=0.0,
                    topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                    force_suppress=force_suppress)


alias("MultiBoxDetection", "_contrib_MultiBoxDetection", "multibox_detection")


@register("_contrib_box_encode", aliases=["box_encode"], num_outputs=2,
          differentiable=False)
def _box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
                stds=(0.1, 0.1, 0.2, 0.2)):
    """Corner boxes → center-form regression targets vs matched refs
    (reference: src/operator/contrib/bounding_box.cc BoxEncode)."""
    m = jnp.take_along_axis(refs, matches.astype(jnp.int32)[..., None]
                            .repeat(4, -1), axis=1)
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = anchors[..., 0] + 0.5 * aw
    ay = anchors[..., 1] + 0.5 * ah
    gw = m[..., 2] - m[..., 0]
    gh = m[..., 3] - m[..., 1]
    gx = m[..., 0] + 0.5 * gw
    gy = m[..., 1] + 0.5 * gh
    means = jnp.asarray(means, jnp.float32)
    stds = jnp.asarray(stds, jnp.float32)
    t = jnp.stack([
        ((gx - ax) / jnp.maximum(aw, 1e-12) - means[0]) / stds[0],
        ((gy - ay) / jnp.maximum(ah, 1e-12) - means[1]) / stds[1],
        (jnp.log(jnp.maximum(gw, 1e-12) / jnp.maximum(aw, 1e-12))
         - means[2]) / stds[2],
        (jnp.log(jnp.maximum(gh, 1e-12) / jnp.maximum(ah, 1e-12))
         - means[3]) / stds[3]], axis=-1)
    valid = (samples > 0.5)[..., None]
    targets = jnp.where(valid, t, 0.0)
    masks = jnp.where(valid, jnp.ones_like(t), jnp.zeros_like(t))
    return targets, masks


@register("_contrib_box_decode", aliases=["box_decode"],
          differentiable=False)
def _box_decode(data, anchors, std0=1.0, std1=1.0, std2=1.0, std3=1.0,
                clip=-1.0, format="corner"):
    """Regression deltas + anchors → corner boxes (reference: BoxDecode)."""
    if format == "corner":
        aw = anchors[..., 2] - anchors[..., 0]
        ah = anchors[..., 3] - anchors[..., 1]
        ax = anchors[..., 0] + 0.5 * aw
        ay = anchors[..., 1] + 0.5 * ah
    else:
        ax, ay, aw, ah = (anchors[..., i] for i in range(4))
    dx = data[..., 0] * std0
    dy = data[..., 1] * std1
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    out = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                     cx + 0.5 * w, cy + 0.5 * h], axis=-1)
    if clip > 0:
        out = jnp.clip(out, 0.0, clip)
    return out


@register("_contrib_PSROIPooling", aliases=["PSROIPooling"],
          differentiable=False)
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                   pooled_size=1, group_size=0):
    """Position-sensitive ROI pooling (reference:
    src/operator/contrib/psroi_pooling.cc — R-FCN heads).
    data (B, C, H, W) with C = output_dim*group²; rois (R, 5)."""
    g = int(group_size) if group_size else int(pooled_size)
    p = int(pooled_size)
    B, C, H, W = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[i] * spatial_scale for i in range(1, 5))
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bins = []
        img = data[bidx]
        for ph in range(p):
            for pw in range(p):
                gy = ph * g // p
                gx = pw * g // p
                ys = y1 + rh * ph / p
                ye = y1 + rh * (ph + 1) / p
                xs = x1 + rw * pw / p
                xe = x1 + rw * (pw + 1) / p
                yy = jnp.arange(H, dtype=jnp.float32)
                xx = jnp.arange(W, dtype=jnp.float32)
                my = ((yy + 1 > ys) & (yy < ye)).astype(jnp.float32)
                mxm = ((xx + 1 > xs) & (xx < xe)).astype(jnp.float32)
                mask = my[:, None] * mxm[None, :]
                area = jnp.maximum(mask.sum(), 1.0)
                chans = img.reshape(output_dim, g * g, H, W)[
                    :, gy * g + gx]
                bins.append((chans * mask).sum(axis=(-1, -2)) / area)
        out = jnp.stack(bins, axis=-1)          # (output_dim, p*p)
        return out.reshape(output_dim, p, p)
    return jax.vmap(one_roi)(rois.astype(jnp.float32))


@register("Proposal", aliases=["_contrib_Proposal", "proposal"],
          differentiable=False)
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
              feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal generation (reference: src/operator/contrib/
    proposal.cc): anchors + deltas → clip → min-size filter → top-N by
    score → NMS → top-post-N rois (B*(N,5) stacked)."""
    B, A2, H, W = cls_prob.shape
    A = A2 // 2
    base = float(feature_stride)
    # anchor set at (0,0): center-form
    anchors = []
    for r in ratios:
        for s in scales:
            size = base * base / float(r)
            w = jnp.sqrt(size) * float(s)
            h = w * float(r)
            anchors.append([-(w - base) / 2, -(h - base) / 2,
                            (w + base) / 2 - 1, (h + base) / 2 - 1])
    anc = jnp.asarray(anchors, jnp.float32)            # (A, 4)
    sx = jnp.arange(W, dtype=jnp.float32) * base
    sy = jnp.arange(H, dtype=jnp.float32) * base
    shift = jnp.stack(jnp.meshgrid(sx, sy, indexing="xy"), axis=-1)
    shift = jnp.concatenate([shift, shift], axis=-1)   # (H, W, 4)
    all_anchors = (anc[None, None] + shift[:, :, None]).reshape(-1, 4)

    def one(scores, deltas, info):
        s = scores[A:].transpose(1, 2, 0).reshape(-1)   # fg scores
        d = deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes = _box_decode(
            d.reshape(1, -1, 4),
            all_anchors.reshape(1, -1, 4), format="corner")[0]
        boxes = jnp.clip(boxes,
                         jnp.zeros((4,)),
                         jnp.stack([info[1] - 1, info[0] - 1,
                                    info[1] - 1, info[0] - 1]))
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        min_size = rpn_min_size * info[2]
        keep = (ws >= min_size) & (hs >= min_size)
        s = jnp.where(keep, s, -1.0)
        k = min(rpn_pre_nms_top_n, s.shape[0])
        top_s, top_i = lax.top_k(s, k)
        top_boxes = boxes[top_i]
        dets = jnp.concatenate([top_s[:, None], top_boxes], axis=1)
        # NMS over ALL pre-nms candidates (topk here would invalidate boxes
        # before suppression even ran), then COMPACT the survivors to the
        # front — _box_nms leaves -1 rows in place — and truncate to post-N.
        kept = _box_nms(dets[None], overlap_thresh=threshold,
                        valid_thresh=0.0, topk=-1,
                        coord_start=1, score_index=0, id_index=-1)[0]
        alive = kept[:, 0] > -1
        order = jnp.argsort(jnp.where(alive, 0, 1), stable=True)
        return kept[order][:rpn_post_nms_top_n]

    outs = jax.vmap(one)(cls_prob, bbox_pred,
                         jnp.broadcast_to(im_info, (B, 3)))
    scores = outs[..., 0:1]
    boxes = outs[..., 1:5]
    batch_idx = jnp.broadcast_to(
        jnp.arange(B, dtype=jnp.float32)[:, None, None],
        (B, boxes.shape[1], 1))
    rois = jnp.concatenate([batch_idx, boxes], axis=-1).reshape(-1, 5)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


@register("MultiProposal", aliases=["_contrib_MultiProposal"],
          differentiable=False)
def _multi_proposal(cls_prob, bbox_pred, im_info, **kw):
    """Batched Proposal (reference: multi_proposal.cc) — same math; the
    batch loop is already vmapped in Proposal."""
    return _proposal(cls_prob, bbox_pred, im_info, **kw)


@register("_contrib_DeformableConvolution",
          aliases=["DeformableConvolution"], differentiable=False)
def _deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                            stride=(1, 1), dilate=(1, 1), pad=(1, 1),
                            num_filter=1, num_group=1,
                            num_deformable_group=1, no_bias=False,
                            workspace=1024, layout=None):
    """Deformable conv v1 (reference: src/operator/contrib/
    deformable_convolution.cc): bilinear-sample the input at
    offset-perturbed taps, then a dense 1x1-style contraction per tap."""
    if num_group != 1:
        raise ValueError("DeformableConvolution: num_group != 1 is not "
                         "supported on the TPU backend yet")
    kh, kw = kernel
    B, C, H, W = data.shape
    Ho = (H + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
    Wo = (W + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1
    # offset: (B, 2*dg*kh*kw, Ho, Wo) — (dy, dx) per tap per group
    off = offset.reshape(B, num_deformable_group, kh * kw, 2, Ho, Wo)
    yy = jnp.arange(Ho, dtype=jnp.float32) * stride[0] - pad[0]
    xx = jnp.arange(Wo, dtype=jnp.float32) * stride[1] - pad[1]
    cg = C // num_deformable_group

    def sample(img, y, x):
        """img (C', H, W); y/x (...): bilinear with zero padding."""
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy = y - y0
        wx = x - x0

        def at(yi, xi):
            inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            v = img[:, yc, xc]
            return jnp.where(inside, v, 0.0)
        return ((1 - wy) * (1 - wx) * at(y0, x0) + (1 - wy) * wx * at(y0, x0 + 1)
                + wy * (1 - wx) * at(y0 + 1, x0) + wy * wx * at(y0 + 1, x0 + 1))

    def one(img, offs):
        cols = []
        for g in range(num_deformable_group):
            part = img[g * cg:(g + 1) * cg].astype(jnp.float32)
            for t in range(kh * kw):
                i, j = t // kw, t % kw
                ty = yy[:, None] + i * dilate[0] + offs[g, t, 0]
                tx = xx[None, :] + j * dilate[1] + offs[g, t, 1]
                cols.append(sample(part, ty, tx))   # (cg, Ho, Wo)
        return jnp.concatenate(cols, axis=0)        # (C*kh*kw grouped)

    cols = jax.vmap(one)(data.astype(jnp.float32), off.astype(jnp.float32))
    # cols: (B, dg*cg*kh*kw, Ho, Wo) ordered [g][tap][c]; weight (O, C/ng, kh, kw)
    cols = cols.reshape(B, num_deformable_group, kh * kw, cg, Ho, Wo)
    cols = cols.transpose(0, 1, 3, 2, 4, 5).reshape(B, C * kh * kw, Ho, Wo)
    wmat = weight.reshape(num_filter, -1)
    out = jnp.einsum("of,bfhw->bohw",
                     wmat.astype(jnp.float32),
                     cols.reshape(B, C * kh * kw, Ho, Wo))
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out.astype(data.dtype)


@register("_contrib_mrcnn_mask_target", aliases=["mrcnn_mask_target"],
          num_outputs=2, differentiable=False)
def _mrcnn_mask_target(rois, gt_masks, matches, cls_targets,
                       num_rois=None, num_classes=1, mask_size=(14, 14)):
    """Mask R-CNN training targets (reference: src/operator/contrib/
    mrcnn_mask_target.cu): crop each roi's matched gt mask to mask_size
    and scatter it into its class slot.  rois (B, R, 4) corner, gt_masks
    (B, M, H, W), matches (B, R) gt index, cls_targets (B, R) class id."""
    ms = tuple(mask_size) if isinstance(mask_size, (tuple, list)) \
        else (int(mask_size), int(mask_size))
    B, R = matches.shape[0], matches.shape[1]
    H, W = gt_masks.shape[2], gt_masks.shape[3]

    def one(rois_b, masks_b, match_b, cls_b):
        def per_roi(roi, mi, ci):
            m = masks_b[mi.astype(jnp.int32)]            # (H, W)
            x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
            ys = y1 + (y2 - y1) * (jnp.arange(ms[0]) + 0.5) / ms[0]
            xs = x1 + (x2 - x1) * (jnp.arange(ms[1]) + 0.5) / ms[1]
            yi = jnp.clip(ys, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xs, 0, W - 1).astype(jnp.int32)
            crop = m[yi][:, xi]                          # nearest sample
            onehot = jax.nn.one_hot(ci.astype(jnp.int32), num_classes,
                                    dtype=crop.dtype)
            return onehot[:, None, None] * crop[None]
        targets = jax.vmap(per_roi)(rois_b, match_b, cls_b)
        # only the MATCHED class channel is supervised (reference weights
        # are one_hot(cls) — broadcasting over classes would train every
        # other channel toward an all-zero mask)
        onehot_w = jax.nn.one_hot(cls_b.astype(jnp.int32), num_classes,
                                  dtype=jnp.float32)
        weights = onehot_w * (cls_b > 0).astype(jnp.float32)[:, None]
        wmask = jnp.broadcast_to(weights[:, :, None, None],
                                 (R, num_classes) + ms)
        return targets, wmask
    t, w = jax.vmap(one)(rois.astype(jnp.float32), gt_masks, matches,
                         cls_targets)
    return t, w


@register("_contrib_ModulatedDeformableConvolution",
          aliases=["ModulatedDeformableConvolution"], differentiable=False)
def _modulated_deformable_convolution(data, offset, mask, weight, bias=None,
                                      kernel=(3, 3), stride=(1, 1),
                                      dilate=(1, 1), pad=(1, 1),
                                      num_filter=1, num_group=1,
                                      num_deformable_group=1, no_bias=False,
                                      workspace=1024, layout=None):
    """Deformable conv v2 (reference: modulated_deformable_convolution.cc):
    v1 sampling plus a learned per-tap modulation scalar in [0, 1] applied
    to the sampled columns BEFORE the contraction (post-hoc output scaling
    would not be equivalent)."""
    kh, kw = kernel
    if num_group != 1:
        raise ValueError("ModulatedDeformableConvolution: num_group != 1 "
                         "is not supported on the TPU backend yet")
    B, C, H, W = data.shape
    Ho = (H + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
    Wo = (W + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1
    off = offset.reshape(B, num_deformable_group, kh * kw, 2, Ho, Wo)
    mod = mask.reshape(B, num_deformable_group, kh * kw, Ho, Wo)
    yy = jnp.arange(Ho, dtype=jnp.float32) * stride[0] - pad[0]
    xx = jnp.arange(Wo, dtype=jnp.float32) * stride[1] - pad[1]
    cg = C // num_deformable_group

    def sample(img, y, x):
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy = y - y0
        wx = x - x0

        def at(yi, xi):
            inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            return jnp.where(inside, img[:, yc, xc], 0.0)
        return ((1 - wy) * (1 - wx) * at(y0, x0)
                + (1 - wy) * wx * at(y0, x0 + 1)
                + wy * (1 - wx) * at(y0 + 1, x0)
                + wy * wx * at(y0 + 1, x0 + 1))

    def one(img, offs, mods):
        cols = []
        for g in range(num_deformable_group):
            part = img[g * cg:(g + 1) * cg].astype(jnp.float32)
            for t in range(kh * kw):
                i, j = t // kw, t % kw
                ty = yy[:, None] + i * dilate[0] + offs[g, t, 0]
                tx = xx[None, :] + j * dilate[1] + offs[g, t, 1]
                cols.append(sample(part, ty, tx) * mods[g, t][None])
        return jnp.concatenate(cols, axis=0)

    cols = jax.vmap(one)(data.astype(jnp.float32), off.astype(jnp.float32),
                         mod.astype(jnp.float32))
    cols = cols.reshape(B, num_deformable_group, kh * kw, cg, Ho, Wo)
    cols = cols.transpose(0, 1, 3, 2, 4, 5).reshape(B, C * kh * kw, Ho, Wo)
    out = jnp.einsum("of,bfhw->bohw",
                     weight.reshape(num_filter, -1).astype(jnp.float32),
                     cols)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out.astype(data.dtype)


@register("_contrib_DeformablePSROIPooling",
          aliases=["DeformablePSROIPooling"], differentiable=False)
def _deformable_psroi_pooling(data, rois, trans, spatial_scale=1.0,
                              output_dim=1, group_size=1, pooled_size=1,
                              part_size=0, sample_per_part=1,
                              trans_std=0.0, no_trans=False):
    """Deformable position-sensitive ROI pooling (reference:
    src/operator/contrib/deformable_psroi_pooling.cc — R-FCN deform heads):
    PSROIPooling with per-bin learned (dx, dy) offsets scaled by trans_std.
    data (B, C, H, W) with C = output_dim*group², rois (R, 5),
    trans (R, 2, part, part): channel 0 = dx, channel 1 = dy per part
    cell (the layout the flattened indexing below consumes)."""
    g = int(group_size)
    p = int(pooled_size)
    part = int(part_size) if part_size else p
    B, C, H, W = data.shape

    def one_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[i] * spatial_scale for i in range(1, 5))
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        img = data[bidx]
        bins = []
        for ph in range(p):
            for pw in range(p):
                if no_trans:
                    dx = dy = 0.0
                else:
                    pi = min(ph * part // p, part - 1)
                    pj = min(pw * part // p, part - 1)
                    dx = tr[0 * part * part + pi * part + pj] * trans_std \
                        * rw
                    dy = tr[1 * part * part + pi * part + pj] * trans_std \
                        * rh
                ys = y1 + rh * ph / p + dy
                ye = y1 + rh * (ph + 1) / p + dy
                xs = x1 + rw * pw / p + dx
                xe = x1 + rw * (pw + 1) / p + dx
                yy = jnp.arange(H, dtype=jnp.float32)
                xx = jnp.arange(W, dtype=jnp.float32)
                my = ((yy + 1 > ys) & (yy < ye)).astype(jnp.float32)
                mxm = ((xx + 1 > xs) & (xx < xe)).astype(jnp.float32)
                mask = my[:, None] * mxm[None, :]
                area = jnp.maximum(mask.sum(), 1.0)
                gy = min(ph * g // p, g - 1)
                gx = min(pw * g // p, g - 1)
                chans = img.reshape(output_dim, g * g, H, W)[:, gy * g + gx]
                bins.append((chans * mask).sum(axis=(-1, -2)) / area)
        return jnp.stack(bins, axis=-1).reshape(output_dim, p, p)
    trans_flat = trans.reshape(trans.shape[0], -1)
    return jax.vmap(one_roi)(rois.astype(jnp.float32),
                             trans_flat.astype(jnp.float32))
