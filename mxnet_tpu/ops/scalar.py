"""Scalar-operand elemwise ops (the reference's _plus_scalar family).

Reference: src/operator/tensor/elemwise_binary_scalar_op_basic.cc,
elemwise_binary_scalar_op_extended.cc, elemwise_binary_scalar_op_logic.cc.
MXNet routes NDArray-op-python-number arithmetic through these; the
``scalar`` attribute is a static param, so under jit it folds into the
compiled program (no host->device transfer per call).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


@register("_plus_scalar", aliases=["plus_scalar"])
def _plus_scalar(data, scalar=0.0):
    return data + jnp.asarray(scalar, data.dtype)


@register("_minus_scalar", aliases=["minus_scalar"])
def _minus_scalar(data, scalar=0.0):
    return data - jnp.asarray(scalar, data.dtype)


@register("_rminus_scalar", aliases=["rminus_scalar"])
def _rminus_scalar(data, scalar=0.0):
    return jnp.asarray(scalar, data.dtype) - data


@register("_mul_scalar", aliases=["mul_scalar"])
def _mul_scalar(data, scalar=1.0):
    return data * jnp.asarray(scalar, data.dtype)


@register("_div_scalar", aliases=["div_scalar"])
def _div_scalar(data, scalar=1.0):
    return data / jnp.asarray(scalar, data.dtype)


@register("_rdiv_scalar", aliases=["rdiv_scalar"])
def _rdiv_scalar(data, scalar=1.0):
    return jnp.asarray(scalar, data.dtype) / data


@register("_mod_scalar", aliases=["mod_scalar"], differentiable=False)
def _mod_scalar(data, scalar=1.0):
    return jnp.mod(data, jnp.asarray(scalar, data.dtype))


@register("_rmod_scalar", aliases=["rmod_scalar"], differentiable=False)
def _rmod_scalar(data, scalar=1.0):
    return jnp.mod(jnp.asarray(scalar, data.dtype), data)


@register("_power_scalar", aliases=["power_scalar"])
def _power_scalar(data, scalar=1.0):
    return jnp.power(data, jnp.asarray(scalar, data.dtype))


@register("_rpower_scalar", aliases=["rpower_scalar"])
def _rpower_scalar(data, scalar=1.0):
    return jnp.power(jnp.asarray(scalar, data.dtype), data)


@register("_maximum_scalar", aliases=["maximum_scalar"])
def _maximum_scalar(data, scalar=0.0):
    return jnp.maximum(data, jnp.asarray(scalar, data.dtype))


@register("_minimum_scalar", aliases=["minimum_scalar"])
def _minimum_scalar(data, scalar=0.0):
    return jnp.minimum(data, jnp.asarray(scalar, data.dtype))


@register("_hypot_scalar", aliases=["hypot_scalar"])
def _hypot_scalar(data, scalar=0.0):
    return jnp.hypot(data, jnp.asarray(scalar, data.dtype))


@register("_equal_scalar", aliases=["equal_scalar"], differentiable=False)
def _equal_scalar(data, scalar=0.0):
    return (data == jnp.asarray(scalar, data.dtype)).astype(data.dtype)


@register("_not_equal_scalar", aliases=["not_equal_scalar"],
          differentiable=False)
def _not_equal_scalar(data, scalar=0.0):
    return (data != jnp.asarray(scalar, data.dtype)).astype(data.dtype)


@register("_greater_scalar", aliases=["greater_scalar"], differentiable=False)
def _greater_scalar(data, scalar=0.0):
    return (data > jnp.asarray(scalar, data.dtype)).astype(data.dtype)


@register("_greater_equal_scalar", aliases=["greater_equal_scalar"],
          differentiable=False)
def _greater_equal_scalar(data, scalar=0.0):
    return (data >= jnp.asarray(scalar, data.dtype)).astype(data.dtype)


@register("_lesser_scalar", aliases=["lesser_scalar"], differentiable=False)
def _lesser_scalar(data, scalar=0.0):
    return (data < jnp.asarray(scalar, data.dtype)).astype(data.dtype)


@register("_lesser_equal_scalar", aliases=["lesser_equal_scalar"],
          differentiable=False)
def _lesser_equal_scalar(data, scalar=0.0):
    return (data <= jnp.asarray(scalar, data.dtype)).astype(data.dtype)


@register("_logical_and_scalar", aliases=["logical_and_scalar"],
          differentiable=False)
def _logical_and_scalar(data, scalar=0.0):
    return jnp.logical_and(data, scalar).astype(data.dtype)


@register("_logical_or_scalar", aliases=["logical_or_scalar"],
          differentiable=False)
def _logical_or_scalar(data, scalar=0.0):
    return jnp.logical_or(data, scalar).astype(data.dtype)


@register("_logical_xor_scalar", aliases=["logical_xor_scalar"],
          differentiable=False)
def _logical_xor_scalar(data, scalar=0.0):
    return jnp.logical_xor(data, scalar).astype(data.dtype)


@register("smooth_l1_scalar", aliases=["_smooth_l1_scalar"])
def _smooth_l1_scalar(data, scalar=1.0):
    # reference smooth_l1 with sigma passed as the scalar operand
    s2 = jnp.asarray(scalar, data.dtype) ** 2
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * data * data, a - 0.5 / s2)
