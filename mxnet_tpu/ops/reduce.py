"""Reductions, argmin/max, sort/topk, norms.

Reference: src/operator/tensor/broadcast_reduce_op.h (ReduceAxesCompute),
src/operator/tensor/ordering_op.cc (topk/sort/argsort).

MXNET_SAFE_ACCUMULATION: the reference accumulates fp16 reductions in fp32
when set; XLA does the same for bf16 when we pass an explicit accumulator
dtype — handled by promoting below.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

_ACC = {jnp.bfloat16: jnp.float32, jnp.float16: jnp.float32}


def _acc_reduce(fn, x, axis, keepdims, exclude=False):
    if exclude and axis is not None:
        ax = (axis,) if isinstance(axis, int) else tuple(axis)
        axis = tuple(i for i in range(x.ndim) if i not in ax)
    out = fn(x, axis=axis, keepdims=keepdims,
             dtype=_ACC.get(x.dtype.type)) if fn in (jnp.sum, jnp.prod, jnp.mean) \
        else fn(x, axis=axis, keepdims=keepdims)
    return out.astype(x.dtype)


@register("sum", aliases=["sum_axis"])
def _sum(x, axis=None, keepdims=False, exclude=False):
    return _acc_reduce(jnp.sum, x, axis, keepdims, exclude)


@register("mean")
def _mean(x, axis=None, keepdims=False, exclude=False):
    return _acc_reduce(jnp.mean, x, axis, keepdims, exclude)


@register("prod")
def _prod(x, axis=None, keepdims=False, exclude=False):
    return _acc_reduce(jnp.prod, x, axis, keepdims, exclude)


@register("nansum")
def _nansum(x, axis=None, keepdims=False, exclude=False):
    return _acc_reduce(jnp.nansum, x, axis, keepdims, exclude)


@register("nanprod")
def _nanprod(x, axis=None, keepdims=False, exclude=False):
    return _acc_reduce(jnp.nanprod, x, axis, keepdims, exclude)


@register("max", aliases=["max_axis"])
def _max(x, axis=None, keepdims=False, exclude=False):
    return _acc_reduce(jnp.max, x, axis, keepdims, exclude)


@register("min", aliases=["min_axis"])
def _min(x, axis=None, keepdims=False, exclude=False):
    return _acc_reduce(jnp.min, x, axis, keepdims, exclude)


@register("norm")
def _norm(x, ord=2, axis=None, keepdims=False):
    xf = x.astype(_ACC.get(x.dtype.type, x.dtype))
    if ord == 1:
        out = jnp.sum(jnp.abs(xf), axis=axis, keepdims=keepdims)
    else:
        out = jnp.sqrt(jnp.sum(jnp.square(xf), axis=axis, keepdims=keepdims))
    return out.astype(x.dtype)


@register("L2Normalization")
def _l2norm(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / denom


@register("argmax", differentiable=False)
def _argmax(x, axis=None, keepdims=False):
    out = jnp.argmax(x, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)   # MXNet argmax returns float


@register("argmin", differentiable=False)
def _argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def _argmax_channel(x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register("sort", differentiable=False)
def _sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", differentiable=False)
def _argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    d = jnp.bfloat16 if dtype == "bfloat16" else dtype
    return out.astype(d)


@register("topk", differentiable=False)
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    # XLA top_k works on the last axis; move axis there.
    if axis is None:
        x = x.reshape(-1)
        axis = -1
    ax = axis % x.ndim
    xm = jnp.moveaxis(x, ax, -1)
    src = -xm if is_ascend else xm
    vals, idx = jax_topk(src, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax)
    d = jnp.bfloat16 if dtype == "bfloat16" else dtype
    if ret_typ == "indices":
        return idx.astype(d)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idx.astype(d))
    if ret_typ == "mask":
        onehot = jnp.sum(jnp.eye(xm.shape[-1], dtype=x.dtype)[idx], axis=-2)
        return jnp.moveaxis(onehot, -1, ax)
    raise ValueError("unknown ret_typ %r" % ret_typ)


def jax_topk(x, k):
    from jax import lax
    return lax.top_k(x, k)


@register("cumsum")
def _cumsum(x, axis=None, dtype=None):
    d = jnp.bfloat16 if dtype == "bfloat16" else dtype
    return jnp.cumsum(x, axis=axis, dtype=d)


@register("cumprod")
def _cumprod(x, axis=None, dtype=None):
    return jnp.cumprod(x, axis=axis, dtype=dtype)
