"""Control-flow ops: foreach / while_loop / cond.

Reference: src/operator/control_flow.cc (_foreach, _while_loop, _cond),
python/mxnet/ndarray/contrib.py (foreach, while_loop, cond).

TPU-native design: these lower DIRECTLY to `lax.scan` / `lax.while_loop` /
`lax.cond` (SURVEY.md §2.1 control-flow row: "near-free").  The user body
is a Python callable over NDArrays; inside the combinator the NDArrays wrap
jax tracers (the same mechanism HybridBlock's CachedOp uses), so one XLA
program is built for the whole loop — the reference needed subgraph ops +
LoopState for this; XLA's native loop constructs replace all of it.

Autograd: when the tape is recording, the whole combinator is recorded as
ONE tape node whose VJP is `jax.vjp` over the scanned function —
gradients flow through loops exactly as the reference's backward-through-
subgraph did.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["foreach", "while_loop", "cond"]


def _flatten(obj, out: List[Any]):
    from ..ndarray.ndarray import NDArray
    if isinstance(obj, NDArray):
        out.append(obj)
        return None
    if isinstance(obj, (list, tuple)):
        return type(obj)(_flatten(x, out) for x in obj)
    out.append(obj)
    return None


def _tree_to_jax(obj):
    from ..ndarray.ndarray import NDArray
    if isinstance(obj, NDArray):
        return obj._jax
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_jax(x) for x in obj)
    return jnp.asarray(obj)


def _tree_to_nd(obj, ctx):
    from ..ndarray.ndarray import NDArray
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_nd(x, ctx) for x in obj)
    return NDArray(obj, ctx=ctx)


def _first_ctx(*objs):
    from ..ndarray.ndarray import NDArray
    from ..device import current_context
    for obj in objs:
        leaves = jax.tree_util.tree_leaves(
            obj, is_leaf=lambda x: isinstance(x, NDArray))
        for leaf in leaves:
            if isinstance(leaf, NDArray):
                return leaf.context
    return current_context()


def _maybe_record(pure_fn, inputs_tree, out_tree_def):
    """Run pure_fn over the jax leaves of inputs; if the tape is recording,
    register one custom node with jax.vjp's cotangent closure."""
    from .. import autograd
    from ..ndarray.ndarray import NDArray
    ctx = _first_ctx(inputs_tree)
    jax_in = _tree_to_jax(inputs_tree)
    if autograd.is_recording():
        nd_leaves: List[NDArray] = []
        _flatten(inputs_tree, nd_leaves)
        nd_leaves = [x for x in nd_leaves if isinstance(x, NDArray)]

        flat_in = [x._jax for x in nd_leaves]

        def flat_fn(*leaves):
            it = iter(leaves)

            def rebuild(obj):
                if isinstance(obj, NDArray):
                    return next(it)
                if isinstance(obj, (list, tuple)):
                    return type(obj)(rebuild(x) for x in obj)
                return obj
            rebuilt = rebuild(inputs_tree)
            outs = pure_fn(_tree_to_jax(rebuilt))
            return tuple(jax.tree_util.tree_leaves(outs))

        out_leaves, vjp_fn = jax.vjp(flat_fn, *flat_in)

        def tape_vjp(cotangents):
            return vjp_fn(tuple(cotangents))

        wrapped = autograd.record_custom(tape_vjp, nd_leaves,
                                         tuple(out_leaves), ctx,
                                         name="control_flow")
        return out_tree_def(list(wrapped), ctx)
    outs = pure_fn(jax_in)
    leaves = list(jax.tree_util.tree_leaves(outs))
    return out_tree_def([NDArray(o, ctx=ctx) for o in leaves], ctx)


def foreach(body: Callable, data, init_states):
    """Scan `body(x_t, states) -> (out_t, new_states)` over axis 0 of
    `data` (reference: contrib.foreach → _foreach op; here = lax.scan)."""
    from ..ndarray.ndarray import NDArray
    single_data = isinstance(data, NDArray)
    single_state = isinstance(init_states, NDArray)
    datas = [data] if single_data else list(data)
    states0 = [init_states] if single_state else list(init_states)
    ctx = _first_ctx(datas, states0)

    out_struct = {}

    def pure(tree):
        d_vals, s_vals = tree

        def step(carry, xs):
            x_nds = [NDArray(x, ctx=ctx) for x in xs]
            c_nds = [NDArray(c, ctx=ctx) for c in carry]
            out, new_states = body(x_nds[0] if single_data else x_nds,
                                   c_nds[0] if single_state else c_nds)
            out_l: List[NDArray] = []
            out_struct["tmpl"] = _flatten(out, out_l)
            out_struct["n_out"] = len(out_l)
            ns_l: List[NDArray] = []
            out_struct["s_tmpl"] = _flatten(new_states, ns_l)
            return (tuple(o._jax for o in ns_l),
                    tuple(o._jax for o in out_l))

        carry, ys = lax.scan(step, tuple(s_vals), tuple(d_vals))
        return tuple(ys) + tuple(carry)

    def rebuild(leaves: List[NDArray], ctx):
        n = out_struct["n_out"]
        outs, states = leaves[:n], leaves[n:]

        def fill(tmpl, vals, pos):
            if tmpl is None:
                v = vals[pos[0]]
                pos[0] += 1
                return v
            if isinstance(tmpl, (list, tuple)):
                return type(tmpl)(fill(t, vals, pos) for t in tmpl)
            return tmpl
        out = fill(out_struct["tmpl"], outs, [0])
        st = fill(out_struct["s_tmpl"], states, [0])
        return out, st

    return _maybe_record(pure, (datas, states0), rebuild)


def while_loop(cond_fn: Callable, body: Callable, loop_vars,
               max_iterations: int = None):
    """Reference: contrib.while_loop.  TPU-native: bounded `lax.scan` with
    an active-mask (XLA needs static trip count for differentiability; the
    reference's _while_loop also required max_iterations).  Returns
    (outputs=None, final_loop_vars) — per-step output stacking is only
    supported through `foreach`."""
    from ..ndarray.ndarray import NDArray
    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations (static bound "
                         "for XLA; the reference required it too)")
    single = isinstance(loop_vars, NDArray)
    lv = [loop_vars] if single else list(loop_vars)
    ctx = _first_ctx(lv)

    def pure(tree):
        (vals,) = tree

        def step(carry, _):
            vals, active = carry
            v_nds = [NDArray(v, ctx=ctx) for v in vals]
            arg = v_nds[0] if single else v_nds
            c = cond_fn(arg)
            c_val = c._jax if isinstance(c, NDArray) else jnp.asarray(c)
            active_now = jnp.logical_and(active, c_val.reshape(()))
            out = body(arg)
            out = [out] if single else list(out)
            new_vals = tuple(
                jnp.where(active_now, o._jax.astype(v.dtype), v)
                for o, v in zip(out, vals))
            return (new_vals, active_now), None

        (final, _), _ = lax.scan(step, (tuple(vals), jnp.asarray(True)),
                                 None, length=max_iterations)
        return final

    def rebuild(leaves, ctx):
        return leaves[0] if single else list(leaves)

    return None, _maybe_record(pure, ([v for v in lv],), rebuild)


def cond(pred: Callable, then_func: Callable, else_func: Callable,
         inputs):
    """Reference: contrib.cond → lax.cond. `pred(inputs)` must return a
    scalar; both branches must produce identically-shaped outputs."""
    from ..ndarray.ndarray import NDArray
    single = isinstance(inputs, NDArray)
    ins = [inputs] if single else list(inputs)
    ctx = _first_ctx(ins)
    struct = {}

    def pure(tree):
        (vals,) = tree
        v_nds = [NDArray(v, ctx=ctx) for v in vals]
        arg = v_nds[0] if single else v_nds
        p = pred(arg)
        p_val = (p._jax if isinstance(p, NDArray) else jnp.asarray(p))

        def run(branch):
            def f(vals):
                v_nds = [NDArray(v, ctx=ctx) for v in vals]
                out = branch(v_nds[0] if single else v_nds)
                out_l: List[NDArray] = []
                struct["tmpl"] = _flatten(out, out_l)
                return tuple(o._jax for o in out_l)
            return f

        return lax.cond(p_val.reshape(()).astype(bool),
                        run(then_func), run(else_func), tuple(vals))

    def rebuild(leaves, ctx):
        def fill(tmpl, vals, pos):
            if tmpl is None:
                v = vals[pos[0]]
                pos[0] += 1
                return v
            if isinstance(tmpl, (list, tuple)):
                return type(tmpl)(fill(t, vals, pos) for t in tmpl)
            return tmpl
        return fill(struct["tmpl"], leaves, [0])

    return _maybe_record(pure, (ins,), rebuild)
